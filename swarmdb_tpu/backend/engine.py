"""Continuous-batching generation engine.

The TPU serving core the north star demands (SURVEY §7 step 4): a
fixed-shape decode loop under ``jax.jit`` with slot management —

- ``max_batch`` slots; each slot holds one in-flight sequence with its own
  absolute position, sampling params, and PRNG stream.
- ONE compiled decode step serves every population of slots: inactive slots
  run masked garbage that is ignored host-side (shapes never change, so XLA
  never recompiles).
- Decode runs in CHUNKS of ``decode_chunk`` steps under one ``lax.scan``
  per host round-trip: the sampled token feeds the next step entirely
  on-device, and the host fetches a [K+1, B] token block with ONE sync.
  This amortizes host<->device latency — on this image the TPU tunnel costs
  ~80 ms per synchronous fetch, so per-token syncs would cap the whole
  engine at ~12 steps/s regardless of batch. Slots that finish (EOS /
  max_new_tokens) mid-chunk compute garbage for the remainder; the host
  discards it. Their KV lanes are fully overwritten at next admission, so
  the garbage is never read.
- Prefill runs per-sequence at bucketed lengths (powers of two) to bound
  the number of compiled variants, then the prefix cache is inserted into
  the slot's rows of the batch KV cache. Single-shard PAGED engines
  instead pack each admission wave into ONE ragged no-padding token
  stream (``_prefill_ragged_waves``: per-row (start, len, prefix_len)
  descriptors, prefix KV read in place from the page pool, widths off a
  power-of-two ladder — ``SWARMDB_RAGGED_PREFILL=0`` restores the
  bucketed waves). Prefill never syncs: its sampled first token is
  scattered into the on-device ``last_tokens`` vector and reaches the
  host as row 0 of the next chunk's token block.
- Admission is priority-ordered (MessagePriority: CRITICAL first — the
  reference stores priorities but never uses them, SURVEY §2.2).
- Tokens stream to per-request callbacks as they are sampled; the HTTP
  layer bridges these to SSE (asyncio) queues.

The engine is model-agnostic: it takes a ``forward(params, tokens,
positions, cache)`` callable (Llama or Mixtral) plus cache constructors.
"""

from __future__ import annotations

import concurrent.futures
import functools
import heapq
import itertools
import logging
import os
import threading
import time
import uuid
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from ..obs import TRACER, FlightRecorder
from ..obs.metrics import (HIST_DECODE_CHUNK, HIST_QUEUE_WAIT, HIST_TTFT)
from ..obs.profiler import NullLane, profiler as kernel_profiler
from ..utils.metrics import MetricsRegistry
from ..utils.sync import make_condition
from .sampling import (SamplingParams, make_slot_keys,
                       sample_tokens, token_logprob)

logger = logging.getLogger("swarmdb_tpu.engine")

#: Finish reasons a client (or the lane supervisor) may transparently
#: retry: the request itself was fine — the ENGINE lost it (loop death,
#: lane quarantine, transient dispatch failure) or deliberately returned
#: it (pool-pressure shedding, a stale rolling-resume epoch). Mirrors the
#: ``BrokerError.retryable`` contract from the HA control plane: the
#: failure names itself retryable instead of every caller keeping a
#: private list. Non-retryable reasons ("eos", "length", "cancelled",
#: "deadline") are final.
RETRYABLE_REASONS = frozenset({
    "engine_error", "engine_restart", "lane_quarantined", "shed",
    "stale_resume",
})


def is_retryable_reason(reason: str) -> bool:
    """True when a finish reason is safe to requeue (see
    :data:`RETRYABLE_REASONS`)."""
    return reason in RETRYABLE_REASONS


# ---- swarmprof variant naming (obs/profiler.py, ISSUE 15) ----------------
# One compiled program = one profiler key. The decode/resident families
# have a single shape each; prefill families key on the shapes that pick
# the compiled variant (rows x token bucket, + the prefix-gather width
# where it is a compile axis). The SAME helper names warmup-harvest
# entries and runtime dispatches, so cost-model facts and device-time
# accounting join by construction.

PROF_DECODE_KEYS = ("decode.full", "decode.fast", "decode.greedy")
PROF_RESIDENT_KEYS = ("resident.full", "resident.fast", "resident.greedy")


def prof_key(family: str, tok_shape, ppb: Optional[int] = None) -> str:
    """Profiler variant key for a prefill family + its shape axes."""
    if len(tok_shape) == 1:
        return f"{family}[w{tok_shape[0]}]"
    r, b = tok_shape
    if ppb is None:
        return f"{family}[r{r}xb{b}]"
    return f"{family}[r{r}xb{b}xp{ppb}]"


@dataclass
class GenRequest:
    prompt: List[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    priority: int = 1
    request_id: str = field(default_factory=lambda: str(uuid.uuid4()))
    # on_token(request_id, token_id) fires per sampled token (engine thread!)
    on_token: Optional[Callable[[str, int], None]] = None
    # on_done(request_id, token_ids, finish_reason)
    on_done: Optional[Callable[[str, List[int], str], None]] = None
    submitted_at: float = field(default_factory=time.time)
    metadata: Dict[str, Any] = field(default_factory=dict)
    # ---- rolling-KV conversation continuation (paged engines only) ----
    # resume_pages: page ids already holding this conversation's KV (the
    # CALLER keeps custody — the engine only references them; see
    # ServingService's rolling registry). resume_len: tokens already in
    # those pages; ``prompt`` then carries ONLY the new suffix tokens and
    # decode continues at resume_len + len(prompt).
    resume_pages: Optional[List[int]] = None
    resume_len: int = 0
    # keep_pages: at retirement, transfer the slot's fresh pages out of
    # engine custody and fire on_pages(request_id, pages, written_len,
    # tail_tokens) instead of freeing — the caller may resume from them
    # next turn. tail_tokens are emitted tokens whose K/V is not yet
    # written (host-confirmed extent is chunk-granular); prepend them to
    # the next resume's prompt.
    keep_pages: bool = False
    on_pages: Optional[Callable[[str, List[int], int, List[int]],
                                None]] = None
    # resume_epoch: the allocator pool generation the resume_pages were
    # handed out in (Engine.pool_epoch() at plan time). submit() AND
    # admission re-validate it: a pool reset between plan and admission
    # reclaims every page, so resuming stale ids would alias another
    # slot's pages — cross-conversation KV corruption (ADVICE r4 #2).
    resume_epoch: Optional[int] = None
    # promote_payload: warm-tier promotion (ISSUE 19) — the host-RAM
    # raw page payload ((k, v) pool_gather_pages outputs) that must be
    # bulk-inserted into resume_pages BEFORE the resume prefill reads
    # them. resume_pages were freshly RESERVED by the tier manager;
    # admission performs the H2D insert on the engine thread (the pools
    # are donated by engine jits — no other thread may touch them) and
    # clears this field. None for ordinary (hot) resumes.
    promote_payload: Optional[Any] = None
    # shard_hint: DP-sharded paged pools only — admission prefers a free
    # slot on this shard (mod n_shards). Prefix-cache pages are only
    # usable by same-shard slots, so routing a conversation's turns to
    # one shard keeps its cached prefix hittable; without the hint the
    # load-spreading rotation would scatter turns (and their
    # registrations) across shards. Advisory: any free slot still admits.
    shard_hint: Optional[int] = None
    # ---- fault-tolerant serving (ISSUE 9) -----------------------------
    # deadline: absolute wall-clock time past which this request must not
    # be served. The engine fails expired QUEUED requests with reason
    # "deadline" during admission (never a half-served stream); the lane
    # supervisor enforces it end to end and refuses retries that cannot
    # fit before it. None = no deadline.
    deadline: Optional[float] = None
    # retries_left: how many times a RETRYABLE failure (see
    # RETRYABLE_REASONS) may transparently requeue this request before
    # the failure surfaces. Consumed by the supervisor, not the engine.
    retries_left: int = 0


@dataclass
class _Slot:
    active: bool = False
    request: Optional[GenRequest] = None
    position: int = 0           # next absolute position to write
    generated: List[int] = field(default_factory=list)
    logprobs: List[float] = field(default_factory=list)  # parallel to generated
    pending_first: bool = False  # prefill token not yet surfaced to host
    cancelled: bool = False      # retire at the next processed block
    first_token_at: Optional[float] = None
    admitted_at: Optional[float] = None  # prefill start (flight timeline)
    # engine-local host-sync count stamped at admission: retirement
    # records how many sanctioned syncs this request's lifetime spanned
    # (flight request timelines -> the host_syncs-per-request contract)
    admit_syncs: int = 0
    # device-side next write position: advances by K at each DISPATCH
    # (pipelined chunks are issued before the previous block is read);
    # ``position`` stays the host-confirmed value, advanced at processing
    dispatched_position: int = 0


@dataclass
class PagedKV:
    """Block-paged KV mode wiring (VERDICT r1 missing #2 -> fixed).

    The engine's main cache becomes a shared page pool + page table
    (ops/paged_kv.py): HBM ∝ num_pages*page_size instead of
    max_batch*max_seq. Prefill still runs on dense bucket-sized temp caches
    (`Engine.forward_fn`); ``decode_forward`` is the paged-cache model
    forward (e.g. ``llama.forward_paged``) and ``init_pool`` builds the
    {"k","v","page_table"} cache dict. Admission allocates pages via the
    host-side allocator and stalls (keeps requests queued) when the pool
    cannot cover a request's worst-case footprint.
    """

    decode_forward: Callable    # (params, tokens, positions, cache) -> ...
    init_pool: Callable         # () -> {"k", "v", "page_table"}
    page_size: int
    num_pages: int
    allocator: Any              # ops.paged_kv.PageAllocator
    # DP-sharded pools only: shard_map'd collective-free PLAIN prefill
    # (parallel/serving.build_sharded_paged) over waves packed into
    # per-shard row blocks (Engine._packed_geometry sizes the blocks).
    # None = the generic GSPMD prefill (single-chip, or prefix waves).
    prefill_packed: Optional[Callable] = None
    # Single-shard pools only (lane engines included): packed RAGGED
    # prefill (ISSUE 11) — (params, tokens[W], tok_row[W], tok_pos[W],
    # row_tables[R, maxp], starts[R], lens[R], prefix_lens[R], k_pool,
    # v_pool) -> ([R, V] last-token logits, sfx_k, sfx_v [L, W, Hkv, D]).
    # One no-padding token stream per admission wave; prefix KV (cache
    # hits and earlier chunks of a split prompt) is read straight from
    # the page pool. None = the row-bucketed dense-bucket prefill.
    prefill_ragged: Optional[Callable] = None


class Engine:
    """Slot-based continuous batching over a jitted decode step."""

    # Cross-thread / device-state contracts, machine-checked by swarmlint
    # (python -m swarmdb_tpu.analysis — see analysis/ and README):
    # swarmlint: guarded-by[self._cv]: _queue, _admitting, _cancel_pending, _stop
    # swarmlint: device-state: _last_tokens, _last_lps, cache, base_keys

    def __init__(
        self,
        forward_fn: Callable,            # forward(params, tokens, positions, cache)
        init_cache_fn: Callable,         # (batch, max_seq) -> cache pytree
        params: Any,
        *,
        max_batch: int = 8,
        max_seq: int = 1024,
        eos_id: int = 2,
        pad_id: int = 0,
        seed: int = 0,
        prefill_buckets: Optional[Sequence[int]] = None,
        metrics: Optional[MetricsRegistry] = None,
        donate_cache: bool = True,
        decode_chunk: int = 8,
        paged: Optional[PagedKV] = None,
        prefill_batch: Optional[int] = None,
        chunked_fns: Optional[Tuple[Callable, Callable, Callable]] = None,
        pipeline_depth: int = 2,
        prefix_fns: Optional[Tuple[Callable, Callable]] = None,
        prefix_pages: int = 0,
        prefix_page_size: int = 16,
        forward_last_fn: Optional[Callable] = None,
        flight_dir: Optional[str] = None,
        aging_s: Optional[float] = None,
    ) -> None:
        # forward_last_fn(params, tokens, positions, cache, last_pos) ->
        # ([B, V] logits at each row's last_pos, cache): prefill only ever
        # samples the LAST position, so computing the LM head there alone
        # (same math — head columns are position-independent) skips the
        # full-bucket fp32 logits (0.5 GB per wave at Bp=16, T=255, V=32k)
        # and ~7% of prefill FLOPs. Absent -> full forward + gather.
        self.forward_fn = forward_fn
        self._forward_last = forward_last_fn
        self.params = params
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.metrics = metrics or MetricsRegistry()
        # latency sinks bound ONCE: hot-marked paths must never pay a
        # defaultdict lookup — or allocate a fresh histogram — per
        # observation (swarmlint SWL503)
        self._lat_queue_wait = self.metrics.latencies["queue_wait_s"]
        self._lat_prefill = self.metrics.latencies["prefill_s"]
        self._lat_first_token = self.metrics.latencies["first_token_s"]
        # observability: request spans ride the process-global tracer;
        # the flight recorder (last-N engine steps + last-M request
        # timelines) is per-engine and auto-dumped on restart/error —
        # see swarmdb_tpu/obs/ and GET /admin/flight
        self.tracer = TRACER
        self.flight = FlightRecorder()
        self._flight_dir = flight_dir
        self._flight_last_had_work = False
        # swarmprof lane handle (obs/profiler.py): per-variant device-
        # time attribution + this lane's duty cycle. SWARMDB_PROFILE=0
        # hands back the shared NullLane — dispatch sites then pay one
        # attribute read (enabled == False), nothing else (type identity
        # pinned by test). ShardLaneGroup relabels lanes "lane<i>".
        self._prof = kernel_profiler().lane()
        self._prof_resident_key = PROF_RESIDENT_KEYS[0]
        # swarmfleet role (ISSUE 20): None = colocated (default, full
        # warmup), "prefill" = admission/ragged-prefill waves only,
        # "decode" = resident decode + rolling-resume only. The role
        # restricts WARMUP (compile count + VMEM), not capability — an
        # off-role request still runs, it just cold-compiles.
        self._role: Optional[str] = None
        # ShardLaneGroup sets this to the lane index: lanes share ONE
        # flight recorder, and step records carry which lane wrote them
        self.flight_shard: Optional[int] = None
        # online SLO sentinel (obs/sentinel.py): the runtime owns it (one
        # per process, shared metrics registry); ServingService points it
        # here so the engine loop drives window closes and breach dumps
        # read THIS engine's flight rings. None = unmonitored engine.
        self.sentinel = None
        # priority aging (anti-starvation, see _age_queue): seconds a
        # queued request waits per effective-priority-class bump; <= 0
        # disables (strict priority, LOW can starve under saturation)
        if aging_s is None:
            try:
                aging_s = float(os.environ.get("SWARMDB_AGING_S", "5.0"))
            except ValueError:
                logger.warning("SWARMDB_AGING_S=%r is not a float; "
                               "using 5.0",
                               os.environ.get("SWARMDB_AGING_S"))
                aging_s = 5.0
        self._aging_s = aging_s

        self.decode_chunk = max(1, int(decode_chunk))
        # How many decode chunks may be in flight before the host reads
        # the oldest block. Depth 2 issues chunk N+1 BEFORE device_get of
        # chunk N, hiding the host<->device round-trip (~69 ms on this
        # image's tunneled TPU — a quarter of a B=128 chunk) behind the
        # next chunk's compute. Token math is unchanged: dispatch order
        # and device state evolution are identical; only when the host
        # READS each block moves. Slots that retire mid-flight compute
        # one extra chunk of garbage their snapshot tells the host to
        # discard. Depth 1 = the round-3 lockstep behavior.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.paged = paged
        # runtime page sanitizer (SWARMDB_PAGECHECK=1, obs/pagecheck.py):
        # non-None only when the allocator came from the checked factory
        # — one attr read on the flag-off path, nothing else
        self._pagecheck = (getattr(paged.allocator, "pagecheck", None)
                           if paged else None)
        if self._pagecheck is not None:
            from ..obs.pagecheck import registry as _pagecheck_registry

            _pagecheck_registry().attach_flight(self.flight)
        # interpreter-mode kernel sanitizer (SWARMDB_KERNCHECK=1,
        # obs/kerncheck.py): same one-env-read gate; attaching the flight
        # recorder arms violation instants + the atexit crash dump
        from ..obs.kerncheck import enabled as _kerncheck_enabled

        self._kerncheck = _kerncheck_enabled()
        if self._kerncheck:
            from ..obs.kerncheck import registry as _kerncheck_registry

            _kerncheck_registry().attach_flight(self.flight)
        # main decode cache: paged pool or dense slot buffer; prefill always
        # uses dense bucket-sized temp caches from init_cache_fn
        self.cache = paged.init_pool() if paged else init_cache_fn(max_batch, max_seq)
        if paged is not None:
            # swarmmem (ISSUE 17): KV bytes per pool page — prices the
            # warm-tier model's re-admission device_put
            from ..obs.memprof import memprof as _memprof
            from ..ops.paged_kv import pool_page_bytes

            try:
                # pool_page_bytes folds the int8 QuantPool's scale planes
                # into the per-page price (plain arrays: nbytes // pages)
                _memprof().set_page_bytes(
                    pool_page_bytes(self.cache["k"])
                    + pool_page_bytes(self.cache["v"]))
            except Exception:  # cache layouts without nbytes (stubs)
                pass
        self._decode_forward = paged.decode_forward if paged else forward_fn
        self._prefill_cache_fn = init_cache_fn
        self._seed = seed
        self.base_keys = make_slot_keys(seed, max_batch)
        # host copy for admission-time row gathers: indexing the device
        # array from the host is an eager dispatch per admission (and on
        # the tunneled TPU of this image every eager round-trip is ~ms);
        # numpy fancy-indexing is free and the result rides the jit call
        # WRITABLE host copy (np.asarray of a device array is read-only):
        # per-request seeds rewrite rows in place
        self._base_keys_np = np.array(self.base_keys)
        # pristine per-slot keys: a request with an explicit seed rewrites
        # its slot's row for its lifetime; the next occupant without one
        # restores the default (reproducible replays either way — the
        # per-step key is fold_in(row, absolute position))
        self._default_keys_np = self._base_keys_np.copy()
        self.slots = [_Slot() for _ in range(max_batch)]
        # device-resident fed-token vector: slot i's next input token lives
        # here between chunks so decode->decode and prefill->decode handoffs
        # never touch the host
        self._last_tokens = jnp.zeros((max_batch,), jnp.int32)
        # raw-model logprob of each slot's fed token (same lifecycle)
        self._last_lps = jnp.zeros((max_batch,), jnp.float32)

        # ONE long-context policy flag, read by the bucket ladder here and
        # both prefix-PP width sites below — retune the threshold in one
        # place only
        self._long_context = max_seq >= 512
        if prefill_buckets is None:
            if self._long_context:
                # long-context: x4 bucket growth. Every compiled variant
                # costs 30-90 s on this image's tunneled XLA service and
                # warmup compiles |buckets| x (1 + |PP widths|) prefill
                # variants — at S=1024 the x2 ladder put ~31 compiles in
                # warmup and blew the bench's 1500 s watchdog. Padding
                # waste from the coarser ladder is bounded by prefill
                # being batch-fused (padding rows ride along) and by the
                # prefix cache absorbing most long-prompt re-prefill.
                ladder = (64, 256, 1024, 4096)
            else:
                ladder = (16, 32, 64, 128, 256)
            prefill_buckets = [b for b in ladder if b <= max_seq]
        prefill_buckets = sorted(prefill_buckets)
        # the largest bucket must hold the longest admissible prompt
        # (max_seq - 1). Append max_seq itself — not max_seq - 1 — so the
        # top (hottest) bucket stays tile/page aligned when max_seq is
        # a power of two or page multiple
        if not prefill_buckets or prefill_buckets[-1] < max_seq - 1:
            prefill_buckets.append(max_seq)
        self.prefill_buckets = prefill_buckets

        # host-side per-slot sampling params. These are handed to the jitted
        # calls as RAW numpy arrays: on this image an explicit
        # jnp.asarray(host) blocks ~400 ms on the TPU tunnel, while the same
        # transfer folded into a jit call's argument path is ~0.1 ms — so
        # the engine never calls jnp.asarray/device_put on the hot path.
        self._temp = np.zeros(max_batch, np.float32)
        self._topk = np.zeros(max_batch, np.int32)
        self._topp = np.ones(max_batch, np.float32)

        # ---- lane supervision signal (backend/supervisor.py) -------------
        # Per-step liveness beat: a plain monotonic float slot written by
        # the engine/emission threads and read by the supervisor — the
        # same single-writer-stamp discipline as the HA failure detector
        # (ha/detector.py). A wedged device dispatch stops the loop from
        # iterating, so the beat goes stale while the thread stays alive:
        # exactly the two-signal split the supervisor's state machine
        # (ALIVE -> SUSPECT -> QUARANTINED) distinguishes.
        self._beat_mono = time.monotonic()
        # True while the loop is inside an engine step (admission /
        # dispatch / block processing). A first-traffic XLA compile can
        # legitimately stall a step for tens of seconds with no beats —
        # the supervisor grants in-step stalls a compile grace window
        # (SWARMDB_LANE_DISPATCH_GRACE_S) before quarantining, while a
        # stall OUTSIDE a step (the chaos wedge seam, a stuck lock) gets
        # none. Single-writer bool slot, loop thread only.
        self._in_step = False
        # Fault-injection seam (backend/chaos.py): called once per engine
        # loop iteration, on the engine thread, BEFORE admission. A kill
        # fault raises LaneKilled (a BaseException, so the loop's error
        # recovery cannot swallow it and the thread dies for real); wedge
        # and slow faults block/sleep here, starving the beat. None in
        # production.
        self.chaos_step: Optional[Callable[["Engine"], None]] = None

        # ---- pool-watermark backpressure (paged engines) ------------------
        # Page-pool exhaustion used to block admission indefinitely with
        # no signal. Watermarks over NON-RECLAIMABLE pool utilization
        # (free + evictable prefix-cache pages count as headroom):
        # admission pauses at the high watermark and resumes at the low
        # one (hysteresis — no admit/fail thrash at the boundary), and
        # past the hard SHED watermark the lowest-priority queued work is
        # returned with retryable reason "shed" so higher-priority work
        # drains first. SWARMDB_POOL_HIGH >= 1 disables.
        def _env_frac(name: str, default: float) -> float:
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                logger.warning("%s=%r is not a float; using %g", name,
                               os.environ.get(name), default)
                return default

        self._bp_high = _env_frac("SWARMDB_POOL_HIGH", 0.92)
        self._bp_low = min(_env_frac("SWARMDB_POOL_LOW", 0.80),
                           self._bp_high)
        self._bp_shed = max(_env_frac("SWARMDB_POOL_SHED", 0.98),
                            self._bp_high)
        self._bp_paused = False
        # tiered-KV demote watermark (ISSUE 19): BELOW the pause
        # watermark — the gate starts signalling the tier manager to
        # spill cold conversations to host RAM before admission ever
        # has to pause, with the same hysteresis band (active until
        # util falls back to the low watermark). SWARMDB_TIER_DEMOTE
        # >= 1 disables the early signal (demote_now still fires on
        # hard allocation failure via on_pool_pressure).
        _d = _env_frac("SWARMDB_TIER_DEMOTE", 0.85)
        self._bp_demote = (_d if _d >= 1.0
                           else max(self._bp_low, min(_d, self._bp_high)))
        self._tier_demoting = False

        self._queue: List[Tuple[int, float, int, GenRequest]] = []  # heap
        # rotates the DP-shard interleave in _free_slot_ids (engine
        # thread only)
        self._admit_rr = 0
        # requests popped from the queue but not yet activated into slots
        # (prefill in flight): cancel() can neither find them queued nor
        # active, so it flags them here and _activate retires them at the
        # next processed block (review finding — a disconnect during a
        # first-bucket compile otherwise orphans the request)
        self._admitting: set = set()
        self._cancel_pending: set = set()
        self._tiebreak = itertools.count()
        self._cv = make_condition("backend.engine.Engine._cv")
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # low-memory hook (ADVICE r4 medium #1): invoked (need_pages) from
        # the engine thread, OUTSIDE the engine lock, when paged admission
        # cannot allocate and nothing was admitted this round. The serving
        # layer evicts idle rolling conversations here; without it, idle
        # conversations could hold the pool while a queued request
        # break-retries forever (admission only retried after retirements,
        # and a fully-idle engine has none).
        self.on_pool_pressure: Optional[Callable[[int], None]] = None
        # tiered-KV hooks (ISSUE 19, wired by TierManager when rolling
        # KV is active on a single-shard paged engine):
        # - on_tier_pressure(need): engine thread, backpressure gate —
        #   the demote watermark tripped; the tier WORKER plans victims
        #   (non-blocking signal, no device work here);
        # - on_tier_drain(): engine thread, start of each admission
        #   round (after the pending-free flush) — execute planned
        #   demotions; their D2H gathers ride the flush wave the
        #   engine already syncs on.
        self.on_tier_pressure: Optional[Callable[[int], None]] = None
        self.on_tier_drain: Optional[Callable[[], None]] = None

        self._donate_cache = donate_cache
        donate = (4,) if donate_cache else ()
        K = self.decode_chunk

        # ---- compiled chunk: K decode steps per host round-trip -----------
        # Two variants: the full sampler, and a sort-free one used whenever
        # no ACTIVE slot has top-k/top-p enabled (sampling.py use_filters —
        # the [B, V] sort is the most expensive op in a large-batch decode
        # step). _dispatch_decode picks per chunk from host-side slot state.
        # Two chunk-loop shapes:
        # - chunked_fns (dense AND paged; the caller supplies the matching
        #   triple): the main cache stays FROZEN across the K steps; each
        #   step's K/V lands in a small [B, K, ...] buffer (uniform
        #   dynamic_update_slice) and is folded into the cache ONCE per
        #   chunk — a full-cache rewrite (dense) or bulk page scatter
        #   (paged) per chunk instead of per step. Profiling on the v5e
        #   showed the per-step rewrite cost ~2x the model matmuls.
        # - fallback (chunked_fns=None): per-step cache threading.
        self._chunked_fns = chunked_fns

        def _decode(params, last_tokens, last_lps, positions, cache,
                    base_keys, temp, topk, topp, *, use_filters,
                    assume_greedy=False):
            # last_tokens [B] fed tokens, last_lps [B] their raw-model
            # logprobs (computed where they were sampled — prefill or the
            # previous chunk), positions [B] next write positions.
            # Logprobs are computed UNCONDITIONALLY: the per-step
            # log_softmax is ~0.3% of a measured decode chunk and the
            # extra host block is 8 KB/chunk, while gating it would double
            # the compiled variant count (each 10-80 s over this image's
            # tunneled compile path) for a flag most requests leave off.
            if self._chunked_fns is not None:
                chunk_fwd, init_chunk, merge_chunk = self._chunked_fns
                chunk_kv = init_chunk(self.max_batch, K)

                def body(carry, step):
                    tok, pos, chunk_kv = carry
                    logits, chunk_kv = chunk_fwd(
                        params, tok[:, None], pos[:, None], cache, chunk_kv,
                        step,
                    )
                    nxt = sample_tokens(logits[:, -1], base_keys, pos, temp,
                                        topk, topp, use_filters=use_filters,
                                        assume_greedy=assume_greedy)
                    lp = token_logprob(logits[:, -1], nxt)
                    return (nxt, pos + 1, chunk_kv), (nxt, lp)

                (last, _, chunk_kv), (sampled, lps) = jax.lax.scan(
                    body, (last_tokens, positions, chunk_kv),
                    jnp.arange(K, dtype=jnp.int32),
                )
                new_cache = merge_chunk(cache, chunk_kv, positions)
                all_toks = jnp.concatenate([last_tokens[None], sampled], axis=0)
                all_lps = jnp.concatenate([last_lps[None], lps], axis=0)
                all_toks, all_lps = self._replicate_block(all_toks, all_lps)
                last, last_lp = self._pin_slot_state(last, lps[-1])
                return all_toks, all_lps, last, last_lp, new_cache

            def body(carry, _):
                tok, pos, cache = carry
                logits, cache = self._decode_forward(
                    params, tok[:, None], pos[:, None], cache
                )
                nxt = sample_tokens(logits[:, -1], base_keys, pos, temp,
                                    topk, topp, use_filters=use_filters,
                                    assume_greedy=assume_greedy)
                lp = token_logprob(logits[:, -1], nxt)
                return (nxt, pos + 1, cache), (nxt, lp)

            (last, _, cache), (sampled, lps) = jax.lax.scan(
                body, (last_tokens, positions, cache), None, length=K
            )
            # row 0 = the fed tokens (surfaces prefill samples the host has
            # never seen); rows 1..K = this chunk's samples
            all_toks = jnp.concatenate([last_tokens[None], sampled], axis=0)
            all_lps = jnp.concatenate([last_lps[None], lps], axis=0)
            all_toks, all_lps = self._replicate_block(all_toks, all_lps)
            last, last_lp = self._pin_slot_state(last, lps[-1])
            return all_toks, all_lps, last, last_lp, cache

        self._decode = jax.jit(
            functools.partial(_decode, use_filters=True),
            donate_argnums=donate)
        self._decode_fast = jax.jit(
            functools.partial(_decode, use_filters=False),
            donate_argnums=donate)
        self._decode_greedy = jax.jit(
            functools.partial(_decode, use_filters=False, assume_greedy=True),
            donate_argnums=donate)
        # ordered by parallel.multihost VARIANT_* codes
        self._decode_variants = (self._decode, self._decode_fast,
                                 self._decode_greedy)

        # ---- device-resident decode sessions (emission ring) -------------
        # One jitted ``lax.while_loop`` runs MANY decode chunks per host
        # visit: each chunk's [K+1, B] token block is pushed host-ward
        # through an ORDERED ``io_callback`` (the emission ring — the
        # device runs one chunk ahead of host-side emission, so the
        # stream is double-buffered by construction), and the host only
        # touches the device ONCE per session: the drain read of the
        # chunk counter after the loop exits. The callback's boolean
        # return is the host's continue vote (new admissible work, a
        # cancel, stop), consumed at the next chunk boundary — stop and
        # stream emission are serviced from the ring without ever
        # blocking the device loop. Single-shard PAGED engines only: the
        # shard_map'd multi-device program and the pod control plane
        # keep the per-chunk scan+pipeline path (SWARMDB_EMIT_RING=0
        # forces that path everywhere).
        self._resident_variants: Optional[Tuple[Any, ...]] = None
        self._resident_snap: Optional[List[Tuple[int, GenRequest, int]]] \
            = None
        self._resident_prev_ns = 0
        self._lane_busy = False
        self._host_sync_n = 0  # engine-LOCAL sync count (registry
        # counters are shared across lanes, so per-request deltas must
        # not absorb sibling engines' syncs)
        if (paged is not None
                and getattr(paged.allocator, "n_shards", 1) <= 1
                and os.environ.get("SWARMDB_EMIT_RING", "1") != "0"):

            def _decode_resident(params, last_tokens, last_lps, positions,
                                 cache, base_keys, temp, topk, topp,
                                 stop_pos, live, max_chunks, *,
                                 use_filters, assume_greedy=False):
                # stop_pos [B]: first position at/after which the slot
                # needs no more tokens (max_new_tokens bound; the host
                # remains the authority on exact retirement — the device
                # estimate only decides when the LOOP may stop). live
                # [B]: slots participating in this session; dead lanes
                # start done and compute discarded garbage, exactly like
                # the scan path.
                def cond(carry):
                    n, done, cont = carry[0], carry[1], carry[2]
                    return (n < max_chunks) & cont & ~jnp.all(done)

                def body(carry):
                    n, done, cont, lt, llp, pos, cache = carry
                    all_toks, all_lps, lt, llp, cache = _decode(
                        params, lt, llp, pos, cache, base_keys, temp,
                        topk, topp, use_filters=use_filters,
                        assume_greedy=assume_greedy)
                    pos = pos + K
                    # eos anywhere in the block (row 0 = fed token covers
                    # an eos prefill sample) marks the lane done; done
                    # lanes keep computing garbage the host discards
                    done = (done | (pos >= stop_pos)
                            | jnp.any(all_toks == self.eos_id, axis=0))
                    cont = io_callback(
                        self._resident_emit,
                        jax.ShapeDtypeStruct((), jnp.bool_),
                        all_toks, all_lps, n, ordered=True)
                    return (n + 1, done, cont, lt, llp, pos, cache)

                init = (jnp.int32(0), ~live, jnp.bool_(True), last_tokens,
                        last_lps, positions, cache)
                n, _done, _cont, lt, llp, _pos, cache = jax.lax.while_loop(
                    cond, body, init)
                return n, lt, llp, cache

            self._resident_variants = tuple(
                jax.jit(functools.partial(_decode_resident,
                                          use_filters=uf,
                                          assume_greedy=ag),
                        donate_argnums=donate)
                for uf, ag in ((True, False), (False, False),
                               (False, True)))
        # set by ShardLaneGroup: returns True when a SIBLING lane has a
        # decode session in flight while this lane admits — the overlap
        # the per-shard lanes exist to create (flight/SLO counter
        # ``engine_admission_overlap_steps``)
        self.overlap_probe: Optional[Callable[[], bool]] = None
        # single-device lane placement (ShardLaneGroup): state rebuilds
        # (restart / in-loop error recovery) must land on THIS device,
        # not the process default
        self._home_device = None
        # multi-host control plane (parallel/multihost.py): set by
        # enable_multihost(); when active, every device call is published
        # so worker hosts replay it in lockstep
        self._mh = None

        # ---- compiled prefill, BATCHED: one variant per bucket ------------
        # Prefill at small T is HBM-bound (a full parameter read), so
        # prefilling up to ``prefill_batch`` admitted prompts in ONE call
        # costs nearly the same as one. Rows beyond the real group are
        # padding (length 1) whose results the host discards.
        if prefill_batch is None:
            prefill_batch = 8
        self.prefill_batch = max(1, min(prefill_batch, max_batch))
        # ---- row-bucketed waves (per-shard admission lanes) ---------------
        # A lane's waves are small (<= slots-per-lane) and often partial;
        # padding the ROW dimension to prefill_batch unconditionally made
        # the lane path pay ~4x its real prefill compute (measured 78%
        # grid padding on the dp8 bench vs 11% at dp1). Small-batch PAGED
        # engines therefore pad rows to the smallest power-of-two bucket
        # covering the admission count instead. Each row bucket is a
        # compiled variant (warmup covers rows x buckets x widths), so
        # the ladder is gated to prefill_batch <= 4 — exactly the lane
        # geometry — unless SWARMDB_PREFILL_ROWS forces it (1) or off (0).
        rows_env = os.environ.get("SWARMDB_PREFILL_ROWS", "auto")
        row_bucketed = (paged is not None
                        and getattr(paged.allocator, "n_shards", 1) <= 1
                        and (self.prefill_batch <= 4
                             if rows_env == "auto" else rows_env != "0"))
        if row_bucketed:
            ladder = [1]
            while ladder[-1] < self.prefill_batch:
                ladder.append(min(self.prefill_batch, ladder[-1] * 2))
            self._row_buckets = ladder
        else:
            self._row_buckets = [self.prefill_batch]

        # ---- fused dense prefill: forward + sample + cache insert + fed-
        # token scatter in ONE compiled dispatch per admission group.
        # The round-3 bench collapse (BENCH_r03: 4.8 msg/s while the
        # compiled chunk alone sustains 40x that) traced in part to the
        # dense admission path running ~6 eager device ops per group — two
        # of them full-cache `.at[].set` copies executed OUTSIDE jit, each
        # an un-donated copy of the whole decode cache plus a host round
        # trip on this image's tunneled TPU. Here the temp prefill cache is
        # created inside the trace, the slot insert donates the main cache,
        # and padding rows carry slot_id == max_batch so mode="drop"
        # discards their writes (they never touch live lanes).
        def _forward_last_of(params, tokens, positions, cacheB, lengths):
            # [Bp, V] logits at each row's final prompt position — via the
            # head-at-last forward when the model provides one (see
            # forward_last_fn above), else full logits + gather
            if self._forward_last is not None:
                return self._forward_last(params, tokens, positions, cacheB,
                                          lengths - 1)
            logits, cacheB = self.forward_fn(params, tokens, positions,
                                             cacheB)
            return logits[jnp.arange(tokens.shape[0]), lengths - 1], cacheB

        self._forward_last_of = _forward_last_of

        def _prefill_insert(params, tokens, lengths, slot_ids, cache,
                            last_tokens, last_lps, base_keys, temp, topk,
                            topp):
            Bp, T = tokens.shape
            positions = jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[None], (Bp, T)
            )
            cacheB = self._prefill_cache_fn(Bp, T)
            last, cacheB = _forward_last_of(params, tokens, positions,
                                            cacheB, lengths)
            next_tok = sample_tokens(
                last, base_keys, lengths - 1, temp, topk, topp
            )
            lp = token_logprob(last, next_tok)
            cache = jax.tree.map(
                lambda full, fresh: full.at[:, slot_ids, :T].set(
                    fresh, mode="drop"),
                cache, cacheB,
            )
            last_tokens = last_tokens.at[slot_ids].set(next_tok, mode="drop")
            last_lps = last_lps.at[slot_ids].set(lp, mode="drop")
            last_tokens, last_lps = self._pin_slot_state(last_tokens,
                                                         last_lps)
            return cache, last_tokens, last_lps

        self._prefill_fused = jax.jit(_prefill_insert,
                                      donate_argnums=(4, 5, 6))

        # ---- fused PAGED prefill: forward + sample + page scatter + fed-
        # token scatter in ONE dispatch, pool-donating. The unfused path
        # (temp-cache zeros + jitted prefill + eager pad + insert + token
        # scatter) cost ~5 device round-trips per admission group; on the
        # tunneled TPU that made paged prefill ~12x slower than the dense
        # fused path (swarm100 r4: 3.4k vs 42k prompt tok/s).
        def _prefill_paged_insert(params, tokens, lengths, target_pages,
                                  slot_ids, k_pool, v_pool, last_tokens,
                                  last_lps, base_keys, temp, topk, topp):
            # tokens [Bp, T]; target_pages [Bp, chunks] physical page ids
            # (padding rows and short-prompt tail chunks -> trash page 0);
            # slot_ids [Bp] fed-token scatter targets (padding -> max_batch,
            # dropped).
            Bp, T = tokens.shape
            positions = jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[None], (Bp, T)
            )
            cacheB = self._prefill_cache_fn(Bp, T)
            last, cacheB = _forward_last_of(params, tokens, positions,
                                            cacheB, lengths)
            next_tok = sample_tokens(
                last, base_keys, lengths - 1, temp, topk, topp
            )
            lp = token_logprob(last, next_tok)
            ck, cv = cacheB                             # [L, Bp, T, Hkv, D]
            ps = self.paged.page_size
            chunks = target_pages.shape[1]
            pad_to = chunks * ps
            if pad_to != T:
                # pad region is prompt padding — length-masked, never read
                pad = [(0, 0), (0, 0), (0, pad_to - T), (0, 0), (0, 0)]
                ck = jnp.pad(ck, pad)
                cv = jnp.pad(cv, pad)
            L = ck.shape[0]
            tail = ck.shape[3:]
            kc = ck.reshape((L, Bp * chunks, ps) + tail)
            vc = cv.reshape((L, Bp * chunks, ps) + tail)
            flat = target_pages.reshape(-1)             # [Bp*chunks]
            # pool_insert_pages quantizes whole pages on write for the
            # int8 QuantPool (scale from per-page-per-head amax); plain
            # pools keep the old cast-and-scatter
            from ..ops.paged_kv import pool_insert_pages

            k_pool = pool_insert_pages(k_pool, flat, kc)
            v_pool = pool_insert_pages(v_pool, flat, vc)
            last_tokens = last_tokens.at[slot_ids].set(next_tok, mode="drop")
            last_lps = last_lps.at[slot_ids].set(lp, mode="drop")
            last_tokens, last_lps = self._pin_slot_state(last_tokens,
                                                         last_lps)
            return k_pool, v_pool, last_tokens, last_lps

        if paged is not None:
            self._prefill_paged_fused = jax.jit(
                _prefill_paged_insert, donate_argnums=(5, 6, 7, 8)
            )
            self._prefill_paged_packed = None
            if paged.prefill_packed is not None:
                # same argument order as _prefill_paged_insert, same
                # donation; rows = n_shards * prefill_batch per wave so
                # any admission skew still fits one dispatch. The pin is
                # a no-op resharding (shard_map's out_specs already put
                # the fed-token vectors on the canonical P('data')), so
                # the packed program stays collective-free.
                _packed_body_fn = paged.prefill_packed

                def _prefill_packed_pinned(params, tokens, lengths, target,
                                           scatter, k_pool, v_pool,
                                           last_tokens, last_lps, keys,
                                           temp, topk, topp):
                    k_pool, v_pool, last_tokens, last_lps = _packed_body_fn(
                        params, tokens, lengths, target, scatter, k_pool,
                        v_pool, last_tokens, last_lps, keys, temp, topk,
                        topp)
                    last_tokens, last_lps = self._pin_slot_state(
                        last_tokens, last_lps)
                    return k_pool, v_pool, last_tokens, last_lps

                self._prefill_paged_packed = jax.jit(
                    _prefill_packed_pinned, donate_argnums=(5, 6, 7, 8)
                )

        # ---- RAGGED packed prefill (ISSUE 11 tentpole) --------------------
        # One no-padding token stream per admission wave: rows concatenate
        # back to back, per-row (start, len, prefix_len) descriptors ride
        # the dispatch, and attention reads each row's prefix KV straight
        # from the page pool (ops/layers.ragged_prefill_dispatch — the
        # Pallas ragged-paged-prefill kernel on TPU). Wave widths come off
        # a power-of-two ladder whose smallest rung (SWARMDB_RAGGED_MIN_
        # WIDTH, default 8) makes every admission round a near-exact
        # binary decomposition — padding_tokens ~0 where the row-bucketed
        # path paid 12%. The floor sits at 8 (one TPU sublane quantum)
        # rather than 1: rungs below 8 each compile a program that the
        # dispatcher immediately pads back up to width 8, so they add
        # compiled variants and per-wave dispatch overhead while moving
        # zero extra real tokens (PROFILE.md round 11 A/B). The ladder is
        # the ONLY compiled-variant axis:
        # |widths| programs replace |buckets| x |row buckets| (+ the whole
        # prefix-variant family, since a cache hit is just a nonzero
        # prefix_len here). SWARMDB_RAGGED_PREFILL=0 restores the
        # row-bucketed waves.
        self._prefill_ragged_fused = None
        self._ragged_widths: List[int] = []
        self._last_wave_kind: Optional[str] = None
        # which decode-attention path serves this engine's waves (paged
        # only): stamped on flight-step records so kernel-vs-gather
        # regressions are attributable from a dump alone
        self._decode_kernel: Optional[str] = None
        if paged is not None:
            from ..ops.layers import decode_kernel_choice

            self._decode_kernel = decode_kernel_choice(
                paged.allocator.maxp * paged.page_size)
        if (paged is not None and paged.prefill_ragged is not None
                and getattr(paged.allocator, "n_shards", 1) <= 1
                and os.environ.get("SWARMDB_RAGGED_PREFILL", "auto") != "0"):
            try:
                min_w = int(os.environ.get("SWARMDB_RAGGED_MIN_WIDTH", "8"))
            except ValueError:
                logger.warning("SWARMDB_RAGGED_MIN_WIDTH=%r is not an int; "
                               "using 8",
                               os.environ.get("SWARMDB_RAGGED_MIN_WIDTH"))
                min_w = 8
            ladder = [max(1, min(min_w, max_seq))]
            while ladder[-1] < max_seq:
                ladder.append(min(max_seq, ladder[-1] * 2))
            self._ragged_widths = ladder
            _ragged_body_fn = paged.prefill_ragged

            def _prefill_ragged_insert(params, tokens, tok_row, tok_pos,
                                       starts, lens, plens, row_tables,
                                       scatter, k_pool, v_pool,
                                       last_tokens, last_lps, base_keys,
                                       temp, topk, topp):
                # tokens/tok_row/tok_pos [W] packed stream (padding:
                # row >= R, pos >= table coverage -> trash writes);
                # descriptors [R]; scatter [R] fed-token targets —
                # max_batch (dropped) for padding rows AND rows whose
                # prompt continues in a later wave of the same round.
                from ..ops.paged_kv import paged_write_ragged

                last, sk, sv = _ragged_body_fn(
                    params, tokens, tok_row, tok_pos, row_tables, starts,
                    lens, plens, k_pool, v_pool)
                # absolute-position PRNG fold == the bucketed paths'
                # (prefix_lens + lengths - 1): identical sampling for an
                # identical prompt whichever path admitted it
                next_tok = sample_tokens(
                    last, base_keys, jnp.maximum(plens + lens - 1, 0),
                    temp, topk, topp)
                lp = token_logprob(last, next_tok)
                k_pool, v_pool = paged_write_ragged(
                    k_pool, v_pool, sk, sv, tok_row, tok_pos, row_tables)
                last_tokens = last_tokens.at[scatter].set(next_tok,
                                                          mode="drop")
                last_lps = last_lps.at[scatter].set(lp, mode="drop")
                last_tokens, last_lps = self._pin_slot_state(last_tokens,
                                                             last_lps)
                return k_pool, v_pool, last_tokens, last_lps

            self._prefill_ragged_fused = jax.jit(
                _prefill_ragged_insert, donate_argnums=(9, 10, 11, 12)
            )

        # ---- automatic prefix caching --------------------------------------
        # Chat serving re-prefills each conversation's WHOLE history every
        # turn (prefill dominated decode ~15:1 on the round-4 serve
        # profile). The prefix cache reuses page-aligned prompt KV across
        # requests: admission matches the longest cached prefix and
        # prefills only the suffix. Dense mode keeps a SIDE pool and
        # copies reused pages into slot lanes; paged mode reuses pool
        # pages IN PLACE (pinning them while referenced). See
        # ops/prefix_cache.py for chain hashing + eviction safety.
        self._prefix = None
        self._prefix_fns = prefix_fns
        # paged mode: pages each live slot keeps pinned (matched hits +
        # pages it registered); unpinned at retirement
        self._slot_prefix_pins: Dict[int, List[int]] = {}
        if prefix_fns is not None and paged is not None:
            # PAGED mode: reuse IN PLACE — the main pool holds the cached
            # pages, hit pages are pinned while a slot's table row
            # references them, suffix KV scatters straight into the
            # slot's fresh pages (page-aligned: reuse is page-granular),
            # and registration is free (no copy — custody of the slot's
            # full prompt pages just moves to the cache at registration).
            if max_seq % paged.page_size:
                raise ValueError("max_seq must be a page-size multiple "
                                 "for prefix caching")
            from ..ops.prefix_cache import make_prefix_lru

            self._prefix_ps = paged.page_size
            # paged mode shares the allocator's pool (and, under
            # SWARMDB_PAGECHECK=1, its shadow state — obs/pagecheck.py)
            self._prefix = make_prefix_lru(paged.num_pages,
                                           paged.page_size,
                                           manage_free=False,
                                           pool=paged.allocator)
            pages_fwd = prefix_fns[0]
            maxp_row = paged.allocator.maxp
            self._prefix_pp_buckets = self._pp_widths(maxp_row)

            def _prefill_paged_prefix_insert(params, tokens, lengths,
                                             prefix_lens, prefix_table,
                                             target_pages, slot_ids, k_pool,
                                             v_pool, last_tokens, last_lps,
                                             base_keys, temp, topk, topp):
                # tokens [Bp, T] SUFFIX tokens; prefix_table [Bp, PP] live
                # pool pages (gather); target_pages [Bp, chunks] fresh
                # pages for the suffix (page-aligned since the reused
                # prefix is page-granular; trash 0 for padding)
                Bp, T = tokens.shape
                ps = self.paged.page_size
                logits, sk, sv = pages_fwd(
                    params, tokens, prefix_table, prefix_lens, k_pool,
                    v_pool, logits_at=lengths - 1,
                )
                last = (logits if logits.ndim == 2
                        else logits[jnp.arange(Bp), lengths - 1])
                next_tok = sample_tokens(
                    last, base_keys, prefix_lens + lengths - 1, temp, topk,
                    topp,
                )
                lp = token_logprob(last, next_tok)
                chunks = target_pages.shape[1]
                pad_to = chunks * ps
                if pad_to != T:
                    pad = [(0, 0), (0, 0), (0, pad_to - T), (0, 0), (0, 0)]
                    sk = jnp.pad(sk, pad)
                    sv = jnp.pad(sv, pad)
                L = sk.shape[0]
                tail = sk.shape[3:]
                kc = sk.reshape((L, Bp * chunks, ps) + tail)
                vc = sv.reshape((L, Bp * chunks, ps) + tail)
                flat = target_pages.reshape(-1)
                from ..ops.paged_kv import pool_insert_pages

                k_pool = pool_insert_pages(k_pool, flat, kc)
                v_pool = pool_insert_pages(v_pool, flat, vc)
                last_tokens = last_tokens.at[slot_ids].set(next_tok,
                                                           mode="drop")
                last_lps = last_lps.at[slot_ids].set(lp, mode="drop")
                last_tokens, last_lps = self._pin_slot_state(last_tokens,
                                                             last_lps)
                return k_pool, v_pool, last_tokens, last_lps

            self._prefill_paged_prefix_fused = jax.jit(
                _prefill_paged_prefix_insert, donate_argnums=(7, 8, 9, 10)
            )

            # ---- rolling-KV resume: suffix prefill continuing a kept
            # conversation MID-PAGE. Same suffix forward as the prefix
            # path (attend kept pages + suffix, positions offset by
            # resume_len), but the suffix K/V is written POSITIONALLY via
            # paged_write_chunk (start = resume_len, arbitrary alignment)
            # into the row's table instead of whole-page scatters — a
            # conversation's length after decode is never page-aligned.
            def _prefill_paged_resume_insert(params, tokens, lengths,
                                             resume_lens, prefix_table,
                                             row_tables, slot_ids, k_pool,
                                             v_pool, last_tokens, last_lps,
                                             base_keys, temp, topk, topp):
                from ..ops.paged_kv import paged_write_chunk, pool_dtype

                Bp, T = tokens.shape
                logits, sk, sv = pages_fwd(
                    params, tokens, prefix_table, resume_lens, k_pool,
                    v_pool, logits_at=lengths - 1,
                )
                last = (logits if logits.ndim == 2
                        else logits[jnp.arange(Bp), lengths - 1])
                next_tok = sample_tokens(
                    last, base_keys, resume_lens + lengths - 1, temp, topk,
                    topp,
                )
                lp = token_logprob(last, next_tok)
                k_pool, v_pool = paged_write_chunk(
                    k_pool, v_pool, sk.astype(pool_dtype(k_pool)),
                    sv.astype(pool_dtype(v_pool)), resume_lens, row_tables,
                )
                last_tokens = last_tokens.at[slot_ids].set(next_tok,
                                                           mode="drop")
                last_lps = last_lps.at[slot_ids].set(lp, mode="drop")
                last_tokens, last_lps = self._pin_slot_state(last_tokens,
                                                             last_lps)
                return k_pool, v_pool, last_tokens, last_lps

            self._prefill_paged_resume_fused = jax.jit(
                _prefill_paged_resume_insert, donate_argnums=(7, 8, 9, 10)
            )
        elif prefix_fns is not None:
            if max_seq % prefix_page_size:
                raise ValueError("max_seq must be a page-size multiple "
                                 "for prefix caching")
            from ..ops.prefix_cache import make_prefix_lru

            self._prefix_ps = prefix_page_size
            self._prefix = make_prefix_lru(max(2, prefix_pages),
                                           prefix_page_size)
            lane_fwd, init_pool = prefix_fns
            self._prefix_init_pool = init_pool
            self._prefix_pool = init_pool(max(2, prefix_pages),
                                          prefix_page_size)
            maxp_lane = max_seq // prefix_page_size
            self._prefix_pp_buckets = self._pp_widths(maxp_lane)

            def _prefill_prefix_insert(params, tokens, lengths, prefix_lens,
                                       prefix_table, reg_cols, reg_pages,
                                       slot_ids, cache, last_tokens,
                                       last_lps, pool_k, pool_v, base_keys,
                                       temp, topk, topp):
                # tokens [Bp, T] SUFFIX tokens; prefix_table [Bp, PP] pool
                # pages; reg_cols [Bp, RC] lane-page index to register
                # (-1 = none); reg_pages [Bp, RC] target pool ids (0=trash)
                Bp, T = tokens.shape
                ps = self._prefix_ps
                PP = prefix_table.shape[1]
                lane_pages = min(PP + -(-T // ps), self.max_seq // ps)
                logits, lane_k, lane_v = lane_fwd(
                    params, tokens, prefix_table, prefix_lens, pool_k,
                    pool_v, lane_pages, logits_at=lengths - 1,
                )
                last = (logits if logits.ndim == 2
                        else logits[jnp.arange(Bp), lengths - 1])
                # absolute position keys the PRNG fold => identical
                # sampling to a full (non-cached) prefill of this prompt
                next_tok = sample_tokens(
                    last, base_keys, prefix_lens + lengths - 1, temp, topk,
                    topp,
                )
                lp = token_logprob(last, next_tok)
                ck, cv = cache
                lane_t = lane_pages * ps
                ck = ck.at[:, slot_ids, :lane_t].set(lane_k, mode="drop")
                cv = cv.at[:, slot_ids, :lane_t].set(lane_v, mode="drop")
                # register: extract the named lane pages (one-hot einsum —
                # per-row gathers don't compile well on TPU) into the pool
                L = lane_k.shape[0]
                RC = reg_cols.shape[1]
                sel = (reg_cols[..., None]
                       == jnp.arange(lane_pages)[None, None, :])
                sel = sel.astype(lane_k.dtype)          # [Bp, RC, P_lane]
                lk = lane_k.reshape(L, Bp, lane_pages, ps, *lane_k.shape[3:])
                lv = lane_v.reshape(L, Bp, lane_pages, ps, *lane_v.shape[3:])
                flat = reg_pages.reshape(-1)
                ck_pages = jnp.einsum("brp,lbpshd->lbrshd", sel, lk)
                cv_pages = jnp.einsum("brp,lbpshd->lbrshd", sel, lv)
                pool_k = pool_k.at[:, flat].set(
                    ck_pages.reshape(L, Bp * RC, ps, *lane_k.shape[3:]))
                pool_v = pool_v.at[:, flat].set(
                    cv_pages.reshape(L, Bp * RC, ps, *lane_v.shape[3:]))
                last_tokens = last_tokens.at[slot_ids].set(next_tok,
                                                           mode="drop")
                last_lps = last_lps.at[slot_ids].set(lp, mode="drop")
                last_tokens, last_lps = self._pin_slot_state(last_tokens,
                                                             last_lps)
                return (ck, cv), last_tokens, last_lps, pool_k, pool_v

            self._prefill_prefix_fused = jax.jit(
                _prefill_prefix_insert, donate_argnums=(8, 9, 10, 11, 12)
            )

            # ---- dense rolling-KV retirement extraction: copy a retired
            # slot's lane KV (positions 0..written, page-chunked) into
            # prefix-pool pages whose custody moves to the caller's
            # registry. The dense lane is slot-private (unlike the paged
            # pool, where custody transfer is pure host bookkeeping), so
            # keeping a conversation's KV across turns costs ONE
            # bandwidth-bound copy here and one gather at resume — far
            # cheaper than the full-history prefill it replaces. Padding
            # rows of target_pages are 0: the trash page absorbs them.
            lane_maxp = max_seq // prefix_page_size

            def _extract_lane(cache, pool_k, pool_v, slot_id, target_pages):
                ck, cv = cache
                L = ck.shape[0]
                tail_shape = ck.shape[3:]
                lk = jnp.take(ck, slot_id, axis=1)  # [L, S, Hkv, D]
                lv = jnp.take(cv, slot_id, axis=1)
                lk = lk.reshape((L, lane_maxp, prefix_page_size) + tail_shape)
                lv = lv.reshape((L, lane_maxp, prefix_page_size) + tail_shape)
                pool_k = pool_k.at[:, target_pages].set(
                    lk.astype(pool_k.dtype))
                pool_v = pool_v.at[:, target_pages].set(
                    lv.astype(pool_v.dtype))
                return pool_k, pool_v

            self._extract_lane_fused = jax.jit(
                _extract_lane, donate_argnums=(1, 2)
            )

        self.total_generated = 0
        self.total_requests = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="swarmdb-engine")
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self._mh is not None:
            # release worker hosts blocked in worker_loop's receive
            try:
                self._mh.publish_stop()
            except Exception:
                logger.exception("multihost stop broadcast failed")

    def alive(self) -> bool:
        """True while the decode loop thread is running."""
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------- supervision signals

    # swarmlint: heartbeat
    def _beat(self) -> None:
        """Per-step liveness proof (engine loop / emission callback):
        one monotonic read into a single-writer float slot — the
        supervisor's verdict path reads it lock-free."""
        self._beat_mono = time.monotonic()

    def _chaos_pending(self) -> bool:
        cs = self.chaos_step
        return cs is not None and getattr(cs, "pending",
                                          lambda: False)()

    # swarmlint: heartbeat
    def beat_age_s(self, now: float = 0.0) -> float:
        """Seconds since the decode loop last proved progress. Idle
        engines still beat (the admission wait loop stamps every wait
        tick); only a dead or wedged loop lets this grow."""
        return (now or time.monotonic()) - self._beat_mono

    # ---------------------------------------------------------- multi-host

    def place_state(self, mesh) -> None:
        """Re-materialize the engine's replicated device state (fed-token
        vector, PRNG keys) ON the mesh, computed device-side.

        Required before multi-process serving: state built by plain
        ``jnp.zeros`` lives on the process-local default device, and a jit
        over a global mesh cannot mix process-local arrays with global
        ones. Computing the state under ``out_shardings`` avoids any host
        transfer and yields bit-identical values on every host. Idempotent
        and also valid (harmless) for single-process multi-chip meshes.

        Also fixes the CANONICAL sharding of the per-slot state vectors
        (``_state_sharding``, enforced by ``_pin_slot_state`` in every
        jitted body): without it each compiled program hands the fed-token
        vectors back in whatever sharding GSPMD picked for THAT program
        (measured: decode returns them P('data') after place_state made
        them replicated), so the next variant's eager call lowers a
        DIFFERENT HLO than warmup_call_plan's specs and the parallel AOT
        precompile's persistent-cache entries are never read — every
        warmup variant compiled twice on mesh-placed engines (PROFILE r5
        finding d / VERDICT r5 #6)."""
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        # decode-chunk token blocks must come back replicated (see
        # _replicate_block) — set BEFORE the first decode call traces
        self._out_rep = rep
        B = self.max_batch
        # canonical per-slot state sharding: batch over 'data' when it
        # divides evenly (matches the shard_map'd packed prefill's
        # out_specs, so pinning costs no collective there), replicated
        # otherwise. What matters is that it never changes again.
        data = mesh.shape.get("data", 1)
        if data > 1 and B % data == 0:
            self._state_sharding = NamedSharding(mesh,
                                                 PartitionSpec("data"))
        else:
            self._state_sharding = rep
        self._last_tokens, self._last_lps = self._fresh_slot_state()
        self.base_keys = jax.jit(
            lambda: make_slot_keys(self._seed, B), out_shardings=rep)()
        self._base_keys_np = np.array(
            jax.device_get(self.base_keys))
        self._default_keys_np = self._base_keys_np.copy()
        if self._prefix is not None and not self.paged:
            # the dense prefix side pool was built process-local in
            # __init__; a jit over a global mesh cannot mix it with the
            # global cache — rematerialize it (zeros) on the mesh,
            # replicated (every shard reads any page via the lane gather)
            self._prefix_pool = jax.jit(
                lambda: self._prefix_init_pool(self._prefix.num_pages,
                                               self._prefix_ps),
                out_shardings=rep)()

    def enable_multihost(self) -> None:
        """Publish every device call to worker hosts (coordinator side).

        Requires ``jax.distributed.initialize`` to have run and the
        engine's params/cache to live on a global mesh; see
        ``parallel/multihost.py`` and ``Engine.worker_loop``. Paged and
        prefix-cached engines are supported (VERDICT r4 #6): their
        allocator / prefix-table state stays coordinator-local — it only
        COMPUTES the numpy arguments (page rows, gather tables,
        registration columns) of device calls, and every device call is
        published through the generic mirrored-call channel, so worker
        pool state evolves identically. Rolling-KV resume remains refused
        in pod mode at the serving layer (page custody cannot survive a
        pod restart)."""
        from ..parallel.multihost import ControlPlane

        self._mh = ControlPlane(self.max_batch, self.prefill_batch)

    def worker_loop(self) -> None:
        """Run on every NON-coordinator host: replay the coordinator's
        device calls in lockstep until it publishes stop.

        Device state (params, cache, fed-token vector) must be constructed
        identically on every host before entering — deterministic sharded
        init guarantees this (parallel/serving.build_sharded_model). The
        loop issues the exact jit call the coordinator issued, with the
        broadcast numpy arguments, so the SPMD programs rendezvous on
        their collectives; sampled tokens exist on this host's shards but
        only the coordinator reads them."""
        from ..parallel import multihost as mh

        if self._mh is None:
            self._mh = mh.ControlPlane(self.max_batch, self.prefill_batch)
        while True:
            op, args = self._mh.receive()
            if op == mh.OP_STOP:
                return
            if op == mh.OP_DECODE:
                variant, positions, keys, temp, topk, topp = args
                fn = self._decode_variants[variant]
                (all_toks, _lps, self._last_tokens, self._last_lps,
                 self.cache) = fn(
                    self.params, self._last_tokens, self._last_lps,
                    positions, self.cache, keys, temp, topk, topp,
                )
            elif op == mh.OP_PREFILL:
                tokens, lengths, scatter, keys, temp, topk, topp = args
                self.cache, self._last_tokens, self._last_lps = \
                    self._prefill_fused(
                        self.params, tokens, lengths, scatter, self.cache,
                        self._last_tokens, self._last_lps, keys, temp, topk,
                        topp,
                    )
            elif op == mh.OP_CALL:
                call_id, call_args = args[0], args[1:]
                self._MH_CALLS[call_id](self, *call_args)

    # Generic mirrored device calls (paged / prefix paths). Each handler
    # consumes ONLY numpy arguments + device state (params, cache, fed
    # tokens, prefix pool) — never the coordinator-local allocator or
    # prefix table — so replaying it on a worker host with the published
    # arguments reproduces the coordinator's device state exactly.
    CALL_PAGED_PREFILL = 0
    CALL_PAGED_PREFIX_PREFILL = 1
    CALL_PAGED_RESUME_PREFILL = 2
    CALL_SET_PT_ROWS = 3
    CALL_DENSE_PREFIX_PREFILL = 4
    CALL_PAGED_PREFILL_PACKED = 5
    CALL_PAGED_PREFILL_RAGGED = 6

    def _replicate_block(self, all_toks, all_lps):
        """Constrain the chunk's sampled-token block to REPLICATED when the
        engine lives on a mesh (``place_state`` sets ``_out_rep``): the
        shard_map'd paged decode leaves it data-sharded, which a pod
        coordinator cannot device_get (the shards span other processes).
        The all-gather this inserts moves [K+1, B] ints — bytes, not
        bandwidth. Traced at first call, AFTER place_state; single-chip
        engines (no mesh) see None and compile unchanged."""
        rep = getattr(self, "_out_rep", None)
        if rep is None:
            return all_toks, all_lps
        return (jax.lax.with_sharding_constraint(all_toks, rep),
                jax.lax.with_sharding_constraint(all_lps, rep))

    def _pin_slot_state(self, *arrays):
        """Constrain per-slot [B] state outputs (fed tokens / logprobs) to
        the canonical sharding chosen by ``place_state``, inside every
        jitted body that returns them. Without the pin, each compiled
        program hands the vectors back in whatever sharding GSPMD picked
        for THAT program (decode emitted P('data') where place_state made
        them replicated), so the NEXT variant's eager call lowers a
        different HLO than ``warmup_call_plan``'s specs — the AOT
        persistent-cache mismatch of PROFILE r5 finding d. Traced at first
        call, AFTER place_state; single-chip engines see None and compile
        unchanged (same pattern as ``_replicate_block``)."""
        sh = getattr(self, "_state_sharding", None)
        if sh is None:
            return arrays
        return tuple(jax.lax.with_sharding_constraint(a, sh)
                     for a in arrays)

    def _fresh_slot_state(self):
        """Zeroed fed-token/logprob vectors in the canonical placement —
        on the mesh when place_state has run (restart must not demote the
        state to process-local, or every variant recompiles against the
        unplaced sharding), default device otherwise."""
        B = self.max_batch
        sh = getattr(self, "_state_sharding", None)
        if sh is None:
            with self._device_ctx():
                return (jnp.zeros((B,), jnp.int32),
                        jnp.zeros((B,), jnp.float32))
        return (
            jax.jit(lambda: jnp.zeros((B,), jnp.int32), out_shardings=sh)(),
            jax.jit(lambda: jnp.zeros((B,), jnp.float32),
                    out_shardings=sh)(),
        )

    # swarmlint: borrows[page]: args
    def _mirrored(self, call_id: int, *args) -> None:  # swarmlint: hot
        """Publish (pod mode) then execute one mirrored device call.
        Publish FIRST, matching the decode/prefill pattern: if the local
        execution raises, the pod is already failing loudly through the
        decode loop's fatal-stop path. Under swarmprof the execution is
        wall-timed around the dispatch (the CPU-fallback device-time
        approximation; one key build + two clock reads per admission
        wave, never per token)."""
        if self._mh is not None:
            self._mh.publish_call(call_id, args)
        prof = self._prof
        if prof.enabled:
            t0 = time.monotonic_ns()
            self._MH_CALLS[call_id](self, *args)
            prof.dispatch(self._PROF_MIRRORED[call_id](args), t0,
                          time.monotonic_ns() - t0)
        else:
            self._MH_CALLS[call_id](self, *args)

    # swarmlint: hot
    def _call_paged_prefill(self, tokens, lengths, target, scatter, keys,
                            temp, topk, topp) -> None:
        k_pool, v_pool, self._last_tokens, self._last_lps = \
            self._prefill_paged_fused(
                self.params, tokens, lengths, target, scatter,
                self.cache["k"], self.cache["v"], self._last_tokens,
                self._last_lps, keys, temp, topk, topp,
            )
        self.cache = self._paged_cache_with(k_pool, v_pool)

    # swarmlint: hot
    def _call_paged_prefill_packed(self, tokens, lengths, target, scatter,
                                   keys, temp, topk, topp) -> None:
        k_pool, v_pool, self._last_tokens, self._last_lps = \
            self._prefill_paged_packed(
                self.params, tokens, lengths, target, scatter,
                self.cache["k"], self.cache["v"], self._last_tokens,
                self._last_lps, keys, temp, topk, topp,
            )
        self.cache = self._paged_cache_with(k_pool, v_pool)

    # swarmlint: hot
    def _call_paged_prefix_prefill(self, tokens, lengths, plens, table,
                                   target, scatter, keys, temp, topk,
                                   topp) -> None:
        pk, pv, self._last_tokens, self._last_lps = \
            self._prefill_paged_prefix_fused(
                self.params, tokens, lengths, plens, table, target, scatter,
                self.cache["k"], self.cache["v"], self._last_tokens,
                self._last_lps, keys, temp, topk, topp,
            )
        self.cache = self._paged_cache_with(pk, pv)

    # swarmlint: hot
    def _call_paged_resume_prefill(self, tokens, lengths, rlens, table,
                                   row_tables, scatter, keys, temp, topk,
                                   topp) -> None:
        pk, pv, self._last_tokens, self._last_lps = \
            self._prefill_paged_resume_fused(
                self.params, tokens, lengths, rlens, table, row_tables,
                scatter, self.cache["k"], self.cache["v"],
                self._last_tokens, self._last_lps, keys, temp, topk, topp,
            )
        self.cache = self._paged_cache_with(pk, pv)

    # swarmlint: hot
    def _call_paged_ragged_prefill(self, tokens, tok_row, tok_pos, starts,
                                   lens, plens, row_tables, scatter, keys,
                                   temp, topk, topp) -> None:
        k_pool, v_pool, self._last_tokens, self._last_lps = \
            self._prefill_ragged_fused(
                self.params, tokens, tok_row, tok_pos, starts, lens,
                plens, row_tables, scatter, self.cache["k"],
                self.cache["v"], self._last_tokens, self._last_lps, keys,
                temp, topk, topp,
            )
        self.cache = self._paged_cache_with(k_pool, v_pool)

    # swarmlint: hot
    def _call_set_pt_rows(self, rows, vals) -> None:
        from ..ops.paged_kv import set_page_table_rows

        self.cache["page_table"] = set_page_table_rows(
            self.cache["page_table"], rows, vals)

    # swarmlint: hot
    def _call_dense_prefix_prefill(self, tokens, lengths, plens, table,
                                   reg_cols, reg_pages, scatter, keys,
                                   temp, topk, topp) -> None:
        pk, pv = self._prefix_pool
        (self.cache, self._last_tokens, self._last_lps, pk, pv) = (
            self._prefill_prefix_fused(
                self.params, tokens, lengths, plens, table, reg_cols,
                reg_pages, scatter, self.cache, self._last_tokens,
                self._last_lps, pk, pv, keys, temp, topk, topp,
            ))
        self._prefix_pool = (pk, pv)

    _MH_CALLS = {
        CALL_PAGED_PREFILL: _call_paged_prefill,
        CALL_PAGED_PREFIX_PREFILL: _call_paged_prefix_prefill,
        CALL_PAGED_RESUME_PREFILL: _call_paged_resume_prefill,
        CALL_SET_PT_ROWS: _call_set_pt_rows,
        CALL_DENSE_PREFIX_PREFILL: _call_dense_prefix_prefill,
        CALL_PAGED_PREFILL_PACKED: _call_paged_prefill_packed,
        CALL_PAGED_PREFILL_RAGGED: _call_paged_ragged_prefill,
    }

    # swarmprof key per mirrored call (args exclude the call id): the
    # SAME shapes the harvest reads off warmup_call_plan specs, so the
    # runtime key always lands on a harvested variant
    _PROF_MIRRORED = {
        CALL_PAGED_PREFILL:
            lambda a: prof_key("prefill.paged", a[0].shape),
        CALL_PAGED_PREFIX_PREFILL:
            lambda a: prof_key("prefill.paged_prefix", a[0].shape,
                               a[3].shape[1]),
        CALL_PAGED_RESUME_PREFILL:
            lambda a: prof_key("prefill.resume", a[0].shape,
                               a[3].shape[1]),
        CALL_SET_PT_ROWS: lambda a: "table.set_rows",
        CALL_DENSE_PREFIX_PREFILL:
            lambda a: prof_key("prefill.dense_prefix", a[0].shape,
                               a[3].shape[1]),
        CALL_PAGED_PREFILL_PACKED:
            lambda a: prof_key("prefill.packed", a[0].shape),
        CALL_PAGED_PREFILL_RAGGED:
            lambda a: prof_key("prefill.ragged", a[0].shape),
    }

    def restart(self) -> None:
        """Recover from a fatal engine death (SURVEY §5.3 failure
        detection): fail whatever was in flight (callers see
        ``engine_restart`` and the runtime's FAILED/resend machinery takes
        over), rebuild device state, and bring the loop back up.

        Refused in pod mode: worker hosts cannot be told to rebuild their
        shards, so a local restart would silently desynchronize the SPMD
        program — the pod recovers by restarting its processes."""
        if self._mh is not None:
            raise RuntimeError(
                "multi-host engine cannot restart in place; restart the "
                "pod processes (worker state cannot be rebuilt remotely)"
            )
        if self._thread is not None and not self._thread.is_alive():
            self._thread = None
        with self._cv:
            self._stop = False
        # counter first (ADVICE r4 #2): epoch checks racing this restart
        # must fail CLOSED — observing the new epoch with the old pool
        # merely drops reusable state, while the old epoch with a rebuilt
        # pool would bless dangling page ids. (The allocator's own
        # generation stamp — bumped inside reset(), re-validated at
        # submit AND admission — is the authoritative guard; this
        # ordering just keeps the metric-derived view consistent too.)
        self.metrics.counters["engine_restarts"].inc()
        # dump the flight record BEFORE _fail_all mutates slot state: the
        # rings hold the last steps of the DEAD loop, which is exactly
        # the evidence a post-mortem needs (SWARMDB_FLIGHT_DIR or the
        # engine's configured flight_dir; always kept as last_dump too)
        self.flight.auto_dump("engine_restart", self._flight_dir)
        self._fail_all("engine_restart")
        self._last_tokens, self._last_lps = self._fresh_slot_state()
        self.cache = self._fresh_cache()
        if self._prefix is not None:
            # dense: the side pool was donated into the failed dispatch —
            # rebuild it; paged: _fresh_cache rebuilt the main pool. Either
            # way every cached entry now points at zeroed pages: forget all
            if not self.paged:
                self._prefix_pool = self._prefix_init_pool(
                    self._prefix.num_pages, self._prefix_ps)
            self._prefix.reset()
            self._slot_prefix_pins.clear()
        self.start()

    def pool_epoch(self) -> int:
        """Epoch stamp for externally-held page ids (rolling-KV registry):
        the pool's own generation, bumped by every reset — both restart()
        and the in-loop error recovery rebuild the pool through reset, so
        holders can't miss an epoch either way. Paged engines stamp the
        page allocator; dense engines stamp the prefix side pool (its
        acquire() is where dense rolling custody comes from); engines
        with neither have no externally-holdable pages."""
        if self.paged:
            return self.paged.allocator.generation
        if self._prefix is not None:
            return self._prefix.generation
        return self.metrics.counters["engine_restarts"].value

    # ------------------------------------------------------ rolling-KV hooks
    # The serving layer's rolling registry holds page custody between
    # turns; these helpers hide which pool the pages came from (paged main
    # pool vs the dense prefix side pool).

    def supports_rolling(self) -> bool:
        if self.paged is not None:
            return (getattr(self, "_prefill_paged_resume_fused", None)
                    is not None
                    and getattr(self.paged.allocator, "n_shards", 1) <= 1)
        return (self._prefix is not None
                and getattr(self, "_prefill_prefix_fused", None) is not None)

    def rolling_page_size(self) -> int:
        return self.paged.page_size if self.paged else self._prefix_ps

    def rolling_free(self, pages) -> None:
        """Return registry-custody pages to their pool (same-epoch only —
        the caller checks pool_epoch before calling)."""
        if self.paged:
            self.paged.allocator.add_free(list(pages))
        else:
            for p in pages:
                self._prefix.release(p)

    def rolling_free_count(self) -> int:
        if self.paged:
            return self.paged.allocator.free_count()
        return self._prefix.free_count()

    def _device_ctx(self):
        """Placement scope for device-state rebuilds: lane engines
        (ShardLaneGroup) live on ONE specific device, and a recovery
        path that rebuilds the pool under the process default device
        would silently mix devices into the next dispatch."""
        if self._home_device is None:
            import contextlib

            return contextlib.nullcontext()
        return jax.default_device(self._home_device)

    def _fresh_cache(self):
        with self._device_ctx():
            if self.paged:
                self.paged.allocator.reset()
                return self.paged.init_pool()
            return self._prefill_cache_fn(self.max_batch, self.max_seq)

    def warmup(self) -> float:
        """Pre-compile every jitted variant the serving loop can hit and
        return seconds spent (see ``_warmup_impl``). Wraps the compile
        work in a swarmprof suspend/resume bracket: compile stalls must
        not be billed as device time (a 30 s XLA compile would dwarf the
        first MFU window), and the cost-model HARVEST — the one place
        ``lower()``/``cost_analysis()`` may run (swarmlint SWL506) —
        happens here, before serving traffic exists."""
        assert not self._any_active(), "warmup requires an idle engine"
        self._prof.suspend()
        try:
            if not isinstance(self._prof, NullLane):
                try:
                    self.profile_harvest()
                except Exception:
                    logger.exception("swarmprof cost harvest failed")
            return self._warmup_impl()
        finally:
            # resume re-anchors the lane's duty-cycle clock at serving
            # start, so duty = busy / time-since-warmed
            self._prof.resume()

    def profile_harvest(self) -> int:
        """Harvest XLA cost-model facts (FLOPs, bytes accessed) for every
        warmup-plan variant into the process profiler — warmup/compile
        time ONLY (the zero-harvest-post-warmup contract is asserted by
        test and policed by SWL506). ``Lowered.cost_analysis()`` runs the
        cost model on the traced module without compiling or executing,
        so a harvest costs one trace per variant. Lane groups share the
        process registry: the first lane to harvest a variant covers its
        siblings. Returns the number of variants harvested."""
        prof = kernel_profiler()
        try:
            leaf = jax.tree_util.tree_leaves(self.params)[0]
            dev = next(iter(leaf.devices()))
            prof.set_platform(dev.platform,
                              getattr(dev, "device_kind", ""))
        except Exception:  # identity is best-effort (mocked params etc.)
            pass
        fam = self._prof_families()
        harvested = 0
        for fn, specs in self.warmup_call_plan():
            family, tbl = fam.get(id(fn), ("unknown", None))
            if family.startswith(("decode", "resident")):
                key = family
            else:
                ppb = specs[tbl].shape[1] if tbl is not None else None
                key = prof_key(family, specs[1].shape, ppb)
            if prof.harvested(key):
                continue
            ca = None
            try:
                ca = fn.lower(*specs).cost_analysis()
            except Exception:
                logger.debug("cost harvest failed for %s", key,
                             exc_info=True)
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            ca = ca or {}
            meta: Dict[str, Any] = {}
            if self.paged is not None:
                # pool payload dtype joins the variant row so roofline
                # A/Bs (bf16 vs int8 pools) stay like-for-like, and
                # the pool's true HBM price per covered token rides
                # along — XLA's cost model prices the FALLBACK graph
                # (whose dequant materializes f32 pages), not the
                # in-kernel dequant the TPU path runs, so the roofline
                # A/B reads KV traffic off this column instead
                from ..ops.paged_kv import kv_dtype_name, pool_page_bytes

                meta["kv_dtype"] = kv_dtype_name()
                try:
                    ps = int(self.paged.page_size)
                    meta["kv_bytes_per_token"] = (
                        pool_page_bytes(self.cache["k"])
                        + pool_page_bytes(self.cache["v"])) // max(1, ps)
                except Exception:  # stub caches without nbytes
                    pass
            if (family.startswith(("decode", "resident"))
                    and self._decode_kernel is not None):
                # which attention path this program lowers to — the
                # flight-step tag, joined onto the variant row
                meta["kernel"] = self._decode_kernel
            elif family == "prefill.ragged":
                from ..ops.layers import prefill_kernel_choice

                meta["kernel"] = prefill_kernel_choice()
            prof.record_variant(key, ca.get("flops"),
                                ca.get("bytes accessed"), meta or None)
            harvested += 1
        return harvested

    def _prof_families(self) -> Dict[int, Tuple[str, Optional[int]]]:
        """id(jitted fn) -> (profiler family, prefix-table spec index)
        for naming warmup-plan entries; the table index names the spec
        whose trailing dim is the prefix-gather width (a compile axis)."""
        fam: Dict[int, Tuple[str, Optional[int]]] = {}
        for i, fn in enumerate(self._decode_variants):
            fam[id(fn)] = (PROF_DECODE_KEYS[i], None)
        if self._resident_variants is not None:
            for i, fn in enumerate(self._resident_variants):
                fam[id(fn)] = (PROF_RESIDENT_KEYS[i], None)
        for name, family, tbl in (
                ("_prefill_fused", "prefill.dense", None),
                ("_prefill_paged_fused", "prefill.paged", None),
                ("_prefill_paged_packed", "prefill.packed", None),
                ("_prefill_ragged_fused", "prefill.ragged", None),
                ("_prefill_paged_prefix_fused", "prefill.paged_prefix", 4),
                ("_prefill_paged_resume_fused", "prefill.resume", 4),
                ("_prefill_prefix_fused", "prefill.dense_prefix", 4)):
            fn = getattr(self, name, None)
            if fn is not None:
                fam[id(fn)] = (family, tbl)
        return fam

    def _warmup_impl(self) -> float:
        """Pre-compile every jitted variant the serving loop can hit — the
        decode chunk plus one prefill per bucket — and return seconds spent.

        BENCH_r03's 4.8 msg/s collapse was largely compile stalls landing
        inside the measured window: as conversations accumulate history,
        prompts graduate to bigger buckets, and each new bucket's first
        admission paid a 10-30 s XLA compile while every in-flight request
        waited. Call this before serving traffic (no slots may be active:
        warmup reuses the live cache/fed-token buffers through donation,
        which is only safe while every lane is dead).

        Warmup inputs are padding: dense prefill rows scatter to slot id
        ``max_batch`` (mode="drop" discards them); the decode chunk writes
        garbage K/V at positions 0..K-1 of dead lanes, which the
        write-before-read invariant makes unreachable to future occupants.
        With a persistent compilation cache (utils/xla_cache.py) the XLA
        work amortizes across processes, so warmup costs seconds, not
        minutes, after the first run.
        """
        assert not self._any_active(), "warmup requires an idle engine"
        t0 = time.time()
        try:
            parallel = int(os.environ.get("SWARMDB_WARMUP_PARALLEL", "1"))
        except ValueError:
            logger.warning("SWARMDB_WARMUP_PARALLEL=%r is not an int; "
                           "warming up sequentially",
                           os.environ.get("SWARMDB_WARMUP_PARALLEL"))
            parallel = 1
        if parallel > 1:
            # AOT-compile every variant concurrently FIRST: the serialized
            # executables land in the persistent cache, so the sequential
            # jit executions below deserialize in seconds instead of
            # compiling for 30-90 s each (tunneled XLA service). Without
            # the persistent cache the AOT executables would be discarded
            # and everything would compile TWICE — refuse, loudly.
            if jax.config.jax_compilation_cache_dir:
                self.precompile(parallel)
            else:
                logger.warning(
                    "SWARMDB_WARMUP_PARALLEL=%d ignored: persistent "
                    "compile cache is off (set SWARMDB_COMPILE_CACHE), so "
                    "parallel AOT results could not be reused", parallel)
        positions = np.zeros((self.max_batch,), np.int32)
        if self._role_warms_decode():
            for variant, decode in enumerate(self._decode_variants):
                if self._mh is not None:
                    self._mh.publish_decode(variant, positions,
                                            self._base_keys_np, self._temp,
                                            self._topk, self._topp)
                (all_toks, _lps, self._last_tokens, self._last_lps,
                 self.cache) = decode(
                    self.params, self._last_tokens, self._last_lps,
                    positions, self.cache, self._base_keys_np, self._temp,
                    self._topk, self._topp,
                )
                jax.block_until_ready(all_toks)

        if self._use_resident() and self._role_warms_decode():
            # resident-session variants: with live all-False the
            # while_loop body never executes (no emission fires) but the
            # program still compiles; state passes through the donation
            no_live = np.zeros((self.max_batch,), bool)
            for fn in self._resident_variants:
                (_n, self._last_tokens, self._last_lps, self.cache) = fn(
                    self.params, self._last_tokens, self._last_lps,
                    positions, self.cache, self._base_keys_np, self._temp,
                    self._topk, self._topp, positions, no_live,
                    np.int32(0),
                )
            jax.block_until_ready(self._last_tokens)

        Bp = self.prefill_batch
        lengths = np.ones(Bp, np.int32)
        zero_i = np.zeros(Bp, np.int32)
        zero_f = np.zeros(Bp, np.float32)
        ones_f = np.ones(Bp, np.float32)
        keys = self._base_keys_np[np.zeros(Bp, np.int64)]
        if self._ragged_active() and self._role_warms_prefill():
            # packed ragged waves: ONE variant per packed width — every
            # input is padding (dead rows, trash-routed positions)
            R = self.max_batch
            maxp = self.paged.allocator.maxp
            cap = maxp * self.paged.page_size
            for wd in self._ragged_widths:
                self._mirrored(
                    self.CALL_PAGED_PREFILL_RAGGED,
                    np.full(wd, self.pad_id, np.int32),
                    np.full(wd, R, np.int32),
                    np.full(wd, cap, np.int32),
                    np.zeros(R, np.int32),
                    np.zeros(R, np.int32),
                    np.zeros(R, np.int32),
                    np.zeros((R, maxp), np.int32),
                    np.full(R, self.max_batch, np.int32),
                    self._base_keys_np[np.zeros(R, np.int64)],
                    np.zeros(R, np.float32),
                    np.zeros(R, np.int32),
                    np.ones(R, np.float32),
                )
        for bucket in self.prefill_buckets:
            if not self._role_warms_prefill():
                break  # fleet decode lanes admit via resume delta-prefill
            tokens = np.full((Bp, bucket), self.pad_id, np.int32)
            if self.paged:
                if self._ragged_active():
                    # ragged waves replace the bucketed (and prefix)
                    # variants entirely — warmed above
                    continue
                # target page 0 = the trash page (absorbs garbage writes);
                # fed-token rows scatter to max_batch (dropped)
                chunks = -(-bucket // self.paged.page_size)
                if self._packed_active():
                    # sharded engines run the packed variant exclusively
                    # on the plain path — warm it, not the dead GSPMD one
                    _, _, R = self._packed_geometry()
                    self._mirrored(
                        self.CALL_PAGED_PREFILL_PACKED,
                        np.full((R, bucket), self.pad_id, np.int32),
                        np.ones(R, np.int32),
                        np.zeros((R, chunks), np.int32),
                        np.full(R, self.max_batch, np.int32),
                        self._base_keys_np[np.zeros(R, np.int64)],
                        np.zeros(R, np.float32), np.zeros(R, np.int32),
                        np.ones(R, np.float32),
                    )
                else:
                    # one variant per ROW bucket too (lane engines pad
                    # waves to the admission count's bucket, not Bp)
                    for rb in self._row_buckets:
                        self._mirrored(
                            self.CALL_PAGED_PREFILL,
                            np.full((rb, bucket), self.pad_id, np.int32),
                            np.ones(rb, np.int32),
                            np.zeros((rb, chunks), np.int32),
                            np.full(rb, self.max_batch, np.int32),
                            self._base_keys_np[np.zeros(rb, np.int64)],
                            np.zeros(rb, np.float32),
                            np.zeros(rb, np.int32),
                            np.ones(rb, np.float32),
                        )
            else:
                drop = np.full(Bp, self.max_batch, np.int32)
                if self._mh is not None:
                    self._mh.publish_prefill(tokens, lengths, drop, keys,
                                             zero_f, zero_i, ones_f)
                self.cache, self._last_tokens, self._last_lps = \
                    self._prefill_fused(
                        self.params, tokens, lengths, drop, self.cache,
                        self._last_tokens, self._last_lps, keys, zero_f,
                        zero_i, ones_f,
                    )
        if self._prefix is not None:
            # prefix-prefill variants: one per (suffix bucket, PP width).
            # Inputs are pure padding — trash-page gathers, drop-scattered
            # rows, no registration (reg_cols all -1 / trash targets)
            drop = np.full(Bp, self.max_batch, np.int32)
            for bucket in self.prefill_buckets:
                for ppb in self._prefix_pp_buckets:
                    tokens = np.full((Bp, bucket), self.pad_id, np.int32)
                    if self.paged:
                        chunks = -(-bucket // self._prefix_ps)
                        if (not self._ragged_active()
                                and self._role_warms_prefill()):
                            # ragged engines serve cache hits through the
                            # ragged waves (a hit is just a prefix_len);
                            # only the rolling-resume variants below stay
                            for rb in self._row_buckets:
                                self._mirrored(
                                    self.CALL_PAGED_PREFIX_PREFILL,
                                    np.full((rb, bucket), self.pad_id,
                                            np.int32),
                                    np.ones(rb, np.int32),
                                    np.zeros(rb, np.int32),
                                    np.zeros((rb, ppb), np.int32),
                                    np.zeros((rb, chunks), np.int32),
                                    np.full(rb, self.max_batch, np.int32),
                                    self._base_keys_np[np.zeros(rb,
                                                                np.int64)],
                                    np.zeros(rb, np.float32),
                                    np.zeros(rb, np.int32),
                                    np.ones(rb, np.float32),
                                )
                        if self._warm_resume():
                            # rolling-KV resume variants (gated: each is a
                            # 30-90 s compile on the tunneled service and
                            # only SWARMDB_ROLLING_KV deployments hit them)
                            maxp = self.paged.allocator.maxp
                            self._mirrored(
                                self.CALL_PAGED_RESUME_PREFILL, tokens,
                                lengths, np.zeros(Bp, np.int32),
                                np.zeros((Bp, ppb), np.int32),
                                np.zeros((Bp, maxp), np.int32), drop,
                                keys, zero_f, zero_i, ones_f,
                            )
                        continue
                    if not self._role_warms_prefill():
                        continue
                    lane_pages = min(ppb + -(-bucket // self._prefix_ps),
                                     self.max_seq // self._prefix_ps)
                    self._mirrored(
                        self.CALL_DENSE_PREFIX_PREFILL, tokens, lengths,
                        np.zeros(Bp, np.int32),
                        np.zeros((Bp, ppb), np.int32),
                        np.full((Bp, lane_pages), -1, np.int32),
                        np.zeros((Bp, lane_pages), np.int32),
                        drop, keys, zero_f, zero_i, ones_f,
                    )
        jax.block_until_ready(self._last_tokens)
        dt = time.time() - t0
        self.metrics.latencies["warmup_s"].observe(dt)
        logger.info("engine warmup compiled %d prefill buckets + decode "
                    "chunk in %.1fs", len(self.prefill_buckets), dt)
        return dt

    def _paged_cache_with(self, k_pool, v_pool):
        """Rebuild the paged cache dict around new k/v pools, carrying
        every non-pool field (page_table, pos0) — ONE site instead of a
        hand-maintained key list at each fused-dispatch return (a
        forgotten key is a KeyError that kills the decode loop)."""
        out = dict(self.cache)
        out["k"] = k_pool
        out["v"] = v_pool
        return out

    def _packed_active(self) -> bool:
        """Whether the PLAIN paged path runs the shard-packed
        collective-free prefill. ONE gate shared by warmup(),
        warmup_call_plan() and _prefill_batch — the three must agree or
        warmup compiles a dead variant while the serving path pays a
        cold compile mid-traffic (same contract as _warm_resume)."""
        return (self.paged is not None
                and getattr(self, "_prefill_paged_packed", None) is not None
                and getattr(self.paged.allocator, "n_shards", 1) > 1)

    def _packed_geometry(self):
        """(n_shards, rows_per_shard, total_rows) of a packed wave. A
        wave holds at most min(prefill_batch, slots_per_shard) DISTINCT
        slots of any one shard (slot ids are unique per wave), so each
        block is sized to that — not to prefill_batch, which would run
        up to slots_per/Bp-fold wasted forward FLOPs per device."""
        n_sh = self.paged.allocator.n_shards
        rows_per = max(1, min(self.prefill_batch, self.max_batch // n_sh))
        return n_sh, rows_per, n_sh * rows_per

    def _ragged_active(self) -> bool:
        """Whether paged admission runs PACKED RAGGED waves (one
        no-padding token stream per wave, prefix KV read in place)
        instead of row-bucketed dense-bucket prefills. ONE gate shared
        by warmup(), warmup_call_plan() and _admit — the same
        agree-or-cold-compile contract as _packed_active. Off when the
        model has no ragged forward, on sharded pools (the shard-packed
        path owns those), or under SWARMDB_RAGGED_PREFILL=0."""
        return (self._prefill_ragged_fused is not None
                and not self._packed_active())

    def _ragged_width_for(self, n: int) -> int:
        """Largest packed-width bucket <= ``n`` — waves peel off the
        ladder top-down, so every wave is EXACTLY full (zero padding)
        until the remainder drops below the smallest rung; that final
        flush pads by < min_width tokens."""
        for w in reversed(self._ragged_widths):
            if w <= n:
                return w
        return self._ragged_widths[0]

    def _role_warms_decode(self) -> bool:
        """Whether this lane's warmup covers the decode-side variants
        (decode chunk, resident sessions). ONE gate shared by warmup()
        and warmup_call_plan() — same agree-or-drift contract as
        _packed_active. Fleet PREFILL lanes skip them."""
        return self._role != "prefill"

    def _role_warms_prefill(self) -> bool:
        """Whether this lane's warmup covers the admission-side prefill
        variants (ragged/packed/bucketed + prefix). Fleet DECODE lanes
        skip them — their only admission path is the rolling-resume
        delta-prefill, which _warm_resume covers."""
        return self._role != "decode"

    def _warm_resume(self) -> bool:
        """Whether warmup covers the rolling-KV resume variants (paged +
        prefix engines, SWARMDB_ROLLING_KV deployments only — plus fleet
        DECODE lanes, whose admission path IS the resume delta-prefill).
        ONE gate shared by warmup() and warmup_call_plan() — they must
        agree or the precompile drift test fails."""
        if self._role == "prefill":
            return False
        return (self.paged is not None
                and getattr(self, "_prefill_paged_resume_fused", None)
                is not None
                and (os.environ.get("SWARMDB_ROLLING_KV") == "1"
                     or self._role == "decode"))

    def warmup_call_plan(self) -> List[Tuple[Any, Tuple[Any, ...]]]:
        """(jitted fn, ShapeDtypeStruct args) for every variant warmup()
        executes — the decode chunk x3 samplers, one prefill per bucket,
        and one prefix prefill per (bucket, PP width). Must mirror
        warmup()'s calls exactly — drift is caught end-to-end by
        `test_precompile_cache_covers_warmup`, which asserts a
        precompiled engine's warmup adds ZERO new persistent-cache
        entries (any shape/dtype/arg-order/donation mismatch shows up
        as a fresh compile)."""
        from jax.sharding import NamedSharding

        def sds(shape, dtype, a=None):
            # mesh-placed device state must carry its NamedSharding into
            # the spec: lowering without it compiles a DIFFERENT program
            # than the eager call on sharded engines, so precompile would
            # populate the persistent cache with executables warmup (and
            # serving) never hit (review r5 drift-guard finding)
            sh = getattr(a, "sharding", None)
            if isinstance(sh, NamedSharding):
                return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)
            return jax.ShapeDtypeStruct(shape, dtype)

        def spec(x):
            return jax.tree.map(lambda a: sds(a.shape, a.dtype, a), x)

        B, Bp = self.max_batch, self.prefill_batch
        params_s, cache_s = spec(self.params), spec(self.cache)
        lt_s = spec(self._last_tokens)
        llp_s = spec(self._last_lps)
        keys_B = spec(self._base_keys_np)
        key_dt = self._base_keys_np.dtype
        f32_B, i32_B = sds((B,), np.float32), sds((B,), np.int32)
        plan: List[Tuple[Any, Tuple[Any, ...]]] = []
        if self._role_warms_decode():
            for decode in self._decode_variants:
                plan.append((decode, (params_s, lt_s, llp_s, i32_B,
                                      cache_s, keys_B, f32_B, i32_B,
                                      f32_B)))
        if self._use_resident() and self._role_warms_decode():
            # resident sessions carry host callbacks, which jax refuses
            # to serialize into the persistent cache — the AOT compile
            # still validates the specs, and warmup's jit execution adds
            # zero persistent entries either way (drift test invariant)
            bool_B = sds((B,), np.bool_)
            for fn in self._resident_variants:
                plan.append((fn, (params_s, lt_s, llp_s, i32_B, cache_s,
                                  keys_B, f32_B, i32_B, f32_B, i32_B,
                                  bool_B, sds((), np.int32))))

        keys_Bp = sds((Bp,) + self._base_keys_np.shape[1:], key_dt)
        i32_Bp, f32_Bp = sds((Bp,), np.int32), sds((Bp,), np.float32)
        if self._ragged_active() and self._role_warms_prefill():
            maxp = self.paged.allocator.maxp
            keys_R = sds((B,) + self._base_keys_np.shape[1:], key_dt)
            for wd in self._ragged_widths:
                w_i32 = sds((wd,), np.int32)
                plan.append((self._prefill_ragged_fused, (
                    params_s, w_i32, w_i32, w_i32, i32_B, i32_B, i32_B,
                    sds((B, maxp), np.int32), i32_B, cache_s["k"],
                    cache_s["v"], lt_s, llp_s, keys_R, f32_B, i32_B,
                    f32_B)))
        for bucket in self.prefill_buckets:
            if not self._role_warms_prefill():
                break  # fleet decode lanes admit via resume delta-prefill
            tok = sds((Bp, bucket), np.int32)
            if self.paged:
                if self._ragged_active():
                    continue
                chunks = -(-bucket // self.paged.page_size)
                if self._packed_active():
                    _, _, R = self._packed_geometry()
                    keys_R = sds((R,) + self._base_keys_np.shape[1:],
                                 key_dt)
                    plan.append((self._prefill_paged_packed, (
                        params_s, sds((R, bucket), np.int32),
                        sds((R,), np.int32), sds((R, chunks), np.int32),
                        sds((R,), np.int32), cache_s["k"], cache_s["v"],
                        lt_s, llp_s, keys_R, sds((R,), np.float32),
                        sds((R,), np.int32), sds((R,), np.float32))))
                    continue
                for rb in self._row_buckets:
                    keys_rb = sds((rb,) + self._base_keys_np.shape[1:],
                                  key_dt)
                    i32_rb, f32_rb = (sds((rb,), np.int32),
                                      sds((rb,), np.float32))
                    plan.append((self._prefill_paged_fused, (
                        params_s, sds((rb, bucket), np.int32), i32_rb,
                        sds((rb, chunks), np.int32), i32_rb,
                        cache_s["k"], cache_s["v"], lt_s, llp_s,
                        keys_rb, f32_rb, i32_rb, f32_rb)))
            else:
                plan.append((self._prefill_fused, (
                    params_s, tok, i32_Bp, i32_Bp, cache_s, lt_s, llp_s,
                    keys_Bp, f32_Bp, i32_Bp, f32_Bp)))
        if self._prefix is not None:
            for bucket in self.prefill_buckets:
                for ppb in self._prefix_pp_buckets:
                    tok = sds((Bp, bucket), np.int32)
                    table = sds((Bp, ppb), np.int32)
                    if self.paged:
                        chunks = -(-bucket // self._prefix_ps)
                        if (not self._ragged_active()
                                and self._role_warms_prefill()):
                            for rb in self._row_buckets:
                                keys_rb = sds(
                                    (rb,) + self._base_keys_np.shape[1:],
                                    key_dt)
                                i32_rb, f32_rb = (sds((rb,), np.int32),
                                                  sds((rb,), np.float32))
                                plan.append(
                                    (self._prefill_paged_prefix_fused, (
                                        params_s,
                                        sds((rb, bucket), np.int32),
                                        i32_rb, i32_rb,
                                        sds((rb, ppb), np.int32),
                                        sds((rb, chunks), np.int32),
                                        i32_rb, cache_s["k"],
                                        cache_s["v"], lt_s, llp_s,
                                        keys_rb, f32_rb, i32_rb, f32_rb)))
                        if self._warm_resume():
                            maxp = self.paged.allocator.maxp
                            plan.append((self._prefill_paged_resume_fused, (
                                params_s, tok, i32_Bp, i32_Bp, table,
                                sds((Bp, maxp), np.int32), i32_Bp,
                                cache_s["k"], cache_s["v"], lt_s, llp_s,
                                keys_Bp, f32_Bp, i32_Bp, f32_Bp)))
                    elif self._role_warms_prefill():
                        lane_pages = min(ppb + -(-bucket // self._prefix_ps),
                                         self.max_seq // self._prefix_ps)
                        reg = sds((Bp, lane_pages), np.int32)
                        plan.append((self._prefill_prefix_fused, (
                            params_s, tok, i32_Bp, i32_Bp, table, reg, reg,
                            i32_Bp, cache_s, lt_s, llp_s,
                            spec(self._prefix_pool[0]),
                            spec(self._prefix_pool[1]),
                            keys_Bp, f32_Bp, i32_Bp, f32_Bp)))
        return plan

    def precompile(self, parallel: int = 4) -> float:
        """AOT-compile every warmup variant with ``parallel`` threads and
        return seconds spent. Compilation releases the GIL (XLA C++ /
        the remote compile service), so independent variants overlap;
        with the persistent cache on (utils/xla_cache.py) each compiled
        executable is serialized to disk, and warmup()'s subsequent jit
        executions — and any serving-path call — deserialize it instead
        of recompiling. Pure compile: nothing executes on the device, so
        engine state (cache donation lifecycle included) is untouched."""
        t0 = time.time()
        plan = self.warmup_call_plan()

        def lower_one(item):
            fn, specs = item
            fn.lower(*specs).compile()

        if parallel > 1:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=parallel) as ex:
                # surface the first failure instead of swallowing it
                list(ex.map(lower_one, plan))
        else:
            for item in plan:
                lower_one(item)
        dt = time.time() - t0
        logger.info("precompiled %d variants with %d threads in %.1fs",
                    len(plan), parallel, dt)
        return dt

    # ------------------------------------------------------------ submission

    def submit(self, request: GenRequest) -> str:
        """Thread-safe enqueue; returns the request id."""
        if request.resume_len + len(request.prompt) >= self.max_seq:
            raise ValueError(
                f"prompt length {request.resume_len + len(request.prompt)} "
                f"(incl. resumed) >= max_seq {self.max_seq}"
            )
        if request.resume_pages is not None:
            if not self.supports_rolling():
                raise ValueError("resume_pages requires the rolling-KV "
                                 "machinery (paged+resume prefill, or a "
                                 "dense engine with the prefix cache)")
            if self._mh is not None:
                # pod mode mirrors the resume DISPATCH fine (CALL_PAGED_
                # RESUME_PREFILL), but page custody lives in the serving
                # layer's registry, and a pod failure recovers by process
                # restart — which silently orphans/aliases every resumed
                # page id. Refuse until registry state is pod-durable.
                raise ValueError("rolling-KV resume is not supported in "
                                 "multi-host (pod) mode")
            if not request.resume_pages or request.resume_len <= 0:
                raise ValueError("resume needs pages and resume_len > 0")
            ps = self.rolling_page_size()
            if len(request.resume_pages) > self._prefix_pp_buckets[-1]:
                raise ValueError(
                    f"{len(request.resume_pages)} resume pages exceed the "
                    f"widest prefix-gather bucket "
                    f"{self._prefix_pp_buckets[-1]}")
            if -(-request.resume_len // ps) != len(request.resume_pages):
                raise ValueError("resume_pages must exactly cover "
                                 "resume_len")
            if (request.resume_epoch is not None
                    and request.resume_epoch != self.pool_epoch()):
                raise ValueError(
                    "stale resume epoch: the page pool was rebuilt since "
                    "these pages were planned (engine restart); the "
                    "conversation must restart fresh"
                )
        if request.keep_pages and self._mh is not None:
            # the dense keep-retirement extraction (_extract_lane_fused)
            # is not a mirrored call, so it would silently desync worker
            # prefix pools; and kept custody is useless in a pod anyway
            # (resume is refused above). Refuse symmetrically (review r5).
            raise ValueError("rolling-KV keep_pages is not supported in "
                             "multi-host (pod) mode")
        if self.paged:
            need = self.paged.allocator.pages_needed(
                len(request.prompt), request.sampling.max_new_tokens,
                self.decode_chunk,
            )
            # per-SLOT capacity, not the global pool: a DP-sharded slot can
            # only draw from its own shard's sub-pool, and an uncoverable
            # request at the queue head wedges the no-skip-ahead admission
            # forever (review finding)
            cap = self.paged.allocator.slot_capacity()
            if need > cap:
                raise ValueError(
                    f"request needs {need} KV pages but a slot can hold at "
                    f"most {cap}; raise num_pages or shorten"
                )
        with self._cv:
            heapq.heappush(
                self._queue,
                (-request.priority, request.submitted_at,
                 next(self._tiebreak), request),
            )
            self.metrics.counters["engine_requests"].inc()
            self._cv.notify_all()
        return request.request_id

    def cancel(self, request_id: str) -> bool:
        """Stop a request early (client disconnect, stop-sequence match).

        Queued requests are removed immediately (their ``on_done`` fires
        with reason "cancelled"); an ACTIVE request's slot is flagged and
        retires when the engine processes its next token block — its lane
        computes at most one more chunk of garbage, exactly like a natural
        EOS mid-chunk. Returns False for unknown/finished ids (cancel of a
        completed request is a no-op, not an error — the races are
        inherent). Thread-safe."""
        with self._cv:
            for i, item in enumerate(self._queue):
                if item[3].request_id == request_id:
                    req = item[3]
                    del self._queue[i]
                    heapq.heapify(self._queue)
                    break
            else:
                req = None
            if req is None:
                if request_id in self._admitting:
                    # popped but not yet activated (prefill in flight, can
                    # take seconds on a cold compile): flag for _activate
                    self._cancel_pending.add(request_id)
                    self.metrics.counters["engine_cancelled"].inc()
                    return True
                for slot in self.slots:
                    if (slot.active and slot.request is not None
                            and slot.request.request_id == request_id):
                        slot.cancelled = True
                        self.metrics.counters["engine_cancelled"].inc()
                        return True
                return False
        # queued removal: fire completion outside the lock (callbacks may
        # re-enter submit()/stats())
        self.metrics.counters["engine_cancelled"].inc()
        if req.on_done is not None:
            try:
                req.on_done(req.request_id, [], "cancelled")
            except Exception:
                logger.exception("on_done callback failed")
        return True

    def generate_sync(self, prompt: List[int], sampling: SamplingParams,
                      timeout: float = 120.0) -> Tuple[List[int], str]:
        """Blocking convenience API (tests, benches)."""
        done = threading.Event()
        result: Dict[str, Any] = {}

        def on_done(rid, toks, reason):
            result["tokens"] = toks
            result["reason"] = reason
            done.set()

        self.submit(GenRequest(prompt=prompt, sampling=sampling, on_done=on_done))
        if not done.wait(timeout):
            raise TimeoutError("generation timed out")
        return result["tokens"], result["reason"]

    # ------------------------------------------------------------- the loop

    def _run(self) -> None:  # swarmlint: hot
        # (token block, logprob block, snapshot, dispatch stamp, decode
        # variant) per chunk
        in_flight: List[Tuple[Any, Any, List[Tuple[int, GenRequest, int]],
                              int, int]] = []
        while True:
            self._in_step = False
            self._beat()
            with self._cv:
                while (not self._stop and not self._queue
                       and not self._any_active() and not in_flight
                       and not self._chaos_pending()):
                    # idle engines must still beat or the supervisor
                    # would read "no work" as "wedged"; the tick bounds
                    # idle beat staleness well under any sane suspect
                    # threshold. An armed chaos fault exits the wait so
                    # it lands at the seam below (outside the lock) even
                    # on an idle lane.
                    self._beat()
                    self._cv.wait(timeout=0.25)
                stopping = self._stop
            if stopping:
                # drain dispatched chunks so their requests complete
                # instead of hanging to their callers' timeouts — OUTSIDE
                # the lock: processing blocks on the device and runs user
                # callbacks, either of which under _cv could deadlock a
                # thread re-entering submit()/stop()
                for entry in in_flight:
                    try:
                        self._process_block(*entry)
                    except Exception:
                        logger.exception("drain on stop failed")
                in_flight.clear()
                break
            cs = self.chaos_step
            if cs is not None:
                # fault-injection seam (backend/chaos.py): kill raises
                # LaneKilled (BaseException — deliberately NOT caught by
                # the recovery handler below, the thread dies); wedge
                # blocks here, starving the liveness beat
                cs(self)
            self._in_step = True
            try:
                self._admit()
                if self._role == "prefill":
                    # fleet prefill lanes retire admission-only requests
                    # straight off the prefill sample — decode never runs
                    # for them, so the lane's whole duty is prefill waves
                    self._drain_prefill_only()
                if self._use_resident():
                    # device-resident session: the while_loop runs chunks
                    # until all lanes finish or the host votes to stop
                    # (admissible work / cancel) through the emission
                    # ring's callback return; ONE host sync per session.
                    # The flight step is recorded POST-admission, PRE-
                    # session: a session boundary is the one moment
                    # occupancy is transiently low (retired slots not yet
                    # refilled), and sampling only there would read a
                    # fully-loaded engine as stalled-with-free-slots
                    # (admission_stall_frac would be garbage).
                    self._flight_step(0)
                    if self._any_active():
                        self._run_resident()
                    continue
                if self._any_active():
                    in_flight.append(self._dispatch_decode())
                while in_flight and (len(in_flight) >= self.pipeline_depth
                                     or not self._any_active()):
                    self._process_block(*in_flight.pop(0))
                self._flight_step(len(in_flight))
            except Exception:
                in_flight.clear()
                logger.exception("engine step failed; failing active requests")
                self.flight.auto_dump("engine_error", self._flight_dir)
                self._fail_all("engine_error")
                if self._mh is not None:
                    # Pod mode: workers may have executed an op this
                    # coordinator failed mid-way, and a local state rebuild
                    # cannot be mirrored to them (their cache would silently
                    # diverge and corrupt every later TP/EP reduction).
                    # Fail the pod loudly; recovery is a process restart.
                    logger.error("multi-host engine failure is fatal; "
                                 "stopping the pod decode program")
                    with self._cv:
                        self._stop = True
                    try:
                        self._mh.publish_stop()
                    except Exception:
                        logger.exception("pod stop broadcast failed")
                    # workers have exited their loop: a second stop
                    # broadcast from Engine.stop() would be a collective
                    # with no peers and hang shutdown
                    self._mh = None
                    break
                # the decode step donates the cache buffer (and the fed-token
                # vector is donated through _set_last_token): if it raised
                # mid-step they may reference deleted buffers — rebuild both
                # so the engine survives the error
                try:
                    with self._device_ctx():
                        self._last_tokens = jnp.zeros((self.max_batch,),
                                                      jnp.int32)
                        self._last_lps = jnp.zeros((self.max_batch,),
                                                   jnp.float32)
                    self.cache = self._fresh_cache()
                    if self._prefix is not None:
                        # the rebuilt pool is zeroed and (paged) its pages
                        # are back on the free list: stale chain entries
                        # would hit zeroed or REUSED pages — forget all
                        # (mirrors restart())
                        if not self.paged:
                            self._prefix_pool = self._prefix_init_pool(
                                self._prefix.num_pages, self._prefix_ps)
                        self._prefix.reset()
                        self._slot_prefix_pins.clear()
                except Exception:
                    logger.exception("cache re-init failed; stopping engine")
                    with self._cv:
                        self._stop = True

    def _any_active(self) -> bool:
        return any(s.active for s in self.slots)

    def _compiled_count(self) -> int:
        """Total compiled-executable count across the engine's jit entry
        points (jax's per-wrapper cache sizes). A step-over-step increase
        in the flight record is a RECOMPILE landing mid-traffic — the
        exact stall class warmup exists to prevent."""
        fns: List[Any] = list(self._decode_variants)
        if self._resident_variants is not None:
            fns.extend(self._resident_variants)
        for name in ("_prefill_fused", "_prefill_paged_fused",
                     "_prefill_paged_packed", "_prefill_paged_prefix_fused",
                     "_prefill_paged_resume_fused", "_prefill_prefix_fused",
                     "_prefill_ragged_fused", "_extract_lane_fused"):
            fn = getattr(self, name, None)
            if fn is not None:
                fns.append(fn)
        n = 0
        for fn in fns:
            size = getattr(fn, "_cache_size", None)
            if callable(size):
                try:
                    n += int(size())
                except Exception:  # private API; absence is not an error
                    pass
        return n

    def _flight_step(self, in_flight_n: int) -> None:  # swarmlint: hot
        """One flight-recorder step record per engine-loop iteration that
        has work (idle iterations are skipped so the ring's last-N steps
        describe the crash window, not hours of quiet)."""
        if self.sentinel is not None:
            # window-close probe: one compare per engine step (the close
            # itself is rare and runs off the sentinel's own snapshot)
            self.sentinel.maybe_tick()
        with self._cv:
            queued = len(self._queue)
            by_prio: Dict[int, int] = {}
            for negp, _, _, _ in self._queue:
                by_prio[-negp] = by_prio.get(-negp, 0) + 1
        active = sum(1 for s in self.slots if s.active)
        has_work = bool(active or queued or in_flight_n)
        if not has_work and not self._flight_last_had_work:
            return
        # one trailing record after work drains: the ring's final step
        # then carries the SETTLED counters (a dump taken while idle
        # matches the metrics registry exactly)
        self._flight_last_had_work = has_work
        c = self.metrics.counters
        rec: Dict[str, Any] = {
            "ts": time.time(),
            "active": active,
            "max_batch": self.max_batch,
            "queued": queued,
            "queued_by_priority": by_prio,
            "in_flight_chunks": in_flight_n,
            # cumulative counters: deltas between steps localize where
            # tokens/padding/syncs happened in time
            "tokens_generated": c["tokens_generated"].value,
            "prompt_tokens": c["prompt_tokens"].value,
            "prefill_padding_tokens": c["prefill_padding_tokens"].value,
            "prefill_packed_tokens": c["prefill_packed_tokens"].value,
            "host_syncs": c["engine_host_syncs"].value,
            "restarts": c["engine_restarts"].value,
            "compiled_variants": self._compiled_count(),
        }
        if self._last_wave_kind is not None:
            # which prefill family served the most recent wave (ragged
            # packed stream vs bucketed dense batch)
            rec["wave_kind"] = self._last_wave_kind
        if self._decode_kernel is not None:
            # which decode-attention path serves this engine (pallas
            # kernel vs XLA page gather) — the analyzer needs it to
            # attribute kernel-vs-gather regressions across records
            rec["decode_kernel"] = self._decode_kernel
        if self._use_resident():
            # evidence-quality marker for the analyzer's stall split:
            # resident-path steps sample occupancy right AFTER admission
            # (the loop records pre-session), so active-vs-queued is a
            # trustworthy admission-stall signal; scan-path steps sample
            # mid-pipeline and stay unmarked (analyze._queue_split only
            # trusts marked dumps)
            rec["occ_at_admit"] = True
        if self.flight_shard is not None:
            rec["shard"] = self.flight_shard
        if self._prefix is not None:
            ps = self._prefix.stats()
            rec["prefix_hit_tokens"] = ps["hit_tokens"]
            rec["prefix_miss_tokens"] = ps["miss_tokens"]
        if (self.paged is not None
                and getattr(self.paged.allocator, "n_shards", 1) > 1):
            # DP-sharded pool: per-shard occupancy — the dpx=0.22 class
            # of mystery is usually one starved/overloaded shard
            shard_of = self.paged.allocator.shard_of
            by_shard: Dict[int, int] = {}
            for i, s in enumerate(self.slots):
                if s.active:
                    sh = shard_of(i)
                    by_shard[sh] = by_shard.get(sh, 0) + 1
            rec["active_by_shard"] = by_shard
        self.flight.record_step(rec)

    def _age_queue(self) -> None:  # swarmlint: hot
        """Bounded anti-starvation for priority admission (BENCH_r05
        diagnosis): the heap ORDERING — (-priority, submitted_at,
        tiebreak) — is correct, but under a saturating arrival stream
        strict priority leaves LOW waiting unboundedly (p50 TTFT 13.55 s
        vs 2.62 s for CRITICAL on the swarm100 closed loop; the request
        timelines show the whole gap is queue wait). Every ``aging_s``
        seconds a request waits, it COMPETES one priority class higher —
        the effective class is recomputed from wait time (idempotent
        across passes; ``req.priority`` itself is never mutated) and ties
        within a class still break on ``submitted_at``, so an aged LOW
        outranks younger requests of its effective class. Wait is thus
        bounded by ~(3 - priority) * aging_s + the class-3 backlog."""
        if self._aging_s <= 0:
            return
        now = time.time()
        with self._cv:
            if not self._queue:
                return
            changed = False
            for i, (negp, sub, tb, req) in enumerate(self._queue):
                boost = int((now - sub) / self._aging_s)
                if boost <= 0:
                    continue
                eff = min(3, req.priority + boost)
                if eff > -negp:
                    self._queue[i] = (-eff, sub, tb, req)
                    changed = True
            if changed:
                heapq.heapify(self._queue)
                self.metrics.counters["engine_priority_aged"].inc()

    def _free_slot_ids(self) -> List[int]:  # swarmlint: hot
        free = [i for i, s in enumerate(self.slots) if not s.active]
        if (free and self.paged is not None
                and getattr(self.paged.allocator, "n_shards", 1) > 1):
            # DP-sharded pool: id-order admission would pile every light-
            # load request onto shard 0 (slot->shard affinity binds a
            # slot's pages to its shard's SUB-pool), exhausting one
            # sub-pool while the others sit empty. Interleave the free
            # list across shards — rotated by an admission counter so a
            # strictly SERIAL stream (slot 0 always free again by the
            # next admission) also spreads, instead of re-landing every
            # request and its prefix-cache registrations on shard 0.
            alloc = self.paged.allocator
            by_shard: Dict[int, List[int]] = {}
            for i in free:
                by_shard.setdefault(alloc.shard_of(i), []).append(i)
            lanes = list(by_shard.values())
            rot = self._admit_rr % len(lanes)
            self._admit_rr += 1
            lanes = lanes[rot:] + lanes[:rot]
            free = [lane[k] for k in range(max(map(len, lanes)))
                    for lane in lanes if k < len(lane)]
        return free

    # ------------------------------------------------------------- admission

    def _expire_deadlines(self) -> None:  # swarmlint: hot
        """Fail QUEUED requests whose deadline already passed with reason
        "deadline" (final, not retryable): serving them would stream into
        a client that stopped waiting, and admitting them burns pool
        pages higher-priority live requests need. In-flight requests are
        never cut mid-stream — the supervisor's deadline watch cancels
        those at chunk granularity."""
        now = time.time()
        expired: List[GenRequest] = []
        with self._cv:
            if not self._queue:
                return
            keep = []
            for item in self._queue:
                req = item[3]
                if req.deadline is not None and now > req.deadline:
                    expired.append(req)
                else:
                    keep.append(item)
            if expired:
                self._queue[:] = keep
                heapq.heapify(self._queue)
        for req in expired:
            self.metrics.counters["requests_deadline_expired"].inc()
            if req.on_done is not None:
                try:
                    req.on_done(req.request_id, [], "deadline")
                except Exception:
                    logger.exception("on_done callback failed")

    def _pool_headroom(self) -> float:
        """Fraction of the page pool still claimable by admission: free
        pages plus UNPINNED prefix-cache pages (the cache fills the pool
        by design — counting cached-but-evictable pages as used would
        read a healthy warm cache as pressure)."""
        free = self.paged.allocator.free_count()
        if self._prefix is not None:
            free += self._prefix.evictable_count()
        cap = max(1, self.paged.num_pages - 1)  # page 0 is trash
        return min(1.0, free / cap)

    def _backpressure_gate(self) -> bool:  # swarmlint: hot
        """Watermark hysteresis over pool utilization; returns True when
        admission may proceed. Paused admission still reclaims retired
        pages (the caller runs the pending-free flush first) and still
        fires the pool-pressure hook, so parked rolling conversations
        get evicted instead of deadlocking the pause."""
        if self.paged is None or self._bp_high >= 1.0:
            return True
        util = 1.0 - self._pool_headroom()
        # tiered-KV demote band (ISSUE 19): same hysteresis shape as the
        # pause band but one rung lower — start spilling cold
        # conversations to the warm tier BEFORE admission pauses, stop
        # once utilization falls back under the low watermark. The hook
        # only signals the tier worker (no device work in the gate).
        if self.on_tier_pressure is not None and self._bp_demote < 1.0:
            if self._tier_demoting:
                if util <= self._bp_low:
                    self._tier_demoting = False
            elif util >= self._bp_demote:
                self._tier_demoting = True
                self.tracer.instant("tier.pressure", cat="engine",
                                    args={"util": round(util, 3)})
            if self._tier_demoting:
                cap = max(1, self.paged.num_pages - 1)
                need = max(1, int((util - self._bp_low) * cap))
                try:
                    self.on_tier_pressure(need)
                except Exception:
                    logger.exception("tier-pressure callback failed")
        if self._bp_paused:
            if util <= self._bp_low:
                self._bp_paused = False
                self.metrics.counters["engine_admission_resumed"].inc()
                self.flight.record_event(
                    {"kind": "pool.backpressure_resumed",
                     "util": round(util, 3), "shard": self.flight_shard})
                return True
        elif util >= self._bp_high:
            self._bp_paused = True
            self.metrics.counters["engine_admission_paused"].inc()
            self.flight.record_event(
                {"kind": "pool.backpressure_paused",
                 "util": round(util, 3), "shard": self.flight_shard})
            self.tracer.instant("pool.backpressure", cat="engine",
                                args={"util": round(util, 3)})
        if not self._bp_paused:
            return True
        # paused: free what can be freed, shed what must be shed
        if self.on_pool_pressure is not None:
            cap = max(1, self.paged.num_pages - 1)
            need = max(1, int((util - self._bp_low) * cap))
            try:
                self.on_pool_pressure(need)
            except Exception:
                logger.exception("pool-pressure callback failed")
        if util >= self._bp_shed:
            self._shed_lowest()
        return False

    def _shed_lowest(self) -> None:  # swarmlint: hot
        """Past the hard watermark: return the lowest-priority queued
        class with retryable reason "shed" so the higher classes drain
        the remaining pool first. Priority-aware by construction — a
        homogeneous queue sheds nothing (there is no lower-priority work
        to sacrifice; deadlines bound those waits instead)."""
        shed: List[GenRequest] = []
        with self._cv:
            if len(self._queue) < 2:
                return
            prios = {-negp for negp, _, _, _ in self._queue}
            if len(prios) < 2:
                return
            lowest = min(prios)
            keep = []
            for item in self._queue:
                if -item[0] == lowest:
                    shed.append(item[3])
                else:
                    keep.append(item)
            self._queue[:] = keep
            heapq.heapify(self._queue)
        for req in shed:
            self.metrics.counters["requests_shed"].inc()
            self.flight.record_event(
                {"kind": "pool.request_shed", "rid": req.request_id,
                 "priority": req.priority, "shard": self.flight_shard})
            if req.on_done is not None:
                try:
                    req.on_done(req.request_id, [], "shed")
                except Exception:
                    logger.exception("on_done callback failed")

    def _admit(self) -> None:  # swarmlint: hot
        """Move queued requests into free slots (highest priority first) and
        run their prefill in groups of up to ``prefill_batch``.

        Groups are split by bucket so a short prompt co-admitted with a
        long one never pays the long bucket's O(T^2) attention (review
        finding); every popped request is still admitted this round.
        """
        self._age_queue()
        self._expire_deadlines()
        if self.paged:
            # reclaim retired slots' pages first: zero their table rows on
            # device (mirrored to pod workers), THEN return pages to the
            # pool (stale-table/reuse race)
            pending = self.paged.allocator.take_pending_frees()
            if pending:
                freed_pages: List[int] = []
                if self._pagecheck is not None:
                    for sid in pending:
                        freed_pages.extend(
                            self.paged.allocator.pages_for(sid))
                try:
                    self._mirrored(
                        self.CALL_SET_PT_ROWS,
                        np.asarray(pending, np.int32),
                        np.zeros((len(pending),
                                  self.paged.allocator.maxp), np.int32),
                    )
                except Exception:
                    # dispatch failed before the rows were zeroed:
                    # freeing would reopen the stale-table race,
                    # dropping the drained batch would leak its pages
                    # forever (swarmlint SWL801) — requeue and let the
                    # engine's error recovery run, the next admission
                    # round retries the reclaim
                    self.paged.allocator.requeue_pending(pending)
                    raise
                self.paged.allocator.release_taken(pending)
                if self._pagecheck is not None and freed_pages:
                    self._pagecheck_poison(freed_pages)
            if self.on_tier_drain is not None:
                # tiered KV (ISSUE 19): execute the tier worker's planned
                # demotions here — the D2H gathers ride the flush wave
                # this round already syncs on, never the decode hot path
                try:
                    self.on_tier_drain()
                except Exception:
                    logger.exception("tier drain failed")
            if not self._backpressure_gate():
                return
        pressure_called = False
        while True:
            stale_resumes: List[GenRequest] = []
            pressure_need = 0
            with self._cv:
                free = self._free_slot_ids()
                take = min(len(free), len(self._queue), self.prefill_batch)
                if take == 0:
                    return
                if self.paged:
                    # admit in priority order while the pool covers each
                    # request's worst-case page footprint; stop at the first
                    # that doesn't fit (no skip-ahead: prevents starvation
                    # of long prompts behind a stream of short ones). With
                    # the prefix cache, hit pages are pinned and referenced
                    # in place; only the remainder needs fresh pages, and
                    # LRU cache pages are evicted into the free list when
                    # the pool runs short.
                    popped = []
                    rows = []
                    plans: Dict[int, Tuple] = {}
                    use_pp = self._prefix is not None
                    resume_rows: Dict[int, np.ndarray] = {}
                    # candidates = ALL free slots (the wave-size cap
                    # bounds how many ADMIT, not which slots are
                    # eligible — free[:take] would pre-pick slots
                    # positionally and defeat the shard-hint search)
                    remaining = list(free)
                    admitted = 0
                    n_sh = getattr(self.paged.allocator, "n_shards", 1)
                    while remaining and self._queue and admitted < take:
                        req = self._queue[0][3]
                        if (req.resume_pages is not None
                                and req.resume_epoch is not None
                                and req.resume_epoch
                                != self.paged.allocator.generation):
                            # re-validate the resume epoch at ADMISSION,
                            # not just submit (ADVICE r4 #2): a pool
                            # reset while the request sat queued makes
                            # its page ids dangling aliases. No slot is
                            # consumed by a stale pop.
                            heapq.heappop(self._queue)
                            stale_resumes.append(req)
                            continue
                        # slot choice: honor the request's shard hint
                        # when its shard still has a free slot, so a
                        # conversation's turns land where its cached
                        # prefix pages live (same-shard-only reuse).
                        # Unhinted prefix-eligible requests get a
                        # CONTENT-affine default — a stable hash of the
                        # first page of tokens — so identical prefixes
                        # collide on one shard (cross-request reuse)
                        # while distinct prompts still spread.
                        slot_id = None
                        hint = req.shard_hint
                        if (hint is None and n_sh > 1 and use_pp
                                and len(req.prompt) >= self._prefix_ps
                                and not req.keep_pages):
                            hint = zlib.crc32(np.asarray(
                                req.prompt[:self._prefix_ps],
                                np.int32).tobytes())
                        if hint is not None and n_sh > 1:
                            h = hint % n_sh
                            for j, sid in enumerate(remaining):
                                if self.paged.allocator.shard_of(sid) == h:
                                    slot_id = remaining.pop(j)
                                    break
                        if slot_id is None:
                            slot_id = remaining.pop(0)
                        if req.resume_pages is not None:
                            # rolling-KV continuation: the kept pages are
                            # referenced (caller custody); only the part
                            # past resume_len needs fresh pages
                            ps_ = self.paged.page_size
                            worst = min(
                                self.paged.allocator.max_seq,
                                req.resume_len + len(req.prompt)
                                + req.sampling.max_new_tokens
                                + self.decode_chunk,
                            )
                            total = -(-worst // ps_)
                            n_fresh = max(0,
                                          total - len(req.resume_pages))
                            row = self.paged.allocator.allocate_with_prefix(
                                slot_id, req.resume_pages, n_fresh)
                            if row is None:
                                pressure_need = n_fresh
                                break  # pool exhausted; retry later
                            heapq.heappop(self._queue)
                            self._admitting.add(req.request_id)
                            popped.append(req)
                            rows.append((slot_id, row))
                            resume_rows[slot_id] = row
                            admitted += 1
                            continue
                        need = self.paged.allocator.pages_needed(
                            len(req.prompt), req.sampling.max_new_tokens,
                            self.decode_chunk,
                        )
                        row = None
                        hits: List[int] = []
                        chains: List[bytes] = []
                        for attempt in range(2):
                            hits, chains = [], []
                            # keep_pages (rolling) requests bypass the
                            # hash prefix cache both ways: a hit would
                            # reference cache-custody pages that
                            # retirement cannot hand to the caller, and
                            # registration would steal the slot's own
                            # pages INTO cache custody
                            if (use_pp and len(req.prompt) >= self._prefix_ps
                                    and not req.keep_pages):
                                hits, chains = self._prefix_plan(
                                    req.prompt, pin=True)
                                # DP-sharded pool: a slot can only
                                # reference pages of its own shard (the
                                # shard_map'd decode addresses its local
                                # sub-pool); truncate foreign-shard hits
                                keep = self.paged.allocator.usable_prefix(
                                    slot_id, hits)
                                if keep < len(hits):
                                    self._prefix.unpin(hits[keep:])
                                    hits = hits[:keep]
                            row = self._paged_allocate(
                                slot_id, hits, max(0, need - len(hits)))
                            if row is not None:
                                break
                            if hits:
                                self._prefix.unpin(hits)
                            # the hint is ADVISORY (review r5): a hinted
                            # shard whose sub-pool cannot cover the
                            # request must not head-of-line-block the 7
                            # healthy shards — retry once on the
                            # freest-pooled other free slot
                            if (attempt == 0 and hint is not None
                                    and n_sh > 1 and remaining):
                                remaining.append(slot_id)  # still free
                                alt = max(remaining,
                                          key=self.paged.allocator.free_count)
                                remaining.remove(alt)
                                slot_id = alt
                                continue
                            break
                        if row is None:
                            pressure_need = max(0, need - len(hits))
                            break  # pool exhausted; retry after retirements
                        heapq.heappop(self._queue)
                        self._admitting.add(req.request_id)
                        popped.append(req)
                        rows.append((slot_id, row))
                        admitted += 1
                        if (use_pp and len(req.prompt) >= self._prefix_ps
                                and not req.keep_pages):
                            plans[slot_id] = (hits, chains)
                else:
                    resume_rows = {}
                    popped = []
                    for _ in range(take):
                        if not self._queue:
                            break
                        req = self._queue[0][3]
                        if (req.resume_pages is not None
                                and req.resume_epoch is not None
                                and req.resume_epoch != self.pool_epoch()):
                            # dense rolling resume planned against a pool
                            # that has since been rebuilt (same race as
                            # the paged branch above)
                            heapq.heappop(self._queue)
                            stale_resumes.append(req)
                            continue
                        heapq.heappop(self._queue)
                        popped.append(req)
                    self._admitting.update(r.request_id for r in popped)
            # outside the lock: fire callbacks / the pressure hook (either
            # may re-enter submit() or take the serving layer's locks)
            for req in stale_resumes:
                self.metrics.counters["engine_stale_resumes"].inc()
                if req.on_done is not None:
                    try:
                        req.on_done(req.request_id, [], "stale_resume")
                    except Exception:
                        logger.exception("on_done callback failed")
            if self.paged and not popped:
                if (pressure_need > 0 and not pressure_called
                        and self.on_pool_pressure is not None):
                    # ONE eviction attempt per admission round: the hook
                    # frees idle rolling conversations' pages; if even
                    # that can't cover the head request, fall back to
                    # waiting for retirements as before
                    pressure_called = True
                    try:
                        self.on_pool_pressure(pressure_need)
                    except Exception:
                        logger.exception("pool-pressure callback failed")
                    continue
                if stale_resumes:
                    continue  # stale pops may have unblocked the queue head
                return
            if self.paged and rows and self._pagecheck is not None:
                # sanitizer: stamp owners, then verify the canary of
                # every re-allocated page is still intact — an
                # overwritten canary is a write-after-free landing
                # between free and re-allocation
                for (sid, _row), req in zip(rows, popped):
                    self._pagecheck_admit(sid, req)
            if self.paged and rows:
                self._mirrored(
                    self.CALL_SET_PT_ROWS,
                    np.asarray([r[0] for r in rows], np.int32),
                    np.stack([r[1] for r in rows]).astype(np.int32),
                )
            if self.paged:
                # warm-tier promotions (ISSUE 19): bulk-insert the host
                # payload into the freshly reserved resume pages BEFORE
                # the resume prefill reads them. Engine thread only —
                # the pools are donated by the prefill jits below.
                for req in popped:
                    if req.promote_payload is not None:
                        self._promote_insert(req)
            use_prefix = self._prefix is not None
            ragged = self.paged is not None and self._ragged_active()
            row_by_slot = dict(rows) if self.paged else {}
            groups: Dict[Tuple[Any, int], List[Tuple]] = {}
            ragged_batch: List[Tuple] = []
            prefix_batch: List[Tuple] = []
            resume_batch: List[Tuple] = []
            max_suffix = max_hits = 0
            # paged pops can SKIP a slot (stale resume popped without
            # consuming it), so pair each request with the slot recorded
            # at its allocation, not positionally with `free`
            slot_ids = ([r[0] for r in rows] if self.paged
                        else free[:len(popped)])
            for slot_id, req in zip(slot_ids, popped):
                if slot_id in resume_rows:
                    resume_batch.append((slot_id, req, resume_rows[slot_id]))
                    continue
                if ragged:
                    # packed ragged waves absorb BOTH the plain and the
                    # prefix-planned rows (a cache hit is just a nonzero
                    # prefix_len descriptor); resume rows keep the
                    # bucketed path (mid-page custody bookkeeping)
                    if use_prefix and slot_id in plans:
                        hits, chains = plans[slot_id]
                    else:
                        hits, chains = [], None
                    ragged_batch.append((slot_id, req, hits, chains,
                                         row_by_slot[slot_id]))
                    continue
                if not self.paged and req.resume_pages is not None:
                    # dense rolling resume: kept prefix-pool pages compose
                    # into the lane (no row-table — the lane IS the slot)
                    resume_batch.append((slot_id, req, None))
                    continue
                # sub-page prompts (no hit possible, nothing to register)
                # stay on the plain path; everything else goes through the
                # prefix path even on a full miss so its pages get
                # REGISTERED for the next turn. Paged requests were
                # matched (and pinned) during the pop loop above —
                # matching again would double-pin — so route on the plan's
                # existence there.
                if self.paged and self._prefix is not None:
                    planned = slot_id in plans
                else:
                    planned = (use_prefix
                               and len(req.prompt) >= self._prefix_ps)
                if planned:
                    if self.paged:
                        hits, chains = plans[slot_id]
                    else:
                        hits, chains = self._prefix_plan(req.prompt)
                    suffix_len = len(req.prompt) - len(hits) * self._prefix_ps
                    prefix_batch.append((slot_id, req, hits, chains))
                    max_suffix = max(max_suffix, suffix_len)
                    max_hits = max(max_hits, len(hits))
                else:
                    key = (self._bucket_for(len(req.prompt)), 0)
                    groups.setdefault(key, []).append((slot_id, req))
            if prefix_batch:
                # ONE group per admission wave, padded to the wave's max
                # (suffix bucket, prefix width): prefill cost is dominated
                # by the weight read, so co-dispatching short-suffix rows
                # with long ones is nearly free while per-(bucket, width)
                # splitting multiplies whole-model HBM passes (measured:
                # fragmentation cost more than prefix reuse saved)
                key = (self._bucket_for(max(1, max_suffix)),
                       self._pp_bucket_for(max(1, max_hits)))
                groups[key] = prefix_batch
            if resume_batch:
                # rolling-KV continuations, grouped PER suffix bucket
                # (sentinel -ppb keys route to the resume prefill). The
                # prefix wave's one-group rule does not transfer here:
                # resume deltas are bimodal — a one-turn continuation is
                # a few tokens while a conversation that chatted plain
                # during an in-flight stretch returns with hundreds — and
                # padding the short rows to the deep straggler's bucket
                # multiplies their whole-model pass (measured 290ms vs
                # 10ms at S=512), landing squarely on resume TTFT. The
                # warmup grid already covers every (bucket, width) pair.
                per_bucket: Dict[int, List[Tuple]] = {}
                for item in resume_batch:
                    b = self._bucket_for(max(1, len(item[1].prompt)))
                    per_bucket.setdefault(b, []).append(item)
                for b, items in per_bucket.items():
                    maxp = max(
                        max(1, len(it[1].resume_pages)) for it in items)
                    key = (b, -self._pp_bucket_for(maxp))
                    groups.setdefault(key, []).extend(items)
            if ragged_batch:
                groups[("ragged", 0)] = ragged_batch
            for (bucket, ppb), batch in groups.items():
                try:
                    if bucket == "ragged":
                        self._prefill_ragged_waves(batch)
                    elif ppb < 0 and not self.paged:
                        self._prefill_dense_resume_batch(batch, bucket, -ppb)
                    elif ppb < 0:
                        self._prefill_paged_resume_batch(batch, bucket, -ppb)
                    elif ppb > 0 and self.paged:
                        self._prefill_paged_prefix_batch(batch, bucket, ppb)
                    elif ppb > 0:
                        self._prefill_prefix_batch(batch, bucket, ppb)
                    else:
                        self._prefill_batch(batch)
                except Exception:
                    # the requests are already off the queue and not yet in
                    # slots: fail them here or their on_done would never fire
                    # (generate_sync / SSE streams would hang to the timeout)
                    logger.exception("prefill failed for %s",
                                     [item[1].request_id for item in batch])
                    if self._mh is not None:
                        # pod mode: the op may already be published (workers
                        # applied a prefill this coordinator didn't) —
                        # swallowing here would silently desynchronize the
                        # SPMD state; escalate to _run's pod-fatal handler
                        for item in batch:
                            req = item[1]
                            if req.on_done is not None:
                                try:
                                    req.on_done(req.request_id, [],
                                                "engine_error")
                                except Exception:
                                    pass
                        raise
                    for item in batch:
                        slot_id, req = item[0], item[1]
                        with self._cv:
                            self._admitting.discard(req.request_id)
                            self._cancel_pending.discard(req.request_id)
                        if self.paged:
                            # release the slot's pages or the next occupant's
                            # allocate() raises "already holds pages" and the
                            # whole engine fails over (review finding)
                            self.paged.allocator.mark_retired(slot_id)
                            # prefix items carry (slot, req, hits, chains);
                            # resume items carry (slot, req, row ndarray) —
                            # only matched-hit LISTS are pinned
                            if (len(item) > 2 and isinstance(item[2], list)
                                    and item[2]):
                                self._prefix.unpin(item[2])  # matched hits
                        if req.on_done is not None:
                            try:
                                req.on_done(req.request_id, [], "engine_error")
                            except Exception:
                                pass

    def _pp_widths(self, maxp: int) -> List[int]:
        """Prefix-PP gather-width buckets (both prefix engines): each
        width multiplies warmup's compile count by |prefill buckets|, so
        long context drops the quarter width — its high-hit-rate regime
        matches near-full prefixes anyway (see the prefill-bucket ladder
        comment in __init__ for the per-compile cost)."""
        widths = ({maxp // 2, maxp - 1} if self._long_context
                  else {maxp // 4, maxp // 2, maxp - 1})
        return sorted({max(1, w) for w in widths})

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def _rows_for(self, n: int) -> int:
        """Smallest row bucket covering an ``n``-admission wave (the wave
        arrays' leading dimension; [prefill_batch] unless the engine is
        row-bucketed — see __init__)."""
        for rb in self._row_buckets:
            if n <= rb:
                return rb
        return self._row_buckets[-1]

    def _set_slot_key(self, slot_id: int, seed) -> None:
        """Per-request PRNG seed: rewrite the slot's key row (host array;
        the keys ride every dispatch as a numpy argument, so this costs
        nothing on device). None restores the engine-default slot key."""
        if seed is None:
            self._base_keys_np[slot_id] = self._default_keys_np[slot_id]
        else:
            s = int(seed) & 0xFFFFFFFFFFFFFFFF
            self._base_keys_np[slot_id] = (s >> 32, s & 0xFFFFFFFF)

    # ------------------------------------------------------- prefix caching

    def _pp_bucket_for(self, n: int) -> int:
        """Smallest prefix-gather width bucket covering ``n`` hit pages."""
        for b in self._prefix_pp_buckets:
            if n <= b:
                return b
        return self._prefix_pp_buckets[-1]

    def _prefix_plan(self, prompt: List[int], pin: bool = False):
        """Longest cached prefix for ``prompt`` -> (hit page ids, chain
        hashes for every full prompt page). Hits are capped one page short
        of the prompt so at least one suffix token remains to prefill
        (the sampled first token needs logits). ``pin=True`` (paged mode)
        pins the hits so a later admission in the same round cannot evict
        pages this request's table row is about to reference."""
        from ..ops.prefix_cache import page_chains

        ps = self._prefix_ps
        n_full = len(prompt) // ps
        chains = page_chains(prompt, ps, max_pages=n_full)
        cap = n_full - 1 if n_full * ps == len(prompt) else n_full
        cap = min(cap, self._prefix_pp_buckets[-1])
        if cap <= 0:
            return [], chains
        if pin:
            hits = self._prefix.match_and_pin(chains[:cap], prompt)
        else:
            hits = self._prefix.match(chains[:cap], prompt)
        return hits, chains

    def _paged_allocate(self, slot_id: int, hits: List[int],
                        n_fresh: int) -> Optional[np.ndarray]:
        """Allocate a paged slot row (= pinned hit pages + fresh pages),
        evicting LRU prefix-cache pages into the allocator's free list
        when the pool runs short. None if still uncoverable."""
        alloc = self.paged.allocator
        if self._prefix is not None:
            # sharded pool: only this slot's shard's free pages count, and
            # only same-shard cache pages are worth evicting (a foreign-
            # shard eviction frees pages this slot can never use — review
            # finding: unfiltered rounds drained the whole cache)
            shortfall = n_fresh - alloc.free_count(slot_id)
            if shortfall > 0:
                evicted = self._prefix.evict_lru(
                    shortfall, want=alloc.evictable(slot_id))
                if evicted:
                    alloc.add_free(evicted)
            return alloc.allocate_with_prefix(slot_id, hits, n_fresh)
        return alloc.allocate(slot_id, n_fresh)

    # ------------------------------------------------- page sanitizer
    # Both helpers run ONLY under SWARMDB_PAGECHECK=1 (self._pagecheck
    # set by the checked-allocator factory) — the flag-off path never
    # reaches them. They are deliberately NOT marked hot: the canary
    # verify is a sanctioned per-admission device sync the sanitizer
    # pays for detection.

    def _pagecheck_poison(self, pages: List[int]) -> None:
        """Stamp freed pages' device K/V with the canary pattern (one
        eager scatter per reclaim batch). Skipped in pod mode — a
        local-only device write would desynchronize the SPMD mirrors."""
        if self._mh is not None or not pages:
            return
        from ..ops.paged_kv import canary_fill

        self.cache["k"], self.cache["v"] = canary_fill(
            self.cache["k"], self.cache["v"], pages)
        self._pagecheck.mark_poisoned(pages)

    def _pagecheck_admit(self, slot_id: int, req: "GenRequest") -> None:
        """Admission-side sanitizer bookkeeping: stamp the slot's owner
        (request id — the aliasing reports name both conversations),
        then verify the canary of every poisoned page this slot was
        just handed is intact. A mismatch means something WROTE to the
        page while it was free — the write-after-free no host-side
        bookkeeping can see."""
        pc = self._pagecheck
        pc.set_owner(slot_id, req.request_id)
        if self._mh is not None:
            return
        fresh = self.paged.allocator.pages_for(slot_id)
        poisoned = pc.poisoned_pages(fresh)
        if not poisoned:
            return
        from ..ops.paged_kv import canary_check

        bad = canary_check(self.cache["k"], self.cache["v"], poisoned)
        if bad:
            pc.canary_violation(
                bad, detail=f"at admission of {req.request_id}")
        pc.clear_poison(poisoned)

    def _promote_insert(self, req: "GenRequest") -> None:
        """Warm-tier promotion (ISSUE 19): bulk-device_put the host-RAM
        payload into the request's freshly reserved resume pages — the
        EXACT storage-width bytes that left the pool at demotion come
        back (``pool_insert_raw``: no requantization), so a resumed
        greedy decode is bit-identical to never having spilled.

        Engine thread only (the pools are donated by engine jits). The
        insert loops a ONE-page jitted scatter over the payload rather
        than batching: a batched insert's shape varies with the
        conversation's page count, and every new count would compile a
        fresh variant — a multi-hundred-ms stall landing exactly on the
        warm-hit TTFT this tier exists to shrink. One fixed-shape
        variant compiles once; per-page dispatches are off the decode
        hot path and cheap."""
        payload, req.promote_payload = req.promote_payload, None
        if payload is None or not req.resume_pages:
            return
        from ..ops.paged_kv import pool_insert_raw

        t0 = time.time()

        def _page(pay, i):
            if isinstance(pay, tuple):
                return tuple(a[:, i:i + 1] for a in pay)
            return pay[:, i:i + 1]

        fn = getattr(self, "_promote_jit", None)
        if fn is None:
            fn = jax.jit(
                pool_insert_raw,
                donate_argnums=(0,) if self._donate_cache else ())
            self._promote_jit = fn
        with self._device_ctx():
            new_k, new_v = self.cache["k"], self.cache["v"]
            for i, pid in enumerate(req.resume_pages):
                ids_arr = jnp.asarray([pid], jnp.int32)
                new_k = fn(new_k, ids_arr, _page(payload[0], i))
                new_v = fn(new_v, ids_arr, _page(payload[1], i))
        self.cache = self._paged_cache_with(new_k, new_v)
        self.metrics.counters["engine_tier_promote_inserts"].inc()
        self.metrics.latencies["tier_promote_s"].observe(
            time.time() - t0)

    # swarmlint: hot
    def _prefill_paged_prefix_batch(self, batch: List[Tuple], bucket: int,
                                    ppb: int) -> None:
        """Paged-pool prefix prefill: gather reused pages in place, forward
        only the suffix, scatter its KV into the slot's fresh pages (the
        reuse boundary is page-aligned, so suffix chunk c maps to fresh
        page c), then REGISTER the prompt's fresh full pages — custody
        moves to the cache with no copy. One fused pool-donating dispatch
        per admission wave (see ``_prefill_paged_prefix_insert``)."""
        t0 = time.time()
        ps = self._prefix_ps
        Bp = self._rows_for(len(batch))  # row-bucketed wave (lanes)
        chunks = -(-bucket // ps)
        padded = np.full((Bp, bucket), self.pad_id, np.int32)
        lengths = np.ones(Bp, np.int32)
        plens = np.zeros(Bp, np.int32)
        table = np.zeros((Bp, ppb), np.int32)
        target = np.zeros((Bp, chunks), np.int32)
        gather = np.zeros(Bp, np.int64)
        scatter = np.full(Bp, self.max_batch, np.int32)
        reg_records = []
        for row, (slot_id, req, hits, chains) in enumerate(batch):
            prompt = req.prompt
            p0 = len(hits) * ps
            suffix = prompt[p0:]
            padded[row, : len(suffix)] = suffix
            lengths[row] = len(suffix)
            plens[row] = p0
            table[row, : len(hits)] = hits
            gather[row] = slot_id
            scatter[row] = slot_id
            fresh = self.paged.allocator.pages_for(slot_id)
            m = min(len(fresh), chunks)
            target[row, :m] = fresh[:m]
            s = req.sampling
            self._temp[slot_id] = s.temperature
            self._topk[slot_id] = s.top_k
            self._topp[slot_id] = s.top_p
            self._set_slot_key(slot_id, s.seed)
            n_full = len(prompt) // ps
            for page_idx in range(len(hits), n_full):
                f = page_idx - len(hits)
                if f >= len(fresh):
                    break
                reg_records.append(
                    (slot_id, chains[page_idx],
                     tuple(prompt[page_idx * ps:(page_idx + 1) * ps]),
                     fresh[f]))
        self._mirrored(
            self.CALL_PAGED_PREFIX_PREFILL, padded, lengths, plens, table,
            target, scatter, self._base_keys_np[gather],
            self._temp[gather], self._topk[gather], self._topp[gather],
        )
        self.metrics.counters["prefill_padding_tokens"].inc(
            int(padded.size) - int(lengths[:len(batch)].sum()))
        self.metrics.counters["prefill_packed_tokens"].inc(
            int(lengths[:len(batch)].sum()))
        self._last_wave_kind = "bucketed"
        self._prof.wave("bucketed", bucket,
                        int(lengths[:len(batch)].sum()),
                        int(padded.size) - int(lengths[:len(batch)].sum()),
                        prof_key("prefill.paged_prefix", padded.shape, ppb))
        pins: Dict[int, List[int]] = {}
        for slot_id, chain, toks, page_id in reg_records:
            if self._prefix.register(chain, toks, page_id):
                # custody -> cache; pin while this slot still reads it
                self.paged.allocator.transfer_to_cache(slot_id, [page_id])
                self._prefix.pin([page_id])
                pins.setdefault(slot_id, []).append(page_id)
        for slot_id, req, hits, _chains in batch:
            # unpinned at retirement (together with the matched hits)
            self._slot_prefix_pins[slot_id] = hits + pins.get(slot_id, [])
        self.metrics.counters["prefix_reused_tokens"].inc(int(plens.sum()))
        self._activate([(s, r) for s, r, _, _ in batch], t0)

    # swarmlint: hot
    def _prefill_paged_resume_batch(self, batch: List[Tuple], bucket: int,
                                    ppb: int) -> None:
        """One fused suffix prefill CONTINUING kept conversations
        (rolling KV, GenRequest.resume_pages): attend the kept pages +
        the new tokens, write the new K/V positionally from resume_len
        (mid-page), sample. No hash registration — custody of the kept
        pages stays with the caller's registry."""
        t0 = time.time()
        Bp = self.prefill_batch
        maxp = self.paged.allocator.maxp
        padded = np.full((Bp, bucket), self.pad_id, np.int32)
        lengths = np.ones(Bp, np.int32)
        rlens = np.zeros(Bp, np.int32)
        table = np.zeros((Bp, ppb), np.int32)
        row_tables = np.zeros((Bp, maxp), np.int32)
        gather = np.zeros(Bp, np.int64)
        scatter = np.full(Bp, self.max_batch, np.int32)
        for r, (slot_id, req, row) in enumerate(batch):
            suffix = req.prompt
            padded[r, : len(suffix)] = suffix
            lengths[r] = len(suffix)
            rlens[r] = req.resume_len
            table[r, : len(req.resume_pages)] = req.resume_pages
            row_tables[r] = row
            gather[r] = slot_id
            scatter[r] = slot_id
            s = req.sampling
            self._temp[slot_id] = s.temperature
            self._topk[slot_id] = s.top_k
            self._topp[slot_id] = s.top_p
            self._set_slot_key(slot_id, s.seed)
        self._mirrored(
            self.CALL_PAGED_RESUME_PREFILL, padded, lengths, rlens, table,
            row_tables, scatter, self._base_keys_np[gather],
            self._temp[gather], self._topk[gather], self._topp[gather],
        )
        self.metrics.counters["prefill_padding_tokens"].inc(
            int(padded.size) - int(lengths[:len(batch)].sum()))
        self.metrics.counters["prefill_packed_tokens"].inc(
            int(lengths[:len(batch)].sum()))
        self._last_wave_kind = "bucketed"
        self._prof.wave("bucketed", bucket,
                        int(lengths[:len(batch)].sum()),
                        int(padded.size) - int(lengths[:len(batch)].sum()),
                        prof_key("prefill.resume", padded.shape, ppb))
        self.metrics.counters["prefix_reused_tokens"].inc(int(rlens.sum()))
        self._activate([(s, r) for s, r, _ in batch], t0)

    # swarmlint: hot
    def _prefix_fused_dispatch(self, rows, bucket: int, ppb: int,
                               t0: float) -> None:
        """Shared array build + dispatch for the dense prefix-path
        prefills (_prefill_prefix_batch and _prefill_dense_resume_batch —
        the resume path is the registration-free special case: same
        shapes, same executable, no new compile variants).

        ``rows``: (slot_id, req, suffix_tokens, prefix_len, table_pages,
        reg_pairs) per admission; ``reg_pairs`` = [(lane_col, pool_page)]
        to register (empty for resume)."""
        ps = self._prefix_ps
        Bp = self.prefill_batch
        lane_pages = min(ppb + -(-bucket // ps), self.max_seq // ps)
        RC = lane_pages
        padded = np.full((Bp, bucket), self.pad_id, np.int32)
        lengths = np.ones(Bp, np.int32)
        plens = np.zeros(Bp, np.int32)
        table = np.zeros((Bp, ppb), np.int32)
        reg_cols = np.full((Bp, RC), -1, np.int32)
        reg_pages = np.zeros((Bp, RC), np.int32)
        gather = np.zeros(Bp, np.int64)
        scatter = np.full(Bp, self.max_batch, np.int32)
        for row, (slot_id, req, suffix, plen, tpages, reg_pairs) in \
                enumerate(rows):
            padded[row, : len(suffix)] = suffix
            lengths[row] = len(suffix)
            plens[row] = plen
            table[row, : len(tpages)] = tpages
            gather[row] = slot_id
            scatter[row] = slot_id
            s = req.sampling
            self._temp[slot_id] = s.temperature
            self._topk[slot_id] = s.top_k
            self._topp[slot_id] = s.top_p
            self._set_slot_key(slot_id, s.seed)
            for r, (page_idx, pid) in enumerate(reg_pairs):
                reg_cols[row, r] = page_idx
                reg_pages[row, r] = pid
        self._mirrored(
            self.CALL_DENSE_PREFIX_PREFILL, padded, lengths, plens, table,
            reg_cols, reg_pages, scatter, self._base_keys_np[gather],
            self._temp[gather], self._topk[gather], self._topp[gather],
        )
        self.metrics.counters["prefix_reused_tokens"].inc(int(plens.sum()))
        self.metrics.counters["prefill_padding_tokens"].inc(
            int(padded.size) - int(lengths[:len(rows)].sum()))
        self.metrics.counters["prefill_packed_tokens"].inc(
            int(lengths[:len(rows)].sum()))
        self._last_wave_kind = "bucketed"
        self._prof.wave("bucketed", bucket,
                        int(lengths[:len(rows)].sum()),
                        int(padded.size) - int(lengths[:len(rows)].sum()),
                        prof_key("prefill.dense_prefix", padded.shape, ppb))
        self._activate([(r[0], r[1]) for r in rows], t0)

    # swarmlint: hot
    def _prefill_dense_resume_batch(self, batch, bucket: int,
                                    ppb: int) -> None:
        """Dense rolling resume: gather each row's KEPT prefix-pool pages,
        compose them into the slot lane with a MID-PAGE boundary
        (compose_prefix_lane / gqa_attention_prefix are token-granular in
        prefix_lens — no page alignment needed), forward only the suffix,
        and register NOTHING (reg_cols = -1 routes the registration
        einsum's writes to the trash page; page custody stays with the
        caller's registry)."""
        self._prefix_fused_dispatch(
            [(slot_id, req, req.prompt, req.resume_len,
              req.resume_pages, [])
             for slot_id, req, _none in batch],
            bucket, ppb, time.time(),
        )

    # swarmlint: hot
    def _prefill_prefix_batch(self, batch, bucket: int,
                              ppb: int) -> None:
        """One fused suffix prefill for a group of admissions sharing a
        (suffix bucket, prefix width) shape: gather reused prefix pages +
        forward ONLY the suffix + compose/insert each row's KV lane +
        register the prompt's fresh full pages — one dispatch, pool- and
        cache-donating. Mirrors ``_prefill_batch``; see
        ``_prefill_prefix_insert`` in ``__init__``."""
        t0 = time.time()
        ps = self._prefix_ps
        rows = []
        reg_records = []
        acquired = []
        for slot_id, req, hits, chains in batch:
            prompt = req.prompt
            p0 = len(hits) * ps
            # register the prompt's fresh FULL pages (their lane content
            # is final — decode writes start at len(prompt), past them)
            n_full = len(prompt) // ps
            new_idx = list(range(len(hits), n_full))
            ids = self._prefix.acquire(len(new_idx)) if new_idx else []
            acquired.extend(ids)
            reg_pairs = list(zip(new_idx, ids))
            for page_idx, pid in reg_pairs:
                reg_records.append(
                    (chains[page_idx],
                     tuple(prompt[page_idx * ps:(page_idx + 1) * ps]), pid))
            rows.append((slot_id, req, prompt[p0:], p0, hits, reg_pairs))
        try:
            self._prefix_fused_dispatch(rows, bucket, ppb, t0)
        except Exception:
            for pid in acquired:
                self._prefix.release(pid)
            raise
        for rec in reg_records:
            self._prefix.register(*rec)

    # swarmlint: hot
    def _prefill_ragged_waves(self, batch: List[Tuple]) -> None:
        """Packed ragged admission waves (ISSUE 11 tentpole): the wave's
        rows concatenate into ONE token stream — no row buckets, no
        length buckets — described by per-row (start, len, prefix_len)
        descriptors, and every wave's width comes off the power-of-two
        ladder LARGEST-FIT, so waves are exactly full (zero padding)
        until the remainder drops under the smallest rung. A row longer
        than a wave's remaining budget SPLITS: its head's K/V lands in
        its pages this wave, and the tail rides the next wave with
        prefix_len advanced — the ragged kernel reads the
        already-written pages back in place, exactly like a prefix-cache
        hit. Sampling fires only on a row's FINAL chunk (scatter id
        max_batch drops the rest), with the same absolute-position PRNG
        fold as the bucketed paths.

        ``batch`` rows: (slot_id, req, hits, chains, table_row) — hits/
        chains from the admission-time prefix plan (chains None = row not
        prefix-planned: sub-page prompt, keep_pages, or prefix off)."""
        t0 = time.time()
        R = self.max_batch
        ps = self.paged.page_size
        maxp = self.paged.allocator.maxp
        cap = maxp * ps
        pend: List[List[Any]] = []
        for slot_id, req, hits, chains, row in batch:
            p0 = len(hits) * ps
            pend.append([slot_id, req.prompt[p0:], p0, 0, row])
            s = req.sampling
            self._temp[slot_id] = s.temperature
            self._topk[slot_id] = s.top_k
            self._topp[slot_id] = s.top_p
            self._set_slot_key(slot_id, s.seed)
        packed_n = padding_n = 0
        while pend:
            total = 0
            for it in pend:
                total += len(it[1]) - it[3]
            wd = self._ragged_width_for(total)
            tokens = np.full(wd, self.pad_id, np.int32)
            tok_row = np.full(wd, R, np.int32)   # R = dead row sentinel
            tok_pos = np.full(wd, cap, np.int32)  # >= coverage -> trash
            starts = np.zeros(R, np.int32)
            lens = np.zeros(R, np.int32)
            plens = np.zeros(R, np.int32)
            tables = np.zeros((R, maxp), np.int32)
            scatter = np.full(R, self.max_batch, np.int32)
            gather = np.zeros(R, np.int64)
            filled = 0
            r = 0
            for it in pend:
                if filled >= wd or r >= R:
                    break
                slot_id, suffix, p0, consumed, row = (it[0], it[1], it[2],
                                                      it[3], it[4])
                take = min(len(suffix) - consumed, wd - filled)
                if take <= 0:
                    continue
                abs0 = p0 + consumed
                tokens[filled:filled + take] = suffix[consumed:
                                                      consumed + take]
                tok_row[filled:filled + take] = r
                tok_pos[filled:filled + take] = np.arange(
                    abs0, abs0 + take, dtype=np.int32)
                starts[r] = filled
                lens[r] = take
                plens[r] = abs0
                tables[r] = row
                gather[r] = slot_id
                if consumed + take == len(suffix):
                    scatter[r] = slot_id     # final chunk: sample here
                it[3] = consumed + take
                filled += take
                r += 1
            if self._kerncheck:
                # descriptor audit BEFORE the wave ships: a bad page id /
                # trash-page target / duplicate (page, offset) cell is an
                # engine bug the kernel would silently scatter into the
                # pool (runtime face of SWL901/902)
                from ..obs.kerncheck import check_wave_descriptors

                check_wave_descriptors(
                    tok_row, tok_pos, tables,
                    self.paged.allocator.num_pages, ps)
            self._mirrored(
                self.CALL_PAGED_PREFILL_RAGGED, tokens, tok_row, tok_pos,
                starts, lens, plens, tables, scatter,
                self._base_keys_np[gather], self._temp[gather],
                self._topk[gather], self._topp[gather],
            )
            # dispatch-shape profile: the tiny flush waves ROADMAP item 2
            # wants sized show up here as named (ragged, small-width) rows
            self._prof.wave("ragged", wd, filled, wd - filled,
                            prof_key("prefill.ragged", tokens.shape))
            packed_n += filled
            padding_n += wd - filled
            pend = [it for it in pend if it[3] < len(it[1])]
        self.metrics.counters["prefill_packed_tokens"].inc(packed_n)
        self.metrics.counters["prefill_padding_tokens"].inc(padding_n)
        self._last_wave_kind = "ragged"
        if self._prefix is not None:
            # registration mirrors _prefill_paged_prefix_batch: custody
            # of the prompt's fresh FULL pages moves to the cache with no
            # copy; matched hits stay pinned until retirement
            reused = 0
            for slot_id, req, hits, chains, _row in batch:
                if chains is None:
                    continue
                reused += len(hits) * ps
                prompt = req.prompt
                fresh = self.paged.allocator.pages_for(slot_id)
                pins: List[int] = []
                n_full = len(prompt) // ps
                for page_idx in range(len(hits), n_full):
                    f = page_idx - len(hits)
                    if f >= len(fresh):
                        break
                    toks = tuple(prompt[page_idx * ps:(page_idx + 1) * ps])
                    if self._prefix.register(chains[page_idx], toks,
                                             fresh[f]):
                        self.paged.allocator.transfer_to_cache(
                            slot_id, [fresh[f]])
                        self._prefix.pin([fresh[f]])
                        pins.append(fresh[f])
                self._slot_prefix_pins[slot_id] = hits + pins
            if reused:
                self.metrics.counters["prefix_reused_tokens"].inc(reused)
        self._activate([(b[0], b[1]) for b in batch], t0)

    def _prefill_batch(self, batch: List[Tuple[int, GenRequest]]) -> None:  # swarmlint: hot
        """One compiled prefill for up to ``prefill_batch`` admissions.

        The call is padded to the fixed [Bp, bucket] shape (one compiled
        variant per bucket); padding rows are discarded. NO host sync
        happens here — sampled first tokens land in the device fed-token
        vector and surface as row 0 of the next chunk's block.
        """
        t0 = time.time()
        n = len(batch)
        # row-bucketed wave (lane engines): pay for the admissions the
        # wave actually has, not prefill_batch unconditionally
        Bp = self._rows_for(n)
        longest = max(len(req.prompt) for _, req in batch)
        bucket = self._bucket_for(longest)
        padded = np.full((Bp, bucket), self.pad_id, np.int32)
        lengths = np.ones(Bp, np.int32)
        # row -> slot gather index, padded to Bp (padding rows borrow slot 0's
        # params/keys; their outputs are discarded)
        gather = np.zeros(Bp, np.int64)
        # row -> slot scatter index for the fused insert; padding rows point
        # one past the last slot so mode="drop" discards their writes
        scatter = np.full(Bp, self.max_batch, np.int32)
        for row, (slot_id, req) in enumerate(batch):
            prompt = req.prompt  # submit() enforces len < max_seq
            padded[row, : len(prompt)] = prompt
            lengths[row] = len(prompt)
            gather[row] = slot_id
            scatter[row] = slot_id
            # slot sampling params must be set BEFORE prefill samples the
            # first token, or the request inherits the previous occupant's
            s = req.sampling
            self._temp[slot_id] = s.temperature
            self._topk[slot_id] = s.top_k
            self._topp[slot_id] = s.top_p
            self._set_slot_key(slot_id, s.seed)
        # padding waste: grid tokens dispatched minus real prompt tokens
        # (bucket rounding + padding rows) — flight-recorder occupancy
        packed_n = int(lengths[:n].sum())
        padding_n = int(padded.size) - packed_n
        self.metrics.counters["prefill_padding_tokens"].inc(padding_n)
        self.metrics.counters["prefill_packed_tokens"].inc(packed_n)
        self._last_wave_kind = "bucketed"

        if not self.paged:
            # ONE dispatch: forward + sample + slot insert + token scatter.
            # Stale entries a previous occupant left at positions >= bucket
            # are never read: decode writes position p in the same step
            # that first attends to it (write-before-read invariant).
            if self._mh is not None:
                self._mh.publish_prefill(
                    padded, lengths, scatter, self._base_keys_np[gather],
                    self._temp[gather], self._topk[gather],
                    self._topp[gather])
            prof = self._prof
            t0_ns = time.monotonic_ns() if prof.enabled else 0
            self.cache, self._last_tokens, self._last_lps = \
                self._prefill_fused(
                    self.params,
                    padded,              # raw np: transfer rides the dispatch
                    lengths,
                    scatter,
                    self.cache,
                    self._last_tokens,
                    self._last_lps,
                    self._base_keys_np[gather],
                    self._temp[gather],
                    self._topk[gather],
                    self._topp[gather],
                )
            if t0_ns:
                key = prof_key("prefill.dense", padded.shape)
                prof.dispatch(key, t0_ns, time.monotonic_ns() - t0_ns)
                prof.wave("bucketed", bucket, packed_n, padding_n, key)
            self._activate(batch, t0)
            return

        # slot rows allocated fewer pages than the bucket (short prompt
        # in a big bucket) route the all-padding chunks to trash page 0;
        # padding rows (beyond n) scatter entirely to trash
        chunks = -(-bucket // self.paged.page_size)
        if self._packed_active():
            # shard-packed collective-free prefill: re-lay the wave as
            # per-shard row blocks (block d = shard d's rows; slot→shard
            # affinity makes every row's pages and fed-token slot local
            # to its block's device; padding rows are dropped/trashed)
            n_sh, rows_per, R = self._packed_geometry()
            p_tokens = np.full((R, bucket), self.pad_id, np.int32)
            p_lengths = np.ones(R, np.int32)
            p_target = np.zeros((R, chunks), np.int32)
            p_scatter = np.full(R, self.max_batch, np.int32)
            p_gather = np.zeros(R, np.int64)
            fill = [0] * n_sh  # next free row within each shard block
            for row, (slot_id, req) in enumerate(batch):
                sh = self.paged.allocator.shard_of(slot_id)
                r = sh * rows_per + fill[sh]
                fill[sh] += 1
                p_tokens[r] = padded[row]
                p_lengths[r] = lengths[row]
                p_scatter[r] = slot_id
                p_gather[r] = slot_id
                pages = self.paged.allocator.pages_for(slot_id)
                m = min(len(pages), chunks)
                p_target[r, :m] = pages[:m]
            self._mirrored(
                self.CALL_PAGED_PREFILL_PACKED, p_tokens, p_lengths,
                p_target, p_scatter, self._base_keys_np[p_gather],
                self._temp[p_gather], self._topk[p_gather],
                self._topp[p_gather],
            )
            self._prof.wave("packed", bucket, packed_n,
                            int(p_tokens.size) - packed_n,
                            prof_key("prefill.packed", p_tokens.shape))
            self._activate(batch, t0)
            return
        target = np.zeros((Bp, chunks), np.int32)
        for row in range(n):
            pages = self.paged.allocator.pages_for(int(gather[row]))
            m = min(len(pages), chunks)
            target[row, :m] = pages[:m]
        # padding rows -> max_batch, dropped; raw np args: the transfer
        # rides the dispatch (and, pod mode, the publish to workers)
        self._mirrored(
            self.CALL_PAGED_PREFILL, padded, lengths, target, scatter,
            self._base_keys_np[gather], self._temp[gather],
            self._topk[gather], self._topp[gather],
        )
        self._prof.wave("bucketed", bucket, packed_n, padding_n,
                        prof_key("prefill.paged", padded.shape))
        self._activate(batch, t0)

    def _activate(self, batch: List[Tuple[int, GenRequest]], t0: float) -> None:  # swarmlint: hot
        if self._pagecheck is not None:
            # dispatch-time page validation: every page the slot's row
            # was stamped with at allocation is still live at the same
            # alloc epoch (a page freed+reallocated in between is the
            # stale-table race; a foreign page is cross-lane aliasing)
            for slot_id, _req in batch:
                self._pagecheck.validate_row(slot_id)
        for slot_id, req in batch:
            slot = self.slots[slot_id]
            slot.active = True
            slot.request = req
            slot.admitted_at = t0
            # next write position; rolling-KV continuations resume past
            # the tokens already in their kept pages
            slot.position = req.resume_len + len(req.prompt)
            slot.dispatched_position = slot.position
            slot.generated = []
            slot.logprobs = []
            slot.pending_first = True
            slot.admit_syncs = self._host_sync_n
            with self._cv:
                self._admitting.discard(req.request_id)
                # cancelled while the prefill was in flight: retire at the
                # next processed block
                slot.cancelled = req.request_id in self._cancel_pending
                self._cancel_pending.discard(req.request_id)
            slot.first_token_at = None
            self.total_requests += 1
            # prefill work accounting (bench MFU: prompt tokens cost the
            # same per-token FLOPs as decode tokens but 10-20x the volume
            # under chat-history prompts). The LOGICAL prompt includes a
            # rolling continuation's kept tokens; reuse is counted
            # separately in prefix_reused_tokens, so computed = total -
            # reused stays consistent across the prefix and resume paths
            self.metrics.counters["prompt_tokens"].inc(
                len(req.prompt) + req.resume_len)
            # admission accounting for the SLO sentinel's window
            # summaries: requests admitted + one wave per _activate call
            # (the offline analyzer derives the same two numbers from
            # prefill-span clustering; online they are two counter incs)
            self.metrics.counters["engine_admitted"].inc()
            self._lat_queue_wait.observe(t0 - req.submitted_at)
            HIST_QUEUE_WAIT.observe(t0 - req.submitted_at,
                                    req.request_id)
            self.metrics.counters["phase_us_queue_wait"].inc(
                max(0, int((t0 - req.submitted_at) * 1e6)))
            # retro-span: the wait was over before any tracer call site
            # could run, so it is recorded from its wall-clock endpoints
            self.tracer.span_at("engine.admit", req.submitted_at, t0,
                                cat="engine", rid=req.request_id)
        prefill_dt = time.time() - t0
        self._lat_prefill.observe(prefill_dt)
        self.metrics.counters["engine_admission_waves"].inc()
        if self.overlap_probe is not None:
            # per-shard lanes: count waves whose prefill dispatch ran
            # while a SIBLING lane's decode session was in flight — the
            # overlap that a single global admission wave can never have
            try:
                if self.overlap_probe():
                    self.metrics.counters[
                        "engine_admission_overlap_steps"].inc()
            except Exception:  # probe is advisory telemetry only
                pass
        self.metrics.counters["phase_us_prefill"].inc(
            max(0, int(prefill_dt * 1e6)))
        for slot_id, req in batch:
            self.tracer.span_at(
                "engine.prefill", t0, t0 + prefill_dt, cat="engine",
                rid=req.request_id,
                args={"slot": slot_id,
                      "mid": req.metadata.get("message_id")})

    # --------------------------------------------------------------- decode

    def _use_resident(self) -> bool:
        """Whether the loop runs device-resident decode sessions instead
        of per-chunk scan dispatches. ONE gate shared by the loop,
        warmup() and warmup_call_plan() (same drift contract as
        _packed_active): built only for single-shard paged engines, and
        pod mode falls back — worker hosts replay per-call, and a
        host-steered while_loop cannot be mirrored."""
        return self._resident_variants is not None and self._mh is None

    # swarmlint: hot
    def _resident_emit(self, block, lps, n) -> np.bool_:
        """Ordered io_callback target: one call per device chunk, on the
        runtime's callback thread. The engine thread is parked in the
        session drain for the whole session, and ordered callbacks are
        serialized, so this thread IS the engine thread's stand-in:
        token emission, retirement, and stream callbacks all run here,
        without the device loop ever waiting on a host round-trip (the
        return value is consumed one chunk later — double-buffered).
        Never raises: an exception here would poison the device program
        mid-flight, so failures vote to stop the loop instead."""
        try:
            snap = self._resident_snap
            if snap is None:
                return np.bool_(False)
            n = int(n)
            K = self.decode_chunk
            snapshot = [(i, req, pos0 + n * K) for i, req, pos0 in snap]
            now_ns = time.monotonic_ns()
            prev_ns = self._resident_prev_ns
            if prev_ns:
                # resident-path device time: the emission-ring chunk
                # boundary deltas ARE the chunk wall times — no sync,
                # no block_until_ready, the issue's design point
                self._prof.dispatch(self._prof_resident_key, prev_ns,
                                    now_ns - prev_ns)
            self._process_host_block(np.asarray(block), np.asarray(lps),
                                     snapshot, self._resident_prev_ns)
            self._resident_prev_ns = now_ns
            return np.bool_(self._resident_should_continue())
        except Exception:
            logger.exception("emission-ring block processing failed; "
                             "stopping the resident session")
            return np.bool_(False)

    # swarmlint: hot
    def _resident_should_continue(self) -> bool:
        """The host's continue vote, evaluated once per emitted chunk.
        Stop when: the engine is stopping, nothing is active anymore
        (every lane retired host-side — the device's own done mask can
        lag a cancel), or queued work could be admitted into a freed
        slot (exit -> admit -> new session). Reads of _stop/slots are
        deliberately lock-light: a stale verdict is corrected at the
        next chunk boundary."""
        # racy-by-design: a stale verdict costs ONE extra chunk, while
        # taking _cv here would put lock acquisition on every emitted
        # chunk of every lane
        if self._stop:  # swarmlint: disable=SWL301 -- chunk-granular race is benign
            return False
        cs = self.chaos_step
        if cs is not None and getattr(cs, "pending", lambda: False)():
            # an armed chaos fault must land at the loop-top seam: exit
            # the session so the next iteration runs chaos_step (a kill
            # raised inside this ordered callback would be swallowed)
            return False
        active = any(s.active for s in self.slots)
        if not active:
            return False
        with self._cv:
            queued = len(self._queue)
        if queued and any(not s.active for s in self.slots):
            return False
        return True

    # swarmlint: hot
    def _run_resident(self) -> None:
        """Dispatch one device-resident decode session and drain it.

        The session covers every currently-active slot; admission happens
        only between sessions (the continue vote exits the loop when
        queued work meets a free slot). Host<->device traffic for the
        whole session: the dispatch (no sync) and ONE drain read of the
        chunk counter — a request admitted and retired within a session
        therefore spans a single sanctioned sync, vs one per chunk on
        the scan path."""
        B = self.max_batch
        K = self.decode_chunk
        positions = np.zeros((B,), np.int32)
        stop_pos = np.zeros((B,), np.int32)
        live = np.zeros((B,), bool)
        snap: List[Tuple[int, GenRequest, int]] = []
        needs_filters = False
        needs_sampling = False
        max_rem = 0
        for i, s in enumerate(self.slots):
            if not s.active:
                continue
            pos0 = s.dispatched_position
            positions[i] = pos0
            live[i] = True
            # +1 covers the pending first token (row 0 of the first
            # block); the device stops the LOOP here, the host still
            # owns exact retirement semantics
            rem = (s.request.sampling.max_new_tokens
                   - len(s.generated) + 1)
            stop_pos[i] = min(self.max_seq, pos0 + max(1, rem))
            snap.append((i, s.request, pos0))
            max_rem = max(max_rem, int(stop_pos[i]) - pos0)
            if self._topk[i] > 0 or self._topp[i] < 1.0:
                needs_filters = True
            if self._temp[i] > 0:
                needs_sampling = True
        if not snap:
            return
        max_chunks = np.int32(-(-max(1, max_rem) // K) + 1)
        variant = (0 if needs_filters else 1 if needs_sampling else 2)
        fn = self._resident_variants[variant]
        self._prof_resident_key = PROF_RESIDENT_KEYS[variant]
        self._resident_snap = snap
        self._resident_prev_ns = time.monotonic_ns()
        self._lane_busy = True
        try:
            n_dev, lt, llp, cache = fn(
                self.params, self._last_tokens, self._last_lps, positions,
                self.cache, self._base_keys_np, self._temp, self._topk,
                self._topp, stop_pos, live, max_chunks,
            )
            self._last_tokens, self._last_lps, self.cache = lt, llp, cache
            t_sync0 = time.monotonic_ns()
            # swarmlint: sanctioned-drain -- THE one sync per session:
            # its resolution also guarantees every ordered emission
            # callback has run, so slot state below is host-confirmed
            n_chunks = int(jax.device_get(n_dev))
            t_sync1 = time.monotonic_ns()
            self.tracer.span_end(t_sync0, "engine.host_sync", cat="engine")
            self.metrics.counters["engine_host_syncs"].inc()
            self._host_sync_n += 1
            self.metrics.counters["phase_us_host_sync"].inc(
                (t_sync1 - t_sync0) // 1000)
            self.metrics.counters["engine_resident_sessions"].inc()
            self.metrics.counters["engine_resident_chunks"].inc(n_chunks)
        finally:
            self._resident_snap = None
            self._lane_busy = False
        for i, req, _pos0 in snap:
            s = self.slots[i]
            if s.active and s.request is req:
                # every emitted block advanced s.position; the device's
                # next fed token corresponds to exactly that extent
                s.dispatched_position = s.position

    def _dispatch_decode(self):  # swarmlint: hot
        """Issue one K-step decode chunk (NO host sync) and return
        (device token block, snapshot) for later processing.

        The snapshot pins (slot, request, start position) at dispatch
        time: with pipelining, a slot can retire and be re-admitted while
        this chunk is still in flight — its lane then holds the OLD
        occupant's garbage, which processing must discard (the request
        identity check does exactly that).
        """
        positions = np.zeros((self.max_batch,), np.int32)
        snapshot: List[Tuple[int, GenRequest, int]] = []
        needs_filters = False
        needs_sampling = False
        for i, s in enumerate(self.slots):
            if s.active:
                positions[i] = s.dispatched_position
                snapshot.append((i, s.request, s.dispatched_position))
                s.dispatched_position += self.decode_chunk
                if self._topk[i] > 0 or self._topp[i] < 1.0:
                    needs_filters = True
                if self._temp[i] > 0:
                    needs_sampling = True
        variant = (0 if needs_filters else 1 if needs_sampling else 2)
        decode = self._decode_variants[variant]
        if self._mh is not None:
            self._mh.publish_decode(variant, positions, self._base_keys_np,
                                    self._temp, self._topk, self._topp)
        # keys ride as a raw [B, 2] numpy argument (like temp/topk/topp):
        # per-REQUEST seeds just rewrite a host row at admission, with no
        # graph change and no eager transfer
        all_toks, all_lps, self._last_tokens, self._last_lps, self.cache = \
            decode(
                self.params, self._last_tokens, self._last_lps, positions,
                self.cache, self._base_keys_np,
                self._temp, self._topk, self._topp,
            )
        # dispatch stamp: _process_block closes each snapshot slot's
        # "engine.decode_chunk" span against it (monotonic, so a wall
        # clock step can't produce a negative chunk); the variant index
        # rides along so the chunk's device time lands on the right
        # swarmprof key
        return all_toks, all_lps, snapshot, time.monotonic_ns(), variant

    # swarmlint: hot
    def _drain_prefill_only(self) -> None:
        """Fleet PREFILL lanes (ISSUE 20): retire admission-only
        (max_new_tokens <= 1) requests straight off the prefill sample.
        ``_last_tokens[i]`` IS the fed token the colocated decode path
        reads as ``block[0, i]``, so emitting it here keeps the
        prefill→decode handoff bit-identical to colocated serving. One
        host sync per admission round, accounted like _process_block's.
        Off-role slots (max_new > 1, e.g. colocated fallback under a
        quarantined decode pool) are left for the regular decode loop."""
        rows = [i for i, s in enumerate(self.slots)
                if s.active and s.pending_first and s.request is not None
                and s.request.sampling.max_new_tokens <= 1]
        if not rows:
            return
        t_sync0 = time.monotonic_ns()
        # swarmlint: sanctioned-drain
        toks, lps = jax.device_get((self._last_tokens, self._last_lps))
        t_sync1 = time.monotonic_ns()
        self.tracer.span_end(t_sync0, "engine.host_sync", cat="engine")
        self.metrics.counters["engine_host_syncs"].inc()
        self._host_sync_n += 1
        self.metrics.counters["phase_us_host_sync"].inc(
            (t_sync1 - t_sync0) // 1000)
        now = time.time()
        for i in rows:
            s = self.slots[i]
            if not s.active:
                continue
            if s.cancelled:
                self._retire(i, "cancelled")
                continue
            s.pending_first = False
            self._emit_token(i, int(toks[i]), now, logprob=float(lps[i]))
            if s.active:
                # emit retires max_new<=1 on "length"/"eos"; this only
                # fires for a degenerate max_new=0 request
                self._retire(i, "length")

    def _process_block(self, all_toks, all_lps, snapshot,
                       t_dispatch_ns: int = 0, variant: int = -1) -> None:
        """Fetch one dispatched chunk's [K+1, B] token block (+ matching
        raw-model logprobs) with the one host sync and emit its tokens.

        Token (s+1, i) was sampled at write position ``pos0_i + s`` —
        emission stops at a slot's EOS / max_new_tokens / max_seq and the
        remainder of its lane is discarded garbage.
        """
        t_sync0 = time.monotonic_ns()
        # everything else in the hot path rides jit dispatches; this is
        # the scan path's per-chunk drain (the resident emission ring
        # replaces it with one drain per SESSION — _run_resident)
        # swarmlint: sanctioned-drain
        block, lps = jax.device_get((all_toks, all_lps))
        t_sync1 = time.monotonic_ns()
        # the sanctioned sync is itself a span + counter: the flight
        # recorder and bench phase breakdown both need "how much wall
        # time went to host<->device" to be a first-class number
        self.tracer.span_end(t_sync0, "engine.host_sync", cat="engine")
        self.metrics.counters["engine_host_syncs"].inc()
        self._host_sync_n += 1
        self.metrics.counters["phase_us_host_sync"].inc(
            (t_sync1 - t_sync0) // 1000)
        if t_dispatch_ns and variant >= 0:
            # scan-path device time: dispatch -> drained (pipelined
            # chunks overlap, so per-variant sums can exceed wall clock
            # — same stance as phase_us_decode)
            self._prof.dispatch(PROF_DECODE_KEYS[variant], t_dispatch_ns,
                                t_sync1 - t_dispatch_ns)
        self._process_host_block(np.asarray(block), np.asarray(lps),
                                 snapshot, t_dispatch_ns)

    # swarmlint: hot
    def _process_host_block(self, block, lps, snapshot,
                            t_dispatch_ns: int = 0) -> None:
        """Pure host-side half of block processing: emit tokens, retire
        finished slots, close the per-chunk spans. Fed numpy blocks by
        BOTH paths — the scan path after its per-chunk drain, and the
        resident emission ring's ordered callback (where the device is
        never waited on)."""
        # the engine thread parks in the session drain for a whole
        # resident session, so the emission callback is where a live lane
        # proves progress — beat HERE, not just in the loop
        self._beat()
        t_done_ns = time.monotonic_ns()
        if t_dispatch_ns:
            # per-chunk latency, dispatch -> processed (pipelined chunks
            # overlap, so sums can exceed wall clock — documented); on
            # the resident path the stamp is the previous emission, so
            # this is the chunk's device wall time
            self.metrics.counters["phase_us_decode"].inc(
                (t_done_ns - t_dispatch_ns) // 1000)
            # exemplar rid: the chunk covers every snapshot slot; tag it
            # with the first one so a tail decode-chunk bucket opens a
            # representative trace (tuple indexing, no allocation)
            HIST_DECODE_CHUNK.observe(
                (t_done_ns - t_dispatch_ns) / 1e9,
                snapshot[0][1].request_id if snapshot else None)
        now = time.time()
        K = self.decode_chunk
        for i, req, pos0 in snapshot:
            if t_dispatch_ns:
                # one decode-chunk span per live snapshot slot: these are
                # the leaves of a request's exported timeline
                self.tracer.span_end(t_dispatch_ns, "engine.decode_chunk",
                                     cat="engine", rid=req.request_id)
            s = self.slots[i]
            if not s.active or s.request is not req:
                continue  # retired mid-flight (possibly re-admitted)
            if s.cancelled:
                self._retire(i, "cancelled")
                continue
            if s.pending_first:
                # row 0 is the fed token == this slot's prefill sample,
                # which the host deliberately never fetched at admission
                s.pending_first = False
                self._emit_token(i, int(block[0, i]), now,
                                 logprob=float(lps[0, i]))
            for step in range(K):
                if not s.active:
                    break
                if pos0 + step >= self.max_seq:
                    # the cache lane is full; later writes were dropped
                    self._retire(i, "max_seq")
                    break
                self._emit_token(i, int(block[step + 1, i]), now,
                                 logprob=float(lps[step + 1, i]))
            if s.active:
                s.position = pos0 + K

    # swarmlint: hot
    def _emit_token(self, slot_id: int, token: int,
                    now: Optional[float] = None,
                    logprob: Optional[float] = None) -> None:
        """Record a sampled token for a slot, stream it, retire if finished."""
        slot = self.slots[slot_id]
        req = slot.request
        now = now or time.time()
        if slot.first_token_at is None:
            slot.first_token_at = now
            self._lat_first_token.observe(now - req.submitted_at)
            HIST_TTFT.observe(now - req.submitted_at, req.request_id)

        finished_reason = None
        if token == self.eos_id:
            finished_reason = "eos"
        else:
            slot.generated.append(token)
            if logprob is not None:
                slot.logprobs.append(logprob)
            self.total_generated += 1
            self.metrics.rates["tokens_generated"].mark(now)
            self.metrics.counters["tokens_generated"].inc()
            if req.on_token is not None:
                try:
                    req.on_token(req.request_id, token)
                except Exception:
                    logger.exception("on_token callback failed")
            if len(slot.generated) >= req.sampling.max_new_tokens:
                finished_reason = "length"

        if finished_reason is not None:
            self._retire(slot_id, finished_reason)

    def _retire(self, slot_id: int, reason: str) -> None:  # swarmlint: hot
        slot = self.slots[slot_id]
        req = slot.request
        slot.active = False
        slot.request = None
        if self.paged:
            if req is not None and req.keep_pages:
                # rolling KV: hand the conversation's pages to the caller
                # instead of freeing. written_len = host-confirmed written
                # extent (chunk-granular); emitted tokens past it have no
                # K/V yet and ride back as tail_tokens for the caller to
                # prepend to the next turn's suffix (re-feeding rewrites
                # their K/V identically — same context).
                fresh = self.paged.allocator.pages_for(slot_id)
                self.paged.allocator.transfer_to_cache(slot_id, fresh)
                all_pages = list(req.resume_pages or []) + fresh
                written = slot.position
                start = req.resume_len + len(req.prompt)
                tail = list(slot.generated[max(0, written - start):])
                ps = self.paged.page_size
                covering = -(-written // ps) if written > 0 else 0
                kept, extras = all_pages[:covering], all_pages[covering:]
                if extras:
                    self.paged.allocator.add_free(extras)
                if req.on_pages is not None:
                    try:
                        req.on_pages(req.request_id, kept, written, tail)
                    except Exception:
                        logger.exception("on_pages callback failed")
            # pages stay owned (absorbing end-of-chunk garbage writes) until
            # the next admission round zeroes the table row and frees them
            self.paged.allocator.mark_retired(slot_id)
            pins = self._slot_prefix_pins.pop(slot_id, None)
            if pins:
                # eviction/rewrite of these pages can only be DISPATCHED
                # after this point, so any in-flight chunk's reads (issued
                # earlier) complete first — device program order
                self._prefix.unpin(pins)
        elif (req is not None and req.keep_pages
              and reason in ("length", "eos")
              and getattr(self, "_extract_lane_fused", None) is not None):
            # clean finishes only: failure retirements (_fail_all during
            # error recovery) run BEFORE the donated cache/pool buffers
            # are rebuilt, and a device dispatch here would raise on the
            # deleted arrays and kill the recovery itself
            try:
                self._dense_keep_extract(slot_id, slot, req)
            except Exception:
                logger.exception("dense keep extraction failed")
        self.metrics.counters["engine_completed"].inc()
        self.metrics.rates["requests_completed"].mark()
        if req is not None:
            # flight-recorder request timeline (ring write, engine thread)
            self.flight.record_request({
                "rid": req.request_id,
                "priority": req.priority,
                "prompt_len": len(req.prompt) + req.resume_len,
                "generated": len(slot.generated),
                "reason": reason,
                "submitted_at": req.submitted_at,
                "admitted_at": slot.admitted_at,
                "first_token_at": slot.first_token_at,
                "retired_at": time.time(),
                # sanctioned host syncs this request's lifetime spanned,
                # +1 for the drain its retirement rides in (the resident
                # session's drain lands AFTER this record). Scan path:
                # ~one per chunk; resident path: admit + drain (+ final)
                "host_syncs": self._host_sync_n - slot.admit_syncs + 1,
            })
        if req is not None:
            # raw-model logprobs of the generated tokens (parallel list);
            # delivered via request metadata so on_done's signature stays
            req.metadata["logprobs"] = list(slot.logprobs)
        if req and req.on_done is not None:
            try:
                req.on_done(req.request_id, list(slot.generated), reason)
            except Exception:
                logger.exception("on_done callback failed")

    # swarmlint: hot
    def _dense_keep_extract(self, slot_id: int, slot: _Slot,
                            req: GenRequest) -> None:
        """Dense rolling-KV retirement (see _extract_lane in __init__):
        copy the lane's written KV into acquired prefix-pool pages and
        hand custody to on_pages. The last page may be PARTIAL (written
        is mid-page); its tail bytes are stale lane garbage, masked at
        resume by prefix_lens=written. On pool shortage the turn simply
        doesn't roll: no on_pages, the caller's registry keeps its
        previous state (whose pages we then must NOT release)."""
        ps = self._prefix_ps
        written = slot.position
        start = req.resume_len + len(req.prompt)
        tail = list(slot.generated[max(0, written - start):])
        n = -(-written // ps) if written > 0 else 0
        if not (0 < n <= self._prefix_pp_buckets[-1]):
            return
        # escalation ladder for the page budget: plain acquire ->
        # self-reuse (release the superseded SOURCE pages first: their
        # last reads — the resume prefill; this extraction gathers the
        # LANE, not them — were dispatched earlier, so any re-acquirer's
        # writes land after those reads in device program order; without
        # this a resumed conversation needs 2x its footprint live during
        # extraction and rolls starve at half-pool occupancy) ->
        # pressure hook (LRU-evict parked conversations)
        released_source = False
        pages: List[int] = self._prefix.acquire(n)
        if len(pages) != n and req.resume_pages:
            for p in pages:
                self._prefix.release(p)
            for p in req.resume_pages:
                self._prefix.release(p)
            released_source = True
            pages = self._prefix.acquire(n)
        if len(pages) != n and self.on_pool_pressure is not None:
            for p in pages:
                self._prefix.release(p)
            try:
                self.on_pool_pressure(n)
            except Exception:
                logger.exception("pool-pressure callback failed")
            pages = self._prefix.acquire(n)
        if len(pages) != n:
            for p in pages:
                self._prefix.release(p)
            if released_source and req.on_pages is not None:
                # the registry's kept state now references freed pages —
                # hand it an EMPTY state (the serving layer treats
                # pages=[] as restart-next-turn) instead of leaving
                # dangling ids behind
                try:
                    req.on_pages(req.request_id, [], 0, [])
                except Exception:
                    logger.exception("on_pages callback failed")
            return
        target = np.zeros(self.max_seq // ps, np.int32)
        target[: n] = pages
        pk, pv = self._prefix_pool
        t0_ns = time.monotonic_ns() if self._prof.enabled else 0
        try:
            pk, pv = self._extract_lane_fused(
                self.cache, pk, pv, np.int32(slot_id), target)
            if t0_ns:
                self._prof.dispatch("extract.lane", t0_ns,
                                    time.monotonic_ns() - t0_ns)
        except Exception:
            # dispatch failed: nothing read `pages` on device — return
            # them. If the source pages were already self-reuse-released
            # above, the registry still references freed ids: hand it an
            # empty state (review r5 #2: letting _rolling_finalize free
            # st["pages"] AGAIN would put duplicates on the free list —
            # two conversations acquiring the same page)
            for p in pages:
                self._prefix.release(p)
            if released_source and req.on_pages is not None:
                try:
                    req.on_pages(req.request_id, [], 0, [])
                except Exception:
                    logger.exception("on_pages callback failed")
            raise
        self._prefix_pool = (pk, pv)
        if req.resume_pages and not released_source:
            # superseded SOURCE pages (safe for the same program-order
            # reason as the early release above)
            for p in req.resume_pages:
                self._prefix.release(p)
        if req.on_pages is not None:
            try:
                req.on_pages(req.request_id, pages, written, tail)
            except Exception:
                logger.exception("on_pages callback failed")

    def _fail_all(self, reason: str) -> None:
        for i, s in enumerate(self.slots):
            if s.active:
                self._retire(i, reason)
        with self._cv:
            pending = [item[3] for item in self._queue]
            self._queue.clear()
            self._admitting.clear()
            self._cancel_pending.clear()
        for req in pending:
            if req.on_done is not None:
                try:
                    req.on_done(req.request_id, [], reason)
                except Exception:
                    pass

    # ------------------------------------------------------------------ info

    def stats(self) -> Dict[str, Any]:
        # caught by swarmlint SWL301 on landing the guard declarations:
        # len() of a mutating heap from outside the engine lock
        with self._cv:
            queued = len(self._queue)
        out = {
            "active_slots": sum(1 for s in self.slots if s.active),
            "max_batch": self.max_batch,
            "queued": queued,
            "total_requests": self.total_requests,
            "total_generated": self.total_generated,
            "tokens_per_sec_60s": self.metrics.rates["tokens_generated"].rate(),
            "latencies": {
                k: self.metrics.latencies[k].summary()
                for k in ("queue_wait_s", "prefill_s", "first_token_s")
                if k in self.metrics.latencies
            },
        }
        if self._prefix is not None:
            out["prefix_cache"] = self._prefix.stats()
        if self.paged is not None:
            out["pool_headroom"] = round(self._pool_headroom(), 4)
            out["admission_paused"] = self._bp_paused
        return out
