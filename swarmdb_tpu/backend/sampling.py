"""Token sampling — fixed-shape, jit-compatible, per-slot parameters.

Continuous batching means every decode step samples for B slots at once,
each slot with its OWN temperature/top-k/top-p and its own PRNG stream. All
branching is arithmetic (no Python control flow), so one compiled sampler
serves every parameter combination (SURVEY §7 "masked sampling").

Randomness: each slot has a base key; the key for a given step is
``fold_in(base_key, position)`` — deterministic per (slot seed, position),
so replays reproduce and no key state needs threading through the step.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    """Per-request knobs. temperature=0 means greedy (argmax).

    ``stop`` is HOST-side: the serving layer watches decoded text, cancels
    the engine request at the first match, and truncates the reply — the
    compiled sampler never sees it (string matching has no place in a
    fixed-shape TPU program)."""

    temperature: float = 0.0
    top_k: int = 0        # 0 = disabled
    top_p: float = 1.0    # 1.0 = disabled
    max_new_tokens: int = 128
    stop: tuple = ()      # stop strings (each ends generation when seen)
    seed: "int | None" = None  # per-request PRNG seed (None = engine default)


def make_slot_keys(seed: int, batch: int) -> jnp.ndarray:
    """[B, 2] uint32 base keys, one per slot."""
    base = jax.random.PRNGKey(seed)
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(batch))


def token_logprob(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """log P(token) under the RAW model distribution (before temperature /
    filtering — the OpenAI-style logprob convention). [B, V], [B] -> [B]."""
    ls = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(ls, tokens[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] fp32
    base_keys: jnp.ndarray,     # [B, 2] uint32 per-slot base keys
    positions: jnp.ndarray,     # [B] int32 current position (PRNG fold value)
    temperature: jnp.ndarray,   # [B] fp32; 0 => greedy
    top_k: jnp.ndarray,         # [B] int32; 0 => off
    top_p: jnp.ndarray,         # [B] fp32; 1.0 => off
    *,
    use_filters: bool = True,
    assume_greedy: bool = False,
) -> jnp.ndarray:
    """Sample one token per row; greedy rows (temperature==0) take argmax.

    Filtering: temperature-scale -> top-k mask -> top-p (nucleus) mask ->
    categorical, all with static shapes.

    ``use_filters`` / ``assume_greedy`` are TRACE-TIME switches the engine
    flips per chunk from host-visible slot state (it knows every active
    slot's sampling params). The default traces the full pipeline; at
    large batch both the [B, V] sort behind top-k/p AND the [B, V] Gumbel
    draw behind categorical are comparable to the model matmuls
    themselves, so the common all-greedy population compiles down to one
    argmax, and filter-free-but-sampled populations skip the sort.
    """
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)
    if assume_greedy:
        # host guarantees every live row has temperature == 0
        return greedy.astype(jnp.int32)

    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    if not use_filters:
        step_keys = jax.vmap(jax.random.fold_in)(base_keys, positions)
        sampled = jax.vmap(jax.random.categorical)(step_keys, scaled)
        return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)

    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    # top-k: keep entries >= k-th largest (k<=0 disables)
    k_eff = jnp.clip(jnp.where(top_k > 0, top_k, V), 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
    keep_k = scaled >= kth

    # top-p: smallest prefix of the sorted distribution with mass >= top_p
    probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    in_nucleus = (cum - probs_sorted) < top_p[:, None]
    # the argmax must survive any top_p (even <= 0, which would otherwise
    # empty the nucleus and make every row sample token 0)
    in_nucleus = in_nucleus.at[:, 0].set(True)
    cutoff = jnp.min(
        jnp.where(in_nucleus, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    keep_p = scaled >= cutoff

    filtered = jnp.where(keep_k & keep_p, scaled, -jnp.inf)
    step_keys = jax.vmap(jax.random.fold_in)(base_keys, positions)
    sampled = jax.vmap(jax.random.categorical)(step_keys, filtered)

    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
