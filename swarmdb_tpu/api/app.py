"""HTTP API server — the reference's wire surface on aiohttp.

Route-for-route parity with the reference FastAPI app (`api.py:365-935`,
table in SURVEY §2.5): same paths, methods, payload schemas, auth rules
(JWT HS256, ``sub`` = agent id, username ``admin`` = superuser), per-IP
sliding-window rate limiting, CORS, and env-var config names. FastAPI is
not in this image, so the server is aiohttp; schemas stay pydantic so the
wire contract is identical.

Fixed reference defects: D3 (response models match actual payloads), D4
(no ``status`` name shadowing — we return explicit HTTP codes).

TPU extension (north star): ``POST /messages`` and ``POST /groups/message``
accept ``stream: true`` and reply with SSE. With a serving engine attached
(``create_app(serving=...)``) the events are LLM decode tokens streamed off
the TPU; without one, the message lifecycle events stream instead.

Blocking SwarmDB calls run in the default executor so consumer polls never
stall the event loop.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, Optional

from aiohttp import web
from pydantic import ValidationError

from ..core.messages import MessageStatus
from ..core.runtime import SwarmDB
from ..obs import HISTOGRAMS, TRACER, propagate
from ..obs.kerncheck import enabled as kerncheck_enabled
from ..obs.pagecheck import enabled as pagecheck_enabled
from ..obs.memprof import memprof, memprof_enabled
from ..obs.profiler import profile_enabled, profiler as kernel_profiler
from ..utils import jwt as jwt_util
from ..utils.sync import lockcheck_enabled
from . import schemas

logger = logging.getLogger("swarmdb_tpu.api")

ADMIN_USERNAME = "admin"  # reference: "admin" is the authorization superuser

DB_KEY: web.AppKey = web.AppKey("db", object)
CONFIG_KEY: web.AppKey = web.AppKey("config", object)
SERVING_KEY: web.AppKey = web.AppKey("serving", object)
HA_KEY: web.AppKey = web.AppKey("ha_node", object)

# /metrics role encoding (one gauge, stable codes — a flap shows up as a
# step in the time series, not a relabel)
_HA_ROLE_CODES = {"follower": 0, "leader": 1, "deposed": 2, "dead": 3}


@dataclass
class ApiConfig:
    """Env-var backed config; names match the reference catalog
    (`README.md:78-100`, `api.py:38-74`)."""

    jwt_secret_key: str = "change-me-in-production"
    token_expire_minutes: float = 30.0
    rate_limit_per_minute: int = 300
    cors_origins: str = "*"
    host: str = "0.0.0.0"
    port: int = 8000
    # If set, the "admin" username requires this password. The reference
    # accepts ANY non-empty credentials (`api.py:373-374`) which makes every
    # authorization check moot; unset keeps that demo parity but logs loudly.
    admin_password: Optional[str] = None
    # Worker recycling (gunicorn max_requests+jitter counterpart,
    # `gunicorn_config.py:28-34`): after ~this many requests the process
    # drains and exits gracefully; the supervisor (compose
    # restart-unless-stopped, k8s) brings a fresh one up. 0 = never.
    max_requests: int = 0
    max_requests_jitter: int = 0

    @classmethod
    def from_env(cls) -> "ApiConfig":
        import os

        return cls(
            jwt_secret_key=os.environ.get("JWT_SECRET_KEY", "change-me-in-production"),
            token_expire_minutes=float(os.environ.get("TOKEN_EXPIRE_MINUTES", "30")),
            rate_limit_per_minute=int(os.environ.get("RATE_LIMIT_PER_MINUTE", "300")),
            cors_origins=os.environ.get("CORS_ORIGINS", "*"),
            host=os.environ.get("API_HOST", "0.0.0.0"),
            port=int(os.environ.get("API_PORT", "8000")),
            admin_password=os.environ.get("ADMIN_PASSWORD") or None,
            max_requests=int(os.environ.get("API_MAX_REQUESTS", "0")),
            max_requests_jitter=int(os.environ.get("API_MAX_REQUESTS_JITTER", "0")),
        )

    def allowed_origin(self, request_origin: Optional[str]) -> Optional[str]:
        """Resolve the Access-Control-Allow-Origin value for one request, or
        None to OMIT the header (deny). CORS_ORIGINS may be '*' or a
        comma-separated allowlist; a listed origin is echoed back verbatim.
        Never emit "null" (sandboxed iframes send Origin: null and browsers
        treat an echoed "null" as a match — OWASP anti-pattern) and never
        widen a miss to "*"."""
        if self.cors_origins.strip() == "*":
            return "*"
        allowed = {o.strip() for o in self.cors_origins.split(",") if o.strip()}
        if request_origin and request_origin in allowed:
            return request_origin
        return None


def _error(status_code: int, detail: Any) -> web.HTTPException:
    exc_cls = {
        400: web.HTTPBadRequest,
        401: web.HTTPUnauthorized,
        403: web.HTTPForbidden,
        404: web.HTTPNotFound,
        409: web.HTTPConflict,
        422: web.HTTPUnprocessableEntity,
        429: web.HTTPTooManyRequests,
        503: web.HTTPServiceUnavailable,
    }.get(status_code, web.HTTPInternalServerError)
    return exc_cls(
        text=json.dumps({"detail": detail}), content_type="application/json"
    )


async def _run_sync(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
    loop = asyncio.get_running_loop()
    if kwargs:
        import functools

        fn = functools.partial(fn, **kwargs)
    return await loop.run_in_executor(None, fn, *args)


async def _parse(request: web.Request, model: type) -> Any:
    try:
        body = await request.json()
    except Exception:
        raise _error(400, "invalid JSON body")
    try:
        return model.model_validate(body)
    except ValidationError as exc:
        # detail is the parsed error list (FastAPI wire shape), not a
        # double-encoded JSON string
        raise _error(422, json.loads(exc.json()))


def _json(model_or_dict: Any, status_code: int = 200) -> web.Response:
    if hasattr(model_or_dict, "model_dump"):
        body = model_or_dict.model_dump(mode="json")
    else:
        body = model_or_dict
    return web.json_response(body, status=status_code)


class RateLimiter:
    """Per-IP sliding 60 s window (reference `RateLimiter`, `api.py:266-314`).
    asyncio-single-threaded, so no lock needed."""

    def __init__(self, limit_per_minute: int) -> None:
        self.limit = limit_per_minute
        self._windows: Dict[str, deque] = {}
        self._ops = 0

    def check(self, ip: str) -> bool:
        now = time.time()
        self._ops += 1
        if self._ops % 4096 == 0:
            # bound memory across IP churn: drop windows that fell idle
            self._windows = {
                k: w for k, w in self._windows.items() if w and w[-1] >= now - 60.0
            }
        win = self._windows.setdefault(ip, deque())
        while win and win[0] < now - 60.0:
            win.popleft()
        if len(win) >= self.limit:
            return False
        win.append(now)
        return True


def create_app(
    db: SwarmDB,
    config: Optional[ApiConfig] = None,
    serving: Optional[Any] = None,
    on_max_requests: Optional[Any] = None,
    ha_node: Optional[Any] = None,
) -> web.Application:
    """Build the application. ``serving`` is an optional
    :class:`~swarmdb_tpu.backend.service.ServingService` that turns
    LLM-addressed messages into streamed replies. ``on_max_requests``
    fires ONCE when ``cfg.max_requests`` (+ random jitter) requests have
    been served — the worker-recycling hook (the server entry point exits
    gracefully; its supervisor restarts a fresh process). ``ha_node`` is
    an optional :class:`~swarmdb_tpu.ha.node.HANode` this process runs
    under — it feeds the /health HA block, the ``GET /admin/ha`` status
    route, and the ``swarmdb_ha_*`` /metrics gauges."""
    cfg = config or ApiConfig()
    limiter = RateLimiter(cfg.rate_limit_per_minute)
    recycle_at: Optional[int] = None
    if cfg.max_requests > 0:
        import random

        # jitter staggers a fleet's recycles (gunicorn_config.py:33-34)
        recycle_at = cfg.max_requests + random.randint(
            0, max(0, cfg.max_requests_jitter))
    served_requests = {"n": 0, "fired": False}
    if cfg.admin_password is None:
        logger.warning(
            "ADMIN_PASSWORD not set: any client can obtain an admin token "
            "(reference demo parity, api.py:373-374). Set it in production."
        )

    # ---------------------------------------------------------------- auth

    def current_agent(request: web.Request) -> str:
        """Bearer-token dependency (reference `get_current_agent`,
        `api.py:337-361`)."""
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            raise _error(401, "missing bearer token")
        try:
            claims = jwt_util.decode(auth[len("Bearer "):], cfg.jwt_secret_key)
        except jwt_util.ExpiredTokenError:
            raise _error(401, "token expired")
        except jwt_util.JWTError as exc:
            raise _error(401, f"invalid token: {exc}")
        sub = claims.get("sub")
        if not sub:
            raise _error(401, "token missing subject")
        return sub

    def require_admin(agent: str) -> None:
        if agent != ADMIN_USERNAME:
            raise _error(403, "admin privileges required")

    # ---------------------------------------------------------- middlewares

    @web.middleware
    async def middleware(request: web.Request, handler: Any) -> web.StreamResponse:
        t_req = TRACER.span_begin()
        # CORS preflight
        if request.method == "OPTIONS":
            resp: web.StreamResponse = web.Response(status=204)
        else:
            # rate limit everything except health (reference exempts nothing,
            # but probing liveness through a 429 defeats the healthcheck)
            if request.path != "/health":
                ip = request.remote or "unknown"
                if not limiter.check(ip):
                    resp = web.json_response(
                        {"detail": "rate limit exceeded"}, status=429
                    )
                    _add_cors(resp, request.headers.get("Origin"))
                    return resp
            try:
                resp = await handler(request)
            except web.HTTPException as exc:
                # convert to a plain response (returning the exception object
                # is deprecated in aiohttp)
                resp = web.Response(
                    status=exc.status, text=exc.text,
                    content_type=exc.content_type or "application/json",
                )
            except Exception:
                # unexpected failure: still a JSON body WITH CORS headers,
                # or browser clients see an opaque CORS error instead of 500.
                # (SSE handlers contain their own errors post-prepare — see
                # _stream_reply/_stream_group — so no second response can be
                # written over an already-streaming connection.)
                logger.exception("unhandled error on %s %s",
                                 request.method, request.path)
                resp = web.json_response({"detail": "internal error"}, status=500)
        # API-route span: the root of every request's exported timeline
        # (SSE streams close it when the stream ends, so a streamed reply
        # span covers the full decode)
        TRACER.span_end(t_req, "api.request", cat="api",
                        args={"method": request.method,
                              "path": request.path,
                              "status": resp.status})
        _add_cors(resp, request.headers.get("Origin"))
        if recycle_at is not None and request.path != "/health":
            served_requests["n"] += 1
            if (served_requests["n"] >= recycle_at
                    and not served_requests["fired"]):
                served_requests["fired"] = True
                logger.info("max_requests reached (%d); recycling worker",
                            served_requests["n"])
                if on_max_requests is not None:
                    try:
                        on_max_requests()
                    except Exception:
                        logger.exception("max_requests hook failed")
        return resp

    def _add_cors(resp: web.StreamResponse, origin: Optional[str] = None) -> None:
        acao = cfg.allowed_origin(origin)
        if acao is not None:
            resp.headers["Access-Control-Allow-Origin"] = acao
        resp.headers["Access-Control-Allow-Methods"] = "GET, POST, PUT, DELETE, OPTIONS"
        resp.headers["Access-Control-Allow-Headers"] = "Authorization, Content-Type"

    # -------------------------------------------------------------- handlers

    async def auth_token(request: web.Request) -> web.Response:
        """POST /auth/token (reference `api.py:365-388`): demo-grade — any
        non-empty username/password is accepted; sub = username."""
        creds = await _parse(request, schemas.UserCredentials)
        if not creds.username or not creds.password:
            raise _error(401, "empty credentials")
        if (
            creds.username == ADMIN_USERNAME
            and cfg.admin_password is not None
            and creds.password != cfg.admin_password
        ):
            raise _error(401, "invalid admin credentials")
        token = jwt_util.create_access_token(
            creds.username, cfg.jwt_secret_key, cfg.token_expire_minutes
        )
        return _json(schemas.Token(access_token=token))

    async def register_agent(request: web.Request) -> web.Response:
        """POST /agents/register (reference `api.py:391-437`): self or admin."""
        agent = current_agent(request)
        req = await _parse(request, schemas.AgentRegistrationRequest)
        if agent != ADMIN_USERNAME and agent != req.agent_id:
            raise _error(403, "can only register yourself (or be admin)")
        meta = {
            "description": req.description,
            "capabilities": req.capabilities,
            **req.metadata,
        }
        created = await _run_sync(db.register_agent, req.agent_id, meta,
                                  req.adopt_backlog)
        return _json(
            {"status": "registered" if created else "already_registered",
             "agent_id": req.agent_id}
        )

    async def deregister_agent(request: web.Request) -> web.Response:
        """DELETE /agents/{agent_id} (reference `api.py:440-469`)."""
        agent = current_agent(request)
        target = request.match_info["agent_id"]
        if agent != ADMIN_USERNAME and agent != target:
            raise _error(403, "can only deregister yourself (or be admin)")
        removed = await _run_sync(db.deregister_agent, target)
        if not removed:
            raise _error(404, f"agent {target} not registered")
        return _json({"status": "deregistered", "agent_id": target})

    async def send_message(request: web.Request) -> web.StreamResponse:
        """POST /messages (reference `api.py:472-504`): sender is the token
        subject. With ``stream: true`` replies over SSE (TPU extension)."""
        agent = current_agent(request)
        req = await _parse(request, schemas.MessageRequest)
        msg_id = await _run_sync(
            db.send_message,
            agent,
            req.receiver_id,
            req.content,
            message_type=req.message_type,
            priority=req.priority,
            metadata=req.metadata,
        )
        if req.stream:
            return await _stream_reply(request, msg_id)
        msg = await _run_sync(db.get_message, msg_id)
        if msg is None:
            raise _error(404, "message vanished after send")
        return _json(schemas.MessageResponse.from_message(msg))

    async def broadcast(request: web.Request) -> web.Response:
        """POST /messages/broadcast (reference `api.py:507-536`; returns the
        dict the reference actually produced — defect D3 fixed by declaring
        it)."""
        agent = current_agent(request)
        req = await _parse(request, schemas.BroadcastRequest)
        msg_id = await _run_sync(
            db.broadcast_message,
            agent,
            req.content,
            message_type=req.message_type,
            priority=req.priority,
            metadata=req.metadata,
            exclude_agents=req.exclude_agents,
        )
        return _json(schemas.BroadcastResponse(status="broadcast", message_id=msg_id))

    async def get_message(request: web.Request) -> web.Response:
        """GET /messages/{message_id} (reference `api.py:539-568`):
        admin/sender/receiver/visible_to only."""
        agent = current_agent(request)
        msg = await _run_sync(db.get_message, request.match_info["message_id"])
        if msg is None:
            raise _error(404, "message not found")
        allowed = (
            agent == ADMIN_USERNAME
            or agent == msg.sender_id
            or agent == msg.receiver_id
            or agent in msg.visible_to
        )
        if not allowed:
            raise _error(403, "not authorized to view this message")
        return _json(schemas.MessageResponse.from_message(msg))

    async def query_messages(request: web.Request) -> web.Response:
        """GET /messages (reference `api.py:571-621`): non-admin restricted
        to own traffic. (Reference defect D4 — `status` shadowing — does not
        arise: codes are explicit.)"""
        agent = current_agent(request)
        q = request.query
        sender = q.get("sender_id")
        receiver = q.get("receiver_id")
        involving = None
        if agent != ADMIN_USERNAME:
            # restrict to own traffic; the `involving` filter runs DB-side
            # BEFORE the limit, so the caller's messages can't be crowded
            # out of the page by other agents' newer traffic
            if sender is None and receiver is None:
                involving = agent
            elif agent not in (sender, receiver):
                raise _error(403, "non-admin may only query own messages")
        try:
            msgs = await _run_sync(
                db.query_messages,
                sender_id=sender,
                receiver_id=receiver,
                message_type=q.get("message_type"),
                status=q.get("status"),
                start_time=float(q["start_time"]) if "start_time" in q else None,
                end_time=float(q["end_time"]) if "end_time" in q else None,
                limit=int(q.get("limit", "100")),
                involving=involving,
            )
        except ValueError as exc:
            raise _error(422, str(exc))
        return _json([schemas.MessageResponse.from_message(m).model_dump(mode="json")
                      for m in msgs])

    async def agent_messages(request: web.Request) -> web.Response:
        """GET /agents/{agent_id}/messages (reference `api.py:624-664`)."""
        agent = current_agent(request)
        target = request.match_info["agent_id"]
        if agent != ADMIN_USERNAME and agent != target:
            raise _error(403, "can only read your own inbox (or be admin)")
        q = request.query
        try:
            msgs = await _run_sync(
                db.get_agent_messages,
                target,
                status=q.get("status"),
                limit=int(q.get("limit", "100")),
                skip=int(q.get("skip", "0")),
            )
        except ValueError as exc:
            raise _error(422, str(exc))
        return _json([schemas.MessageResponse.from_message(m).model_dump(mode="json")
                      for m in msgs])

    async def receive(request: web.Request) -> web.Response:
        """POST /agents/receive (reference `api.py:667-688`): broker poll for
        the calling agent."""
        agent = current_agent(request)
        req = await _parse(request, schemas.ReceiveRequest)
        msgs = await _run_sync(
            db.receive_messages, agent,
            max_messages=req.max_messages, timeout=req.timeout,
        )
        return _json([schemas.MessageResponse.from_message(m).model_dump(mode="json")
                      for m in msgs])

    async def update_status(request: web.Request) -> web.Response:
        """PUT /messages/{message_id}/status (reference `api.py:691-733`):
        admin or receiver; PROCESSED goes through the dedicated method."""
        agent = current_agent(request)
        msg = await _run_sync(db.get_message, request.match_info["message_id"])
        if msg is None:
            raise _error(404, "message not found")
        if agent != ADMIN_USERNAME and agent != msg.receiver_id:
            raise _error(403, "only the receiver (or admin) may update status")
        req = await _parse(request, schemas.StatusUpdateRequest)
        if req.status == MessageStatus.PROCESSED:
            ok = await _run_sync(db.mark_message_as_processed, msg.id)
        else:
            ok = await _run_sync(db.update_message_status, msg.id, req.status)
        if not ok:
            raise _error(404, "message vanished during update")
        return _json({"status": "updated", "message_id": msg.id,
                      "new_status": req.status.value})

    async def create_group(request: web.Request) -> web.Response:
        """POST /groups (reference `api.py:736-757`)."""
        current_agent(request)
        req = await _parse(request, schemas.AgentGroupRequest)
        if not req.agent_ids:
            raise _error(422, "agent_ids must be non-empty")
        await _run_sync(db.add_agent_group, req.group_name, req.agent_ids)
        return _json({"status": "created", "group_name": req.group_name,
                      "agent_ids": req.agent_ids})

    async def group_message(request: web.Request) -> web.StreamResponse:
        """POST /groups/message (reference `api.py:760-787`; D3 fixed).
        With ``stream: true``, SSE-streams the fan-out replies."""
        agent = current_agent(request)
        req = await _parse(request, schemas.GroupMessageRequest)
        try:
            ids = await _run_sync(
                db.send_to_group, agent, req.group_name, req.content,
                message_type=req.message_type, priority=req.priority,
                metadata=req.metadata,
            )
        except KeyError:
            raise _error(404, f"group {req.group_name} not found")
        if req.stream:
            return await _stream_group(request, ids)
        return _json(schemas.GroupMessageResponse(
            status="sent", group_name=req.group_name, message_ids=ids))

    async def health(request: web.Request) -> web.Response:
        """GET /health (reference `api.py:790-815`): live broker probe.
        With an HA node attached the response carries its role/epoch and
        detector verdict — the compose healthcheck and a load balancer
        read the same surface."""
        ok = await _run_sync(db.broker.healthy)
        tpu_state = None
        if serving is not None and hasattr(serving, "health"):
            try:
                tpu_state = await _run_sync(serving.health)
            except Exception as exc:
                tpu_state = {"status": "error", "error": str(exc)}
        ha_state = None
        if ha_node is not None:
            try:
                full = await _run_sync(ha_node.status)
                ha_state = {k: full.get(k) for k in
                            ("node_id", "role", "epoch", "leader")}
                if "detector" in full:
                    ha_state["detector"] = full["detector"]["state"]
            except Exception as exc:
                ha_state = {"status": "error", "error": str(exc)}
        resp = schemas.HealthResponse(
            status="healthy" if ok else "degraded",
            broker_connected=ok,
            tpu=tpu_state,
            ha=ha_state,
        )
        return _json(resp, 200 if ok else 503)

    async def admin_ha(request: web.Request) -> web.Response:
        """GET /admin/ha — full control-plane status: role, fencing
        epoch, cluster map view, detector state, replication lag, plus
        the recent HA events (promotions/deposals/detector transitions)
        from the flight recorder's event ring. Under partition-level
        leadership the ``partition_leadership`` block carries the
        per-partition table (leader, epoch, replica lag for locally-led
        partitions), leaderships per node, and the leaderless count."""
        require_admin(current_agent(request))
        if ha_node is None:
            raise _error(503, "this process runs without an HA node")
        out = await _run_sync(ha_node.status)
        # partition_serving (ISSUE 14): the serving tier's conversation-
        # locality view — conversations pinned per leader, leaderless
        # count, local/remote split, and the re-pin total
        loc = getattr(serving, "_locality", None)
        if loc is not None:
            try:
                out["partition_serving"] = await _run_sync(loc.stats)
            except Exception:
                logger.exception("locality stats read failed")
        try:
            out["events"] = [
                ev for ev in await _run_sync(ha_node.flight.events)
                if str(ev.get("kind", "")).startswith(("ha.", "chaos."))
            ][-50:]
        except Exception:
            logger.exception("HA event ring read failed")
        return web.json_response(out)

    async def stats(request: web.Request) -> web.Response:
        """GET /stats (reference `api.py:818-838`): admin only."""
        agent = current_agent(request)
        require_admin(agent)
        return _json(schemas.SystemStats(**await _run_sync(db.get_stats)))

    async def admin_save(request: web.Request) -> web.Response:
        """POST /admin/save (reference `api.py:841-861`)."""
        require_admin(current_agent(request))
        path = await _run_sync(db.save_message_history)
        return _json({"status": "saved", "filepath": path})

    async def admin_flush(request: web.Request) -> web.Response:
        """POST /admin/flush (reference `api.py:864-885`)."""
        require_admin(current_agent(request))
        q = request.query
        try:
            max_age = float(q.get("max_age_seconds", str(7 * 24 * 3600)))
        except ValueError as exc:
            raise _error(422, f"bad max_age_seconds: {exc}")
        n = await _run_sync(db.flush_old_messages, max_age)
        return _json({"status": "flushed", "archived_count": n})

    async def admin_resend(request: web.Request) -> web.Response:
        """POST /admin/resend_failed (reference `api.py:888-912`)."""
        require_admin(current_agent(request))
        ids = await _run_sync(db.resend_failed_messages)
        return _json({"status": "resent", "message_ids": ids})

    async def admin_scale(request: web.Request) -> web.Response:
        """POST /admin/scale_partitions (reference `api.py:915-935`)."""
        require_admin(current_agent(request))
        n = await _run_sync(db.auto_scale_partitions)
        return _json({"status": "scaled", "num_partitions": n})

    async def admin_llm_backend(request: web.Request) -> web.Response:
        """POST /admin/llm_backend: attach an agent to a generation
        backend over the wire. The reference keeps assign_llm_backend
        Python-only (` main.py:1293-1311`) — without this route a deployed
        server has no way to make an agent LLM-backed at runtime."""
        require_admin(current_agent(request))
        req = await _parse(request, schemas.LlmBackendRequest)
        if not req.agent_id or not req.backend_id:
            raise _error(422, "agent_id and backend_id must be non-empty")
        known = await _run_sync(lambda: req.agent_id in db.registered_agents)
        if not known:
            raise _error(404, f"agent {req.agent_id} not registered")
        await _run_sync(db.assign_llm_backend, req.agent_id, req.backend_id)
        return _json({"status": "assigned", "agent_id": req.agent_id,
                      "backend_id": req.backend_id})

    async def metrics(request: web.Request) -> web.Response:
        """GET /metrics: Prometheus text exposition of the runtime's
        counters/rates/latency percentiles. Unauthenticated by scraper
        convention; exposes aggregate numbers only, never message
        content or per-agent identity (the per-agent detail stays behind
        the admin-scoped /stats)."""
        snap = await _run_sync(db.metrics.snapshot)
        lines = []

        def _name(k: str) -> str:
            return "swarmdb_" + "".join(
                c if c.isalnum() or c == "_" else "_" for c in k)

        for k, v in sorted(snap["counters"].items()):
            if k.startswith("agent_recv:"):
                continue
            n = _name(k)
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {v}")
        for k, v in sorted(snap["rates"].items()):
            if k.startswith("agent_recv:"):
                continue
            n = _name(k) + "_per_second"
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {v}")
        for k, s in sorted(snap["latencies"].items()):
            n = _name(k)
            lines.append(f"# TYPE {n} summary")
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                if s.get(key) is not None:
                    lines.append(f'{n}{{quantile="{q}"}} {s[key]}')
            lines.append(f"{n}_count {int(s.get('count') or 0)}")
        # fixed-bucket latency histograms (obs/metrics.py, ISSUE 6):
        # TTFT, queue wait, decode chunk, data-plane RTT, replication
        # commit — Prometheus histogram exposition with STABLE bucket
        # boundaries, so p50/p99-over-time exist outside bench runs.
        # Buckets that retained a trace-id exemplar carry it in
        # OpenMetrics exemplar syntax (ISSUE 7): the id resolves via
        # /admin/trace/export?trace_id= (links on /admin/slo).
        lines.extend(HISTOGRAMS.render_prometheus(exemplars=True))
        # online SLO sentinel gauges (obs/sentinel.py): breached flag,
        # window p95s, per-completion cost by category — the pageable
        # surface; /admin/slo carries the attributed alerts
        sentinel = getattr(db, "sentinel", None)
        if sentinel is not None:
            lines.extend(await _run_sync(sentinel.prometheus_lines))
        # lane supervision (ISSUE 9): per-lane health state + beat age
        # (0=alive, 1=suspect, 2=quarantined). The migration/shed/retry
        # counters (swarmdb_requests_migrated / _shed / _retried) are
        # plain registry counters and already rendered above.
        supervisor = getattr(serving, "supervisor", None)
        if supervisor is not None:
            lines.extend(await _run_sync(supervisor.prometheus_lines))
        # runtime lock sanitizer (ISSUE 12, SWARMDB_LOCKCHECK=1):
        # per-site contended-acquire and cumulative-hold counters for
        # the top SWARMDB_LOCKCHECK_TOPN sites, plus the inversion-
        # cycle count (>0 is a pager line: a detected deadlock order)
        if lockcheck_enabled():
            from ..obs import lockcheck

            lines.extend(await _run_sync(
                lockcheck.registry().prometheus_lines))
        # page-pool gauges (ISSUE 13 observability satellite): flag-
        # independent — rendered straight off the serving engine's
        # allocator/prefix stats, so capacity dashboards see
        # allocated/pinned/free headroom whether or not the sanitizer
        # is on. Under SWARMDB_PAGECHECK=1 the registry adds shadow-
        # state gauges, per-lane churn counters, and the violation
        # count (>0 is a pager line: a detected page-safety bug).
        paged = getattr(getattr(serving, "engine", None), "paged", None)
        if paged is not None:
            try:
                pstats = await _run_sync(paged.allocator.stats)
            except Exception:
                logger.exception("page-pool stats read failed")
                pstats = None
            if pstats is not None:
                free = int(pstats.get("free_pages", 0))
                total = int(pstats.get("num_pages", 0))
                trash = int(pstats.get("n_shards")
                            or pstats.get("lanes") or 1)
                pinned = 0
                prefstats = None
                prefix = getattr(serving.engine, "_prefix", None)
                if prefix is not None:
                    try:
                        prefstats = await _run_sync(prefix.stats)
                        pinned = int(prefstats.get("pinned_pages", 0))
                    except Exception:
                        prefstats = None
                        pinned = 0
                lines.append("# TYPE swarmdb_page_free gauge")
                lines.append(f"swarmdb_page_free {free}")
                lines.append("# TYPE swarmdb_page_allocated gauge")
                lines.append(
                    f"swarmdb_page_allocated "
                    f"{max(0, total - trash - free)}")
                lines.append("# TYPE swarmdb_page_pinned gauge")
                lines.append(f"swarmdb_page_pinned {pinned}")
                churn = pstats.get("churn_by_lane") or [
                    (pstats.get("pages_allocated_total", 0),
                     pstats.get("pages_freed_total", 0))]
                lines.append(
                    "# TYPE swarmdb_pages_allocated_total counter")
                lines.append("# TYPE swarmdb_pages_freed_total counter")
                for lane, (a, f) in enumerate(churn):
                    lbl = f'{{lane="lane{lane}"}}'
                    lines.append(
                        f"swarmdb_pages_allocated_total{lbl} {a}")
                    lines.append(
                        f"swarmdb_pages_freed_total{lbl} {f}")
                # prefix-cache lookup gauges (ISSUE 17 satellite): the
                # per-lookup counters only reached bench records before;
                # full_misses/lookups climbing is the anchor-jump
                # signature (runbook step 14), flag-independent like the
                # page gauges above
                if prefstats is not None:
                    lines.append(
                        "# TYPE swarmdb_prefix_lookups_total counter")
                    lines.append(f"swarmdb_prefix_lookups_total "
                                 f"{prefstats.get('lookups', 0)}")
                    lines.append(
                        "# TYPE swarmdb_prefix_full_misses_total counter")
                    lines.append(f"swarmdb_prefix_full_misses_total "
                                 f"{prefstats.get('full_misses', 0)}")
                    lines.append(
                        "# TYPE swarmdb_prefix_cached_pages gauge")
                    lines.append(f"swarmdb_prefix_cached_pages "
                                 f"{prefstats.get('cached_pages', 0)}")
                    lines.append(
                        "# TYPE swarmdb_prefix_hit_tokens_total counter")
                    lines.append(f"swarmdb_prefix_hit_tokens_total "
                                 f"{prefstats.get('hit_tokens', 0)}")
                    lines.append(
                        "# TYPE swarmdb_prefix_miss_tokens_total counter")
                    lines.append(f"swarmdb_prefix_miss_tokens_total "
                                 f"{prefstats.get('miss_tokens', 0)}")
        # tier gauges (ISSUE 19): flag-independent like the page-pool
        # gauges — pages by tier plus the demote/promote/cold-resume
        # counters, rendered off the live TierManager. Without one the
        # hot gauge still renders (everything device-resident is "hot")
        # so dashboards keep a stable series across deployments.
        tier = getattr(serving, "_tier", None)
        if tier is not None:
            try:
                tstatus = await _run_sync(tier.status)
            except Exception:
                logger.exception("tier status read failed")
                tstatus = None
            if tstatus is not None:
                lines.append("# TYPE swarmdb_tier_pages gauge")
                for name in ("hot", "warm", "cold"):
                    lines.append(
                        f'swarmdb_tier_pages{{tier="{name}"}} '
                        f"{tstatus['pages'].get(name, 0)}")
                tcounters = tstatus.get("counters", {})
                for cname in ("demotions", "promotions", "cold_resumes"):
                    lines.append(
                        f"# TYPE swarmdb_tier_{cname}_total counter")
                    lines.append(f"swarmdb_tier_{cname}_total "
                                 f"{tcounters.get(cname, 0)}")
        elif paged is not None:
            try:
                pstats2 = await _run_sync(paged.allocator.stats)
                hot = max(0, int(pstats2.get("num_pages", 0)) - 1
                          - int(pstats2.get("free_pages", 0)))
            except Exception:
                hot = 0
            lines.append("# TYPE swarmdb_tier_pages gauge")
            lines.append(f'swarmdb_tier_pages{{tier="hot"}} {hot}')
            lines.append('swarmdb_tier_pages{tier="warm"} 0')
            lines.append('swarmdb_tier_pages{tier="cold"} 0')
            for cname in ("demotions", "promotions", "cold_resumes"):
                lines.append(f"# TYPE swarmdb_tier_{cname}_total counter")
                lines.append(f"swarmdb_tier_{cname}_total 0")
        if pagecheck_enabled():
            from ..obs import pagecheck

            lines.extend(await _run_sync(
                pagecheck.registry().prometheus_lines))
        if kerncheck_enabled():
            from ..obs import kerncheck

            lines.extend(await _run_sync(
                kerncheck.registry().prometheus_lines))
        # swarmprof (ISSUE 15, SWARMDB_PROFILE — default on): aggregate
        # MFU, per-lane duty cycles, per-variant device seconds /
        # invocations. The pager line is swarmdb_mfu (or a lane's duty)
        # falling while throughput holds — the sentinel attributes it,
        # /admin/profile carries the full roofline table.
        if profile_enabled():
            lines.extend(await _run_sync(
                kernel_profiler().prometheus_lines))
        # swarmmem (ISSUE 17, SWARMDB_MEMPROF — default on): occupancy
        # decomposition, conversation temperature, the sampled
        # miss-ratio curve. The pager line is
        # swarmdb_mem_headroom_pages shrinking while
        # swarmdb_conversation_temperature{state="cold"} grows — parked
        # KV is crowding out admission (runbook step 14).
        if memprof_enabled():
            lines.extend(await _run_sync(memprof().prometheus_lines))
        # replication lag (acks=all deployments): per-follower fsync-
        # watermark lag so the back-pressure path is observable instead
        # of silent — a disconnected follower shows up here as growing
        # lag_records and connected=0 while DELIVERED reports stall
        repl_stats = getattr(db.broker, "replication_stats", None)
        if repl_stats is not None:
            try:
                followers = await _run_sync(repl_stats)
            except Exception:
                logger.exception("replication_stats failed")
                followers = []
            if followers:
                lines.append("# TYPE swarmdb_replica_lag_records gauge")
                lines.append("# TYPE swarmdb_replica_lag_seconds gauge")
                lines.append("# TYPE swarmdb_replica_connected gauge")
                lines.append("# TYPE swarmdb_replica_gapped_partitions gauge")
                for f in followers:
                    lbl = f'{{follower="{f["target"]}"}}'
                    lines.append(
                        f"swarmdb_replica_lag_records{lbl} {f['lag_records']}")
                    lines.append(
                        f"swarmdb_replica_lag_seconds{lbl} {f['lag_seconds']}")
                    lines.append(
                        f"swarmdb_replica_connected{lbl} "
                        f"{1 if f['connected'] else 0}")
                    lines.append(
                        f"swarmdb_replica_gapped_partitions{lbl} "
                        f"{f['gapped']}")
        # HA control plane (ISSUE 4): role / fencing epoch / failure-
        # detector verdict, the gauges an alerting rule pages on — a
        # deposed leader (role=2) or a detector stuck SUSPECT (state=1)
        # is an incident even while traffic still flows
        if ha_node is not None:
            try:
                st = await _run_sync(ha_node.status)
            except Exception:
                logger.exception("HA status read failed")
                st = None
            if st is not None:
                role_code = _HA_ROLE_CODES.get(st.get("role"), -1)
                lines.append("# TYPE swarmdb_ha_role gauge")
                lines.append(
                    f'swarmdb_ha_role{{node="{st["node_id"]}",'
                    f'role="{st.get("role")}"}} {role_code}')
                lines.append("# TYPE swarmdb_ha_epoch gauge")
                lines.append(f"swarmdb_ha_epoch {st.get('epoch', 0)}")
                if st.get("cluster_epoch") is not None:
                    lines.append("# TYPE swarmdb_ha_cluster_epoch gauge")
                    lines.append(
                        f"swarmdb_ha_cluster_epoch {st['cluster_epoch']}")
                det = st.get("detector")
                if det:
                    # 0=alive 1=suspect 2=dead (DetectorState codes)
                    lines.append("# TYPE swarmdb_ha_detector_state gauge")
                    lines.append(
                        f'swarmdb_ha_detector_state{{state='
                        f'"{det["state"]}"}} {det["state_code"]}')
                    lines.append(
                        "# TYPE swarmdb_ha_detector_signal_age_seconds "
                        "gauge")
                    lines.append(
                        f"swarmdb_ha_detector_signal_age_seconds "
                        f"{det['signal_age_s']}")
                # partition-level leadership (ISSUE 10): leaderships per
                # node + the leaderless count — the pager line for "a
                # partition has no leader" is the leaderless gauge > 0
                # outlasting the failover budget
                pl = st.get("partition_leadership")
                if pl:
                    lines.append(
                        "# TYPE swarmdb_partition_leaderships gauge")
                    for nid, n in sorted(
                            (pl.get("leaderships") or {}).items()):
                        lines.append(
                            f'swarmdb_partition_leaderships'
                            f'{{node="{nid}"}} {n}')
                    lines.append(
                        "# TYPE swarmdb_partition_leaderless gauge")
                    lines.append(
                        f"swarmdb_partition_leaderless "
                        f"{pl.get('leaderless', 0)}")
                    # rebalance convergence (ISSUE 14): how long the
                    # last orphan episode (kill -> every orphan
                    # re-seated) took, as observed by this node — the
                    # first-class number the scaled drills bound
                    conv = pl.get("rebalance_convergence_s")
                    if conv is not None:
                        lines.append(
                            "# TYPE swarmdb_rebalance_convergence_"
                            "seconds gauge")
                        lines.append(
                            f"swarmdb_rebalance_convergence_seconds "
                            f"{conv}")
        # conversation locality (ISSUE 14): how many served
        # conversations are pinned to a partition this node leads
        # (local) vs a peer (remote) vs a leaderless partition
        # mid-failover — remote > 0 on a converged cluster means the
        # serving tier and the log ownership have drifted apart
        loc = getattr(serving, "_locality", None)
        if loc is not None:
            try:
                ls = await _run_sync(loc.stats)
            except Exception:
                logger.exception("locality stats read failed")
                ls = None
            if ls is not None:
                lines.append("# TYPE swarmdb_conversation_locality gauge")
                for state in ("local", "remote", "leaderless"):
                    lines.append(
                        f'swarmdb_conversation_locality'
                        f'{{state="{state}"}} {ls.get(state, 0)}')
                lines.append("# TYPE swarmdb_conversation_repins_total "
                             "counter")
                lines.append(f"swarmdb_conversation_repins_total "
                             f"{ls.get('repins', 0)}")
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    def _trace_query(request: web.Request):
        """Shared ?last_n= / ?trace_id= parsing for the trace routes."""
        q = request.query
        last_n = None
        if q.get("last_n"):
            try:
                last_n = max(0, int(q["last_n"]))
            except ValueError:
                raise _error(422, f"bad last_n: {q['last_n']!r}")
        return last_n, (q.get("trace_id") or None)

    async def trace_export(request: web.Request) -> web.Response:
        """GET /admin/trace/export — the span tracer's buffered events as
        Chrome trace-event JSON (load in https://ui.perfetto.dev or
        chrome://tracing). Covers every layer that records spans: API
        routes, runtime send/receive, broker publish, engine admission/
        prefill/decode chunks/host syncs, and message stage marks.

        BOUNDED (ISSUE 6 satellite): ``?trace_id=`` keeps one trace
        (plus HA instants), ``?last_n=`` the newest N spans, and an
        unconditional cap (``SWARMDB_TRACE_EXPORT_MAX``, default 50000
        events) stops a long-lived node from returning an unbounded
        body; ``metadata.truncated`` says when the cap bit."""
        require_admin(current_agent(request))
        last_n, trace_id = _trace_query(request)
        trace = await _run_sync(
            lambda: TRACER.to_chrome_trace(last_n=last_n, rid=trace_id))
        if profile_enabled():
            # device-time tracks (swarmprof dispatch rings) merged next
            # to the host spans they explain: one "device:<lane>" track
            # per lane, variant-named complete events
            trace = await _run_sync(
                lambda: kernel_profiler().merge_chrome_trace(trace))
        return web.json_response(trace)

    async def cluster_trace(request: web.Request) -> web.Response:
        """GET /admin/cluster/trace — ONE merged Perfetto-loadable trace
        for the whole cluster (ISSUE 6 tentpole): fans out to every node
        in the cluster map over the data plane's ``trace_export`` op,
        merges the per-node rings by re-anchored wall clock, and dedups
        (in-process clusters share a tracer). Dead/unreachable nodes are
        skipped and listed in ``metadata.unreachable`` — a failover
        trace must survive the dead leader it documents. Same
        ``?last_n=`` / ``?trace_id=`` filters as /admin/trace/export;
        with ``trace_id`` the merge keeps that trace's spans plus every
        node's HA instants (promotion/fencing land in the timeline)."""
        require_admin(current_agent(request))
        last_n, trace_id = _trace_query(request)
        # the local process is always a source (API + engine spans live
        # here even when this process runs no HA node)
        local = await _run_sync(
            lambda: TRACER.to_chrome_trace(last_n=last_n, rid=trace_id))
        sources = [(propagate.node_id(), local)]
        unreachable = []
        cluster = (ha_node.cluster if ha_node is not None
                   else getattr(db.broker, "cluster", None))

        def _fan_out():
            from ..ha.dataplane import RemoteBroker

            try:
                state = cluster.read()
            except Exception as exc:
                unreachable.append({"node": "<cluster-map>",
                                    "error": str(exc)})
                return
            for nid, info in sorted(state.get("nodes", {}).items()):
                addr = (info or {}).get("data_addr")
                if not addr:
                    continue
                rb = RemoteBroker(addr, timeout_s=2.0)
                try:
                    out = rb.trace_export(last_n=last_n, trace_id=trace_id)
                    sources.append((out.get("node", nid), out["trace"]))
                except Exception as exc:
                    unreachable.append({"node": nid, "error": str(exc)})
                finally:
                    rb.close()

        if cluster is not None:
            await _run_sync(_fan_out)
        merged = propagate.merge_chrome_traces(sources)
        merged["metadata"]["unreachable"] = unreachable
        if trace_id:
            merged["metadata"]["trace_id"] = trace_id
        return web.json_response(merged)

    async def admin_slo(request: web.Request) -> web.Response:
        """GET /admin/slo — the online SLO sentinel (ISSUE 7): config
        and learned baseline, the last closed window's per-completion
        cost decomposition and p95s, the attributed alert ring (each
        alert names the dominant contributor, shares summing to 1, and
        points at its auto-dumped flight + trace files), and the
        histogram exemplars with ready-made ``?trace_id=`` export links
        so a tail bucket opens a real request timeline. ``?tick=1``
        forces a window-close check first (freshness for pollers on an
        otherwise idle node)."""
        require_admin(current_agent(request))
        sentinel = getattr(db, "sentinel", None)
        if sentinel is None:
            raise _error(503, "this runtime has no SLO sentinel")
        if request.query.get("tick"):
            await _run_sync(sentinel.maybe_tick)
        return web.json_response(await _run_sync(sentinel.status))

    async def flight_record(request: web.Request) -> web.Response:
        """GET /admin/flight — the engine flight recorder's current rings
        (last N engine steps + last M request timelines), plus the most
        recent automatic dump if a restart already took one.
        ``?last=1`` returns only that last automatic dump."""
        require_admin(current_agent(request))
        if serving is None or not hasattr(serving, "engine"):
            raise _error(503, "no serving engine attached")
        flight = serving.engine.flight
        if request.query.get("last"):
            if flight.last_dump is None:
                raise _error(404, "no automatic dump taken yet")
            return web.json_response(flight.last_dump)
        return web.json_response(await _run_sync(flight.dump))

    async def admin_lockcheck(request: web.Request) -> web.Response:
        """GET /admin/lockcheck — the runtime lock sanitizer's full
        report (SWARMDB_LOCKCHECK=1): per-site acquire/contention/hold
        stats, the observed acquisition-order edges (site pair, thread,
        first-observation stack), and any inversion cycles. 503 with
        the flag off — an empty report would read as "no deadlock
        orders" when nothing was watching."""
        require_admin(current_agent(request))
        if not lockcheck_enabled():
            raise _error(503, "lock sanitizer off — set "
                              "SWARMDB_LOCKCHECK=1")
        from ..obs import lockcheck

        return web.json_response(
            await _run_sync(lockcheck.registry().report))

    async def admin_pagecheck(request: web.Request) -> web.Response:
        """GET /admin/pagecheck — the runtime page sanitizer's full
        report (SWARMDB_PAGECHECK=1): per-pool shadow-state counts,
        per-lane churn, and every recorded violation (double-free,
        use-after-free canary, epoch mismatch, cross-lane aliasing)
        with owners and stacks. 503 with the flag off — an empty
        report would read as "no page bugs" when nothing watched."""
        require_admin(current_agent(request))
        if not pagecheck_enabled():
            raise _error(503, "page sanitizer off — set "
                              "SWARMDB_PAGECHECK=1")
        from ..obs import pagecheck

        return web.json_response(
            await _run_sync(pagecheck.registry().report))

    async def admin_kerncheck(request: web.Request) -> web.Response:
        """GET /admin/kerncheck — the interpreter-mode kernel
        sanitizer's full report (SWARMDB_KERNCHECK=1): per-check shadow
        run tallies and every recorded violation (out-of-bounds block /
        Ref slice, grid write race, short-written output row, kernel-vs-
        reference parity break) with the offending kernel, grid cell and
        slice. 503 with the flag off — an empty report would read as
        "no kernel bugs" when nothing watched."""
        require_admin(current_agent(request))
        if not kerncheck_enabled():
            raise _error(503, "kernel sanitizer off — set "
                              "SWARMDB_KERNCHECK=1")
        from ..obs import kerncheck

        return web.json_response(
            await _run_sync(kerncheck.registry().report))

    async def admin_profile(request: web.Request) -> web.Response:
        """GET /admin/profile — the swarmprof report (ISSUE 15): the
        platform peak table, every compiled variant's invocations /
        device seconds / harvested FLOPs+bytes / achieved-FLOPs MFU /
        arithmetic intensity / roofline class, per-lane duty cycles,
        and the dispatch-shape profile (wave kind x width, tiny ragged
        flush waves named). 503 with SWARMDB_PROFILE=0 — an empty
        report would read as "no device time spent" when nothing was
        watching."""
        require_admin(current_agent(request))
        if not profile_enabled():
            raise _error(503, "profiler off — unset SWARMDB_PROFILE=0")
        return web.json_response(
            await _run_sync(kernel_profiler().report))

    async def admin_mem(request: web.Request) -> web.Response:
        """GET /admin/mem — the swarmmem report (ISSUE 17): per-pool
        occupancy decomposition + page residency ages, the
        hot/warm/cold conversation temperature ledger, the SHARDS-
        sampled miss-ratio curve, and the warm-tier / cold-resume
        what-if models that size ROADMAP item 3. 503 with
        SWARMDB_MEMPROF=0 — an empty ledger would read as "no pages
        resident" when nothing was watching."""
        require_admin(current_agent(request))
        if not memprof_enabled():
            raise _error(503, "memory accountant off — unset "
                              "SWARMDB_MEMPROF=0")
        return web.json_response(await _run_sync(memprof().report))

    async def admin_tiers(request: web.Request) -> web.Response:
        """GET /admin/tiers — the conversation-state tier hierarchy
        (ISSUE 19): pages by tier (hot device / warm host-RAM / cold
        log-replay), warm-store byte occupancy and LRU churn, the
        demote/promote/cold-resume counters, the measured warm hit
        rate, and the live config (demote watermark, min idle,
        warm capacity). Always answers: without a tier manager the
        payload is ``{"enabled": false}`` plus the hot page count, so
        "is tiering even on" is a curl, not a log dig."""
        require_admin(current_agent(request))
        tier = getattr(serving, "_tier", None)
        if tier is not None:
            return web.json_response(await _run_sync(tier.status))
        out: Dict[str, Any] = {"enabled": False,
                               "pages": {"hot": 0, "warm": 0, "cold": 0}}
        paged = getattr(getattr(serving, "engine", None), "paged", None)
        if paged is not None:
            try:
                pstats = await _run_sync(paged.allocator.stats)
                out["pages"]["hot"] = max(
                    0, int(pstats.get("num_pages", 0)) - 1
                    - int(pstats.get("free_pages", 0)))
            except Exception:
                logger.exception("page-pool stats read failed")
        return web.json_response(out)

    async def admin_lanes(request: web.Request) -> web.Response:
        """GET /admin/lanes — the lane supervisor's full status: per-lane
        state machine (alive/suspect/quarantined), beat ages, quarantine
        and restart counts, and the migration/retry/shed/deadline
        counters ("a lane is quarantined — what to check", runbook
        step 7)."""
        require_admin(current_agent(request))
        supervisor = getattr(serving, "supervisor", None)
        if supervisor is None:
            raise _error(503, "no lane supervisor attached")
        return web.json_response(await _run_sync(supervisor.status))

    async def dashboard(request: web.Request) -> web.Response:
        """GET /dashboard: self-contained observability page (the
        kafka-ui counterpart — reference dockerfile-compose.yaml:51-62).
        The page holds no data; it fetches /health + /stats with the
        operator's pasted bearer token."""
        from .dashboard import DASHBOARD_HTML

        return web.Response(text=DASHBOARD_HTML, content_type="text/html")

    async def agent_load(request: web.Request) -> web.Response:
        """GET /agents/{agent_id}/load — inbox size, unread count, trailing
        msgs/sec. The reference computes this (` main.py:1049-1094`) but
        never exposes it over HTTP (SURVEY §5.5); here it is first-class.
        Self or admin."""
        agent = current_agent(request)
        target = request.match_info["agent_id"]
        if agent != target and agent != ADMIN_USERNAME:
            raise _error(403, "can only read your own load (or be admin)")
        return _json(await _run_sync(db.get_agent_load, target))

    async def profile_start(request: web.Request) -> web.Response:
        """POST /admin/profile/start — begin a jax.profiler trace
        (SURVEY §5.1: the reference has no tracing at all; this captures
        XLA/TPU timelines viewable in TensorBoard/Perfetto)."""
        require_admin(current_agent(request))
        import jax

        trace_dir = (request.query.get("dir")
                     or os.path.join(db.save_dir, "profiles"))
        try:
            # off the event loop: trace setup touches the device backend
            await _run_sync(jax.profiler.start_trace, trace_dir)
        except Exception as exc:  # already tracing / profiler unavailable
            raise _error(409, str(exc))
        return _json({"status": "tracing", "trace_dir": trace_dir})

    async def profile_stop(request: web.Request) -> web.Response:
        """POST /admin/profile/stop — end the jax.profiler trace."""
        require_admin(current_agent(request))
        import jax

        try:
            # stop serializes the whole collected trace (can be seconds) —
            # never on the event loop or every live SSE stream stalls
            await _run_sync(jax.profiler.stop_trace)
        except Exception as exc:  # not tracing
            raise _error(409, str(exc))
        return _json({"status": "stopped"})

    # ----------------------------------------------------------- SSE helpers

    async def _sse_response(request: web.Request) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        _add_cors(resp, request.headers.get("Origin"))
        await resp.prepare(request)
        return resp

    async def _sse_event(resp: web.StreamResponse, event: str, data: Any) -> None:
        payload = json.dumps(data) if not isinstance(data, str) else data
        await resp.write(f"event: {event}\ndata: {payload}\n\n".encode())

    async def _stream_reply(request: web.Request, msg_id: str) -> web.StreamResponse:
        """SSE stream for one message: LLM decode tokens when a serving
        engine is attached (north star), else the message lifecycle.

        Once the stream response is prepared, NO exception may escape —
        aiohttp would try to write a second (500) response over a connection
        that already sent text/event-stream headers. Errors are reported as
        SSE "error" events when the transport still works, else swallowed
        (client went away)."""
        resp = await _sse_response(request)
        try:
            msg = await _run_sync(db.get_message, msg_id)
            if msg is not None:
                await _sse_event(
                    resp, "message",
                    schemas.MessageResponse.from_message(msg).model_dump(mode="json"))
            if serving is not None and msg is not None:
                try:
                    async for tok in serving.stream_reply(msg):
                        await _sse_event(resp, "token", tok)
                    reply_id = msg.metadata.get("reply_id")
                    reply = await _run_sync(db.get_message, reply_id) if reply_id else None
                    if reply is not None:
                        await _sse_event(
                            resp, "reply",
                            schemas.MessageResponse.from_message(reply).model_dump(mode="json"))
                except Exception as exc:
                    await _sse_event(resp, "error", {"detail": str(exc)})
            await _sse_event(resp, "done", {"message_id": msg_id})
            await resp.write_eof()
        except (ConnectionResetError, ConnectionError, asyncio.CancelledError):
            logger.debug("SSE client disconnected during /messages stream")
        except Exception:
            logger.exception("error inside prepared SSE stream")
        return resp

    async def _stream_group(request: web.Request, ids: list) -> web.StreamResponse:
        """Same post-prepare exception containment as _stream_reply."""
        resp = await _sse_response(request)
        try:
            group_msgs = []
            for mid in ids:
                m = await _run_sync(db.get_message, mid)
                if m is None:
                    continue  # flushed/deleted between send and stream
                group_msgs.append(m)
                await _sse_event(
                    resp, "message",
                    schemas.MessageResponse.from_message(m).model_dump(mode="json"))
            if serving is not None:
                try:
                    async for item in serving.stream_group(group_msgs):
                        await _sse_event(resp, item.get("event", "token"), item)
                except Exception as exc:
                    await _sse_event(resp, "error", {"detail": str(exc)})
            await _sse_event(resp, "done", {"message_ids": ids})
            await resp.write_eof()
        except (ConnectionResetError, ConnectionError, asyncio.CancelledError):
            logger.debug("SSE client disconnected during group stream")
        except Exception:
            logger.exception("error inside prepared SSE stream")
        return resp

    # ---------------------------------------------------------------- wiring

    app = web.Application(middlewares=[middleware])
    app[DB_KEY] = db
    app[CONFIG_KEY] = cfg
    app[SERVING_KEY] = serving
    app[HA_KEY] = ha_node
    app.add_routes([
        web.post("/auth/token", auth_token),
        web.post("/agents/register", register_agent),
        web.delete("/agents/{agent_id}", deregister_agent),
        web.post("/messages", send_message),
        web.post("/messages/broadcast", broadcast),
        web.get("/messages/{message_id}", get_message),
        web.get("/messages", query_messages),
        web.get("/agents/{agent_id}/messages", agent_messages),
        web.post("/agents/receive", receive),
        web.put("/messages/{message_id}/status", update_status),
        web.post("/groups", create_group),
        web.post("/groups/message", group_message),
        web.get("/health", health),
        web.get("/stats", stats),
        web.post("/admin/save", admin_save),
        web.post("/admin/flush", admin_flush),
        web.post("/admin/resend_failed", admin_resend),
        web.post("/admin/scale_partitions", admin_scale),
        web.post("/admin/llm_backend", admin_llm_backend),
        # TPU-build additions (no reference routes)
        web.get("/metrics", metrics),
        web.get("/dashboard", dashboard),
        web.get("/agents/{agent_id}/load", agent_load),
        web.post("/admin/profile/start", profile_start),
        web.post("/admin/profile/stop", profile_stop),
        web.get("/admin/trace/export", trace_export),
        web.get("/admin/cluster/trace", cluster_trace),
        web.get("/admin/flight", flight_record),
        web.get("/admin/slo", admin_slo),
        web.get("/admin/ha", admin_ha),
        web.get("/admin/lanes", admin_lanes),
        web.get("/admin/lockcheck", admin_lockcheck),
        web.get("/admin/pagecheck", admin_pagecheck),
        web.get("/admin/kerncheck", admin_kerncheck),
        web.get("/admin/profile", admin_profile),
        web.get("/admin/mem", admin_mem),
        web.get("/admin/tiers", admin_tiers),
    ])

    async def on_shutdown(app: web.Application) -> None:
        # reference `shutdown_event` (`api.py:939-945`)
        await _run_sync(db.close)

    app.on_shutdown.append(on_shutdown)
    return app
