"""Built-in observability dashboard — the kafka-ui counterpart.

The reference deployment ships a kafka-ui container for browsing
topics/consumers (`/root/reference/dockerfile-compose.yaml:51-62`). This
build's equivalent data already exists behind `/stats`, `/health`, and
`/agents/{id}/load`; this module serves a single self-contained HTML page
(GET /dashboard, no build step, no external assets — the image has zero
egress) that polls those routes and renders:

- health + device probe (TPU liveness, engine slots/queue)
- message counters by type/status, send/receive rates
- latency percentiles (send→first-token, prefill, queue wait)
- per-agent table (sent/received, backend assignment, msgs/sec)

Auth: the page itself is public (it contains no data); every data fetch
uses a bearer token the operator pastes once (stored in localStorage).
Admin-scoped routes stay admin-scoped.
"""

from __future__ import annotations

DASHBOARD_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>SwarmDB-TPU dashboard</title>
<style>
  body { font-family: ui-monospace, Menlo, Consolas, monospace;
         margin: 2rem; background: #111; color: #ddd; }
  h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.5rem; }
  table { border-collapse: collapse; margin-top: .5rem; }
  td, th { border: 1px solid #444; padding: .25rem .6rem; text-align: left;
           font-size: .85rem; }
  th { background: #222; }
  .ok { color: #7c7; } .bad { color: #e66; }
  #token { width: 28rem; background: #222; color: #ddd; border: 1px solid #555;
           padding: .3rem; }
  .muted { color: #888; font-size: .8rem; }
</style>
</head>
<body>
<h1>SwarmDB-TPU dashboard</h1>
<p class="muted">bearer token (admin for /stats):
  <input id="token" placeholder="paste access_token">
  <button onclick="saveToken()">use</button>
  <span id="state" class="muted"></span></p>
<h2>Health</h2><div id="health">-</div>
<h2>Engine</h2><div id="engine">-</div>
<h2>Messages</h2><div id="messages">-</div>
<h2>Latencies</h2><div id="latencies">-</div>
<h2>Agents</h2><div id="agents">-</div>
<h2>SLO sentinel</h2><div id="slo">-</div>
<p class="muted">
  <button onclick="download('/admin/slo', 'slo.json')">
    download SLO status</button>
  (baseline, windows, attributed alerts, histogram exemplars with
  trace-export links) &middot; admin token required
</p>
<h2>Tracing &amp; flight recorder</h2>
<p class="muted">
  <button onclick="download('/admin/trace/export', 'trace.json')">
    download Chrome trace</button>
  (open in <a href="https://ui.perfetto.dev" target="_blank">Perfetto</a>
  or chrome://tracing) &middot;
  <button onclick="download('/admin/cluster/trace', 'cluster_trace.json')">
    download CLUSTER trace</button>
  (one merged timeline across every cluster-map node, promotion
  instants included) &middot;
  <button onclick="download('/admin/flight', 'flight.json')">
    download flight record</button>
  (last engine steps + request timelines; auto-dumped on engine restart)
  &middot;
  <button onclick="download('/admin/pagecheck', 'pagecheck.json')">
    download pagecheck report</button>
  (page sanitizer: per-pool shadow states + violations; 503 unless
  SWARMDB_PAGECHECK=1)
  &middot;
  <button onclick="download('/admin/profile', 'profile.json')">
    download swarmprof report</button>
  (per-variant device time / MFU / roofline, lane duty cycles,
  dispatch-shape profile; 503 if SWARMDB_PROFILE=0)
  &middot;
  <button onclick="download('/admin/mem', 'mem.json')">
    download swarmmem report</button>
  (memory accountant: pool occupancy + residency ages, hot/warm/cold
  conversation temperature, sampled miss-ratio curve, warm-tier and
  cold-resume models; 503 if SWARMDB_MEMPROF=0)
  &middot; admin token required
</p>
<script>
function saveToken() {
  localStorage.setItem("swarmdb_token", document.getElementById("token").value);
  refresh();
}
function tok() { return localStorage.getItem("swarmdb_token") || ""; }
// ALL server-derived strings (agent ids, metric keys) are escaped before
// touching innerHTML: agent ids are client-chosen, so an unescaped cell
// would be stored XSS running in the operator's (token-holding) browser.
function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"
  }[c]));
}
function table(rows, header) {
  let h = "<table>";
  if (header) h += "<tr>" + header.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of rows) h += "<tr>" + r.map(c => `<td>${esc(c)}</td>`).join("") + "</tr>";
  return h + "</table>";
}
function fmt(x) {
  if (x === null || x === undefined) return "-";
  if (typeof x === "number") return Number.isInteger(x) ? x : x.toFixed(4);
  return String(x);
}
async function getJSON(path) {
  const r = await fetch(path, {headers: {"Authorization": "Bearer " + tok()}});
  if (!r.ok) throw new Error(path + " -> " + r.status);
  return await r.json();
}
async function download(path, filename) {
  const state = document.getElementById("state");
  try {
    const data = await getJSON(path);
    const blob = new Blob([JSON.stringify(data)],
                          {type: "application/json"});
    const a = document.createElement("a");
    a.href = URL.createObjectURL(blob);
    a.download = filename;
    a.click();
    URL.revokeObjectURL(a.href);
  } catch (err) {
    state.textContent = String(err);
  }
}
async function refresh() {
  const state = document.getElementById("state");
  try {
    const health = await getJSON("/health");
    let hrows = [["status", health.status],
                 ["broker", health.broker_connected],
                 ["version", health.version]];
    if (health.tpu) {
      hrows.push(["device", fmt(health.tpu.device)],
                 ["probe_ms", fmt(health.tpu.probe_ms)]);
    }
    const hdiv = document.getElementById("health");
    hdiv.innerHTML = table(hrows);
    hdiv.className = health.status === "healthy" ? "ok" : "bad";
    if (health.tpu && health.tpu.engine) {
      const e = health.tpu.engine;
      document.getElementById("engine").innerHTML = table([
        ["active_slots", `${e.active_slots} / ${e.max_batch}`],
        ["queued", e.queued],
        ["total_requests", e.total_requests],
        ["total_generated", e.total_generated],
        ["tokens/s (60s)", fmt(e.tokens_per_sec_60s)],
      ]);
    } else {
      document.getElementById("engine").innerHTML =
        '<span class="muted">no serving backend attached</span>';
    }
    const stats = await getJSON("/stats");
    const m = stats.metrics || {};
    const counters = Object.entries(m.counters || {});
    const rates = Object.entries(m.rates || {});
    document.getElementById("messages").innerHTML =
      table([["total", stats.total_messages],
             ...Object.entries(stats.messages_by_type || {}).map(
               ([k, v]) => ["type:" + k, v]),
             ...Object.entries(stats.messages_by_status || {}).map(
               ([k, v]) => ["status:" + k, v]),
             ...rates.map(([k, v]) => ["rate:" + k + " /s", fmt(v)]),
             ...counters.map(([k, v]) => [k, v])]);
    const lat = Object.entries((m.latencies) || {});
    document.getElementById("latencies").innerHTML = lat.length
      ? table(lat.map(([k, v]) =>
          [k, fmt(v.p50), fmt(v.p95), fmt(v.p99), fmt(v.count)]),
          ["metric", "p50", "p95", "p99", "n"])
      : '<span class="muted">none yet</span>';
    // SLO sentinel (admin): its own try so a 503 (no sentinel) or 403
    // doesn't blank the rest of the page
    try {
      const slo = await getJSON("/admin/slo?tick=1");
      const rows = [["breached", slo.breached],
                    ["windows", slo.windows_total],
                    ["alerts", slo.alerts_total],
                    ["baseline", slo.baseline ? "learned" : "warming up"]];
      const last = (slo.alerts || [])[slo.alerts.length - 1];
      if (last) rows.push(
        ["last alert", `${last.id}: dominant ${last.dominant}`]);
      const w = slo.last_window || {};
      if (w.p95_ttft_s != null) rows.push(["p95 TTFT (s)", fmt(w.p95_ttft_s)]);
      if (w.cost_growth_x != null) rows.push(["cost growth x", fmt(w.cost_growth_x)]);
      const sdiv = document.getElementById("slo");
      sdiv.innerHTML = table(rows);
      sdiv.className = slo.breached ? "bad" : "ok";
    } catch (err) {
      document.getElementById("slo").innerHTML =
        '<span class="muted">' + esc(String(err)) + "</span>";
    }
    const agents = Object.entries(stats.messages_by_agent || {});
    document.getElementById("agents").innerHTML = agents.length
      ? table(agents.map(([k, v]) => [k, v.sent, v.received]),
              ["agent", "sent", "received"])
      : '<span class="muted">none</span>';
    state.textContent = "ok @ " + new Date().toLocaleTimeString();
  } catch (err) {
    state.textContent = String(err);
  }
}
document.getElementById("token").value = tok();
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
