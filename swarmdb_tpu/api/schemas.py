"""Wire-API request/response schemas.

Mirrors the reference's pydantic models (`api.py:96-263`) so clients written
against the reference work unchanged. Response models are CORRECT here —
the reference declares ``List[str]`` for broadcast/group responses but
returns dicts (defect D3); we declare what is actually returned.

TPU-build extension: ``MessageRequest.stream`` requests SSE token streaming
of the LLM reply (north star — `/messages` and `/groups/message` stream
decode tokens from TPU HBM).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, Field

from ..core.messages import Message, MessagePriority, MessageStatus, MessageType

MessageContent = Union[str, Dict[str, Any], List[Any]]


class MessageRequest(BaseModel):
    receiver_id: Optional[str] = None
    content: MessageContent
    message_type: MessageType = MessageType.CHAT
    priority: MessagePriority = MessagePriority.NORMAL
    metadata: Dict[str, Any] = Field(default_factory=dict)
    # TPU extension: stream the LLM backend's reply tokens over SSE.
    stream: bool = False


class MessageResponse(BaseModel):
    id: str
    sender_id: str
    receiver_id: Optional[str]
    content: MessageContent
    type: str
    priority: int
    timestamp: float
    status: str
    metadata: Dict[str, Any]
    token_count: Optional[int] = None
    visible_to: List[str] = Field(default_factory=list)

    @classmethod
    def from_message(cls, m: Message) -> "MessageResponse":
        # Reference `MessageResponse.from_message` (`api.py:118-139`).
        return cls(
            id=m.id,
            sender_id=m.sender_id,
            receiver_id=m.receiver_id,
            content=m.content,
            type=m.type.value,
            priority=m.priority.value,
            timestamp=m.timestamp,
            status=m.status.value,
            metadata=m.metadata,
            token_count=m.token_count,
            visible_to=m.visible_to,
        )


class BroadcastRequest(BaseModel):
    content: MessageContent
    message_type: MessageType = MessageType.CHAT
    priority: MessagePriority = MessagePriority.NORMAL
    metadata: Dict[str, Any] = Field(default_factory=dict)
    exclude_agents: List[str] = Field(default_factory=list)


class BroadcastResponse(BaseModel):
    status: str
    message_id: str


class LlmBackendRequest(BaseModel):
    agent_id: str
    backend_id: str


class AgentRegistrationRequest(BaseModel):
    agent_id: str
    description: Optional[str] = None
    capabilities: List[str] = Field(default_factory=list)
    metadata: Dict[str, Any] = Field(default_factory=dict)
    # cross-process adoption: drain records produced for this agent before
    # this registration (SwarmDB.register_agent adopt_backlog)
    adopt_backlog: bool = False


class AgentGroupRequest(BaseModel):
    group_name: str
    agent_ids: List[str]


class GroupMessageRequest(BaseModel):
    group_name: str
    content: MessageContent
    message_type: MessageType = MessageType.CHAT
    priority: MessagePriority = MessagePriority.NORMAL
    metadata: Dict[str, Any] = Field(default_factory=dict)
    stream: bool = False


class GroupMessageResponse(BaseModel):
    status: str
    group_name: str
    message_ids: List[str]


class ReceiveRequest(BaseModel):
    max_messages: int = 10
    timeout: float = 5.0


class StatusUpdateRequest(BaseModel):
    status: MessageStatus


class HealthResponse(BaseModel):
    status: str
    broker_connected: bool
    timestamp: float = Field(default_factory=time.time)
    version: str = "0.1.0"
    # TPU extension: device liveness (SURVEY §5.3)
    tpu: Optional[Dict[str, Any]] = None
    # HA extension (ISSUE 4): this node's role/epoch + detector verdict,
    # present only when the process runs under the HA control plane
    ha: Optional[Dict[str, Any]] = None


class SystemStats(BaseModel):
    total_messages: int
    message_count: int
    registered_agents: int
    messages_by_type: Dict[str, int]
    messages_by_status: Dict[str, int]
    messages_by_agent: Dict[str, Dict[str, int]]
    metrics: Dict[str, Any] = Field(default_factory=dict)


class UserCredentials(BaseModel):
    username: str
    password: str


class Token(BaseModel):
    access_token: str
    token_type: str = "bearer"
