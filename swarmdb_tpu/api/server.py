"""Server entry point: ``python -m swarmdb_tpu.api.server``.

Builds SwarmDB + the aiohttp app from environment variables using the
reference's env-var catalog (`README.md:78-100`, `api.py:38-74`,
`gunicorn_config.py`): KAFKA_BOOTSTRAP_SERVERS, KAFKA_GROUP_ID,
KAFKA_NUM_PARTITIONS, KAFKA_TOPIC, SAVE_DIR, AUTOSAVE_INTERVAL,
JWT_SECRET_KEY, TOKEN_EXPIRE_MINUTES, RATE_LIMIT_PER_MINUTE, CORS_ORIGINS,
API_HOST, API_PORT. Unlike the reference (one SwarmsDB per gunicorn worker,
defect D7), this runs ONE process owning the broker; scale-out is via the
serving mesh, not API-process replication.

Optional TPU serving: set SERVE_MODEL (e.g. ``llama3-8b``, ``tiny-debug``)
to attach a generation backend; agent->backend routing then drives real
decode on device.
"""

from __future__ import annotations

import logging
import os

from aiohttp import web

from ..core.messages import BrokerConfig
from ..core.runtime import SwarmDB
from .app import ApiConfig, create_app


def build_db() -> SwarmDB:
    cfg = BrokerConfig(
        bootstrap_servers=os.environ.get("KAFKA_BOOTSTRAP_SERVERS", "localhost:9092"),
        group_id=os.environ.get("KAFKA_GROUP_ID", "swarm_agents"),
        num_partitions=int(os.environ.get("KAFKA_NUM_PARTITIONS", "3")),
        log_dir=os.environ.get("BROKER_LOG_DIR") or None,
        implementation=os.environ.get("BROKER_IMPL", "auto"),
    )
    return SwarmDB(
        config=cfg,
        topic_name=os.environ.get("KAFKA_TOPIC", "swarm_messages"),
        save_dir=os.environ.get("SAVE_DIR", "message_history"),
        autosave_interval=float(os.environ.get("AUTOSAVE_INTERVAL", "300")),
    )


def build_serving(db: SwarmDB):
    model_name = os.environ.get("SERVE_MODEL")
    if not model_name:
        return None
    try:
        from ..backend.service import ServingService
    except ImportError as exc:
        raise SystemExit(
            f"SERVE_MODEL={model_name!r} requires the serving backend "
            f"(swarmdb_tpu.backend.service): {exc}"
        )
    serving = ServingService.from_model_name(db, model_name)
    if db.token_counter is None:
        # explicit wiring (not a constructor side effect): the deployment's
        # single backend tokenizer fills Message.token_count — the counter
        # the reference keeps pluggable but never supplies (` main.py:295`)
        db.token_counter = serving.tokenizer.count
    return serving


def main() -> None:
    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    from ..parallel.distributed import init_distributed, is_coordinator

    if init_distributed():
        # Multi-host pod: one HTTP ingress (coordinator) owns the broker
        # and API; every process sees the global mesh via jax.devices().
        # Non-coordinator worker participation in the SPMD decode program
        # is driven by the engine's multi-host path; running a second,
        # independent API here would silently serve duplicate traffic —
        # refuse loudly instead (SURVEY §7 single-controller-vs-SPMD).
        if not is_coordinator():
            raise SystemExit(
                "this process is not the coordinator (SWARMDB_PROCESS_ID != 0); "
                "the HTTP API runs on host 0 only"
            )
    db = build_db()
    serving = build_serving(db)
    cfg = ApiConfig.from_env()
    app = create_app(db, cfg, serving=serving)
    if serving is not None:
        serving.start()
    web.run_app(app, host=cfg.host, port=cfg.port)


if __name__ == "__main__":
    main()
