"""Server entry point: ``python -m swarmdb_tpu.api.server``.

Builds SwarmDB + the aiohttp app from environment variables using the
reference's env-var catalog (`README.md:78-100`, `api.py:38-74`,
`gunicorn_config.py`): KAFKA_BOOTSTRAP_SERVERS, KAFKA_GROUP_ID,
KAFKA_NUM_PARTITIONS, KAFKA_TOPIC, SAVE_DIR, AUTOSAVE_INTERVAL,
JWT_SECRET_KEY, TOKEN_EXPIRE_MINUTES, RATE_LIMIT_PER_MINUTE, CORS_ORIGINS,
API_HOST, API_PORT. Unlike the reference (one SwarmsDB per gunicorn worker,
defect D7), this runs ONE process owning the broker; scale-out is via the
serving mesh, not API-process replication.

Optional TPU serving: set SERVE_MODEL (e.g. ``llama3-8b``, ``tiny-debug``)
to attach a generation backend; agent->backend routing then drives real
decode on device.

High availability (ISSUE 4): two mutually exclusive env modes —

- ``SWARMDB_HA_NODE_ID`` (+ ``SWARMDB_HA_CLUSTER``): this process IS a
  cluster node. An :class:`~swarmdb_tpu.ha.node.HANode` supervises the
  broker (failure detection, fenced promotion); the runtime writes
  through the node's role facade, and /health, /admin/ha and the
  ``swarmdb_ha_*`` /metrics gauges expose the control plane.
- ``SWARMDB_HA_CLUSTER`` alone: this process is a CLIENT of an external
  HA cluster — SwarmDB binds a ClusterBroker that re-points to the
  current leader on failover (handled in core/runtime.py).
"""

from __future__ import annotations

import logging
import os

from aiohttp import web

from ..core.messages import BrokerConfig
from ..core.runtime import SwarmDB
from .app import ApiConfig, create_app


def build_ha_node():
    """Embedded HA node, when this server process is a cluster member
    (``SWARMDB_HA_NODE_ID`` + ``SWARMDB_HA_CLUSTER`` set). Returns the
    started :class:`~swarmdb_tpu.ha.node.HANode` or None."""
    node_id = os.environ.get("SWARMDB_HA_NODE_ID")
    cluster_path = os.environ.get("SWARMDB_HA_CLUSTER")
    if not node_id:
        return None
    if not cluster_path:
        raise SystemExit(
            "SWARMDB_HA_NODE_ID is set but SWARMDB_HA_CLUSTER is not — an "
            "HA node needs the shared cluster-map path")
    from ..broker.local import LocalBroker
    from ..ha.cluster import FileClusterMap
    from ..ha.node import HANode

    log_dir = os.environ.get("BROKER_LOG_DIR") or "ha_broker_log"
    impl = os.environ.get("BROKER_IMPL", "auto")
    broker = None
    if impl in ("auto", "native"):
        try:
            from ..broker.native import NativeBroker, native_available

            if native_available():
                broker = NativeBroker(log_dir=log_dir)
        except Exception:
            if impl == "native":
                raise
    if broker is None:
        broker = LocalBroker(
            snapshot_path=os.path.join(log_dir, "snapshot.json"))
    listen = os.environ.get("SWARMDB_HA_LISTEN", "0.0.0.0:9444")
    liveness = os.environ.get("SWARMDB_HA_LIVENESS", "0.0.0.0:9445")
    data = os.environ.get("SWARMDB_HA_DATA", "0.0.0.0:9446")
    host, _, port = listen.rpartition(":")
    _, _, lport = liveness.rpartition(":")
    _, _, dport = data.rpartition(":")
    node = HANode(
        node_id, broker, FileClusterMap(cluster_path),
        listen_host=host or "0.0.0.0", replica_port=int(port),
        liveness_port=int(lport),
        data_port=None if dport == "off" else int(dport),
        advertise_host=os.environ.get("SWARMDB_HA_ADVERTISE_HOST"),
        log_dir=log_dir,
        # deployment entry point = cluster mode: partition leadership
        # defaults ON here (SWARMDB_HA_PARTITION_LEADERSHIP overrides)
        cluster_mode=True,
    )
    node.start(role=os.environ.get("SWARMDB_HA_ROLE", "follower"))
    return node


def build_db(ha_node=None) -> SwarmDB:
    cfg = BrokerConfig(
        bootstrap_servers=os.environ.get("KAFKA_BOOTSTRAP_SERVERS", "localhost:9092"),
        group_id=os.environ.get("KAFKA_GROUP_ID", "swarm_agents"),
        num_partitions=int(os.environ.get("KAFKA_NUM_PARTITIONS", "3")),
        log_dir=os.environ.get("BROKER_LOG_DIR") or None,
        implementation=os.environ.get("BROKER_IMPL", "auto"),
    )
    broker = None
    if ha_node is not None:
        # node-level mode: the per-call role facade (acks=all + fencing
        # while leading, read-only mirror as follower). Partition mode
        # (ISSUE 14): a per-partition-routing ClusterBroker whose opener
        # short-circuits THIS node — every produce reaches the owning
        # partition leader instead of fencing on the local facade, which
        # is what lets partition leadership default ON for cluster nodes
        broker = ha_node.client_broker()
    return SwarmDB(
        config=cfg,
        topic_name=os.environ.get("KAFKA_TOPIC", "swarm_messages"),
        save_dir=os.environ.get("SAVE_DIR", "message_history"),
        autosave_interval=float(os.environ.get("AUTOSAVE_INTERVAL", "300")),
        broker=broker,
    )


def _serve_knobs() -> dict:
    """Engine shape knobs — must be IDENTICAL on every host of a pod (the
    worker replays the coordinator's compiled calls shape-for-shape)."""
    return {
        "max_batch": int(os.environ.get("SERVE_MAX_BATCH", "8")),
        "max_seq": int(os.environ.get("SERVE_MAX_SEQ", "1024")),
        "decode_chunk": int(os.environ.get("SERVE_CHUNK", "8")),
        "seed": int(os.environ.get("SERVE_SEED", "0")),
    }


def _build_pod_engine(model_name: str):
    """Sharded engine over the GLOBAL mesh — same construction on every
    host so device state starts identical (parallel/multihost.py)."""
    from ..backend.tokenizer import default_tokenizer
    from ..parallel.serving import build_serving_engine

    k = _serve_knobs()
    engine, sm = build_serving_engine(
        model_name, max_batch=k["max_batch"], max_seq=k["max_seq"],
        seed=k["seed"], decode_chunk=k["decode_chunk"],
    )
    tokenizer = default_tokenizer(sm.cfg.vocab_size,
                                  os.environ.get("SERVE_TOKENIZER") or None)
    return engine, tokenizer


def build_serving(db: SwarmDB, distributed: bool = False, ha_node=None):
    model_name = os.environ.get("SERVE_MODEL")
    if not model_name:
        return None
    try:
        from ..backend.service import ServingService
    except ImportError as exc:
        raise SystemExit(
            f"SERVE_MODEL={model_name!r} requires the serving backend "
            f"(swarmdb_tpu.backend.service): {exc}"
        )
    if distributed:
        engine, tokenizer = _build_pod_engine(model_name)
        engine.enable_multihost()
        serving = ServingService(db, engine, tokenizer)
    else:
        serving = ServingService.from_model_name(db, model_name)
    # conversation locality rides partition leadership (ISSUE 14): lane
    # pins follow partition leaders and re-pin on rebalance events
    serving.bind_partition_leadership(ha_node)
    if db.token_counter is None:
        # explicit wiring (not a constructor side effect): the deployment's
        # single backend tokenizer fills Message.token_count — the counter
        # the reference keeps pluggable but never supplies (` main.py:295`)
        db.token_counter = serving.tokenizer.count
    return serving


def run_worker() -> None:
    """Non-coordinator pod process: join the SPMD decode program.

    Builds the identical sharded engine over the global mesh and replays
    the coordinator's published device calls until it broadcasts stop
    (Engine.worker_loop). No broker, no HTTP — the single-controller /
    SPMD split of SURVEY §7: host 0 owns the request plane, every host
    executes the tensor plane."""
    model_name = os.environ.get("SERVE_MODEL")
    if not model_name:
        raise SystemExit(
            "worker process needs SERVE_MODEL to build the shared engine"
        )
    engine, _tok = _build_pod_engine(model_name)
    logging.getLogger(__name__).info("worker joined decode program")
    engine.worker_loop()


def build_ssl_context():
    """TLS termination (reference: gunicorn keyfile/certfile,
    `/root/reference/gunicorn_config.py:96-126`): set API_SSL_CERT (+
    API_SSL_KEY for a separate key file) to serve HTTPS; absent = HTTP."""
    cert = os.environ.get("API_SSL_CERT")
    if not cert:
        return None
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, os.environ.get("API_SSL_KEY") or None)
    return ctx


def main() -> None:
    from ..utils.logsink import configure_logging

    configure_logging()  # console + optional rotating/compressed LOG_FILE
    # honor JAX_PLATFORMS even on images whose sitecustomize registers a
    # platform plugin at interpreter startup and latches selection before
    # env vars are read (the supported override is the config update)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    from ..parallel.distributed import init_distributed, is_coordinator

    distributed = init_distributed()
    if distributed and not is_coordinator():
        # Multi-host pod: one HTTP ingress (coordinator) owns the broker
        # and API; every process executes the same SPMD decode program
        # over the global mesh. This process joins as a tensor-plane
        # worker (round-2/3 builds refused here; VERDICT #5).
        run_worker()
        return
    ha_node = build_ha_node()
    db = build_db(ha_node=ha_node)
    serving = build_serving(db, distributed=distributed, ha_node=ha_node)
    cfg = ApiConfig.from_env()
    def _recycle() -> None:
        # worker recycling: SIGTERM ourselves; aiohttp drains in-flight
        # requests within shutdown_timeout and the supervisor (compose
        # restart-unless-stopped / k8s) starts a fresh process
        import signal

        os.kill(os.getpid(), signal.SIGTERM)

    if distributed and cfg.max_requests > 0:
        # a recycling coordinator would strand every worker host mid
        # worker_loop and wedge the pod; recycle a pod by rolling ALL its
        # processes from the orchestrator instead. Zero the knob itself so
        # the middleware neither counts nor logs "recycling" misleadingly.
        import dataclasses

        logging.getLogger(__name__).warning(
            "API_MAX_REQUESTS ignored on a multi-host pod coordinator"
        )
        cfg = dataclasses.replace(cfg, max_requests=0)
    app = create_app(db, cfg, serving=serving, on_max_requests=_recycle,
                     ha_node=ha_node)
    if serving is not None:
        serving.start()
    web.run_app(
        app,
        host=cfg.host,
        port=cfg.port,
        ssl_context=build_ssl_context(),
        # bounded graceful drain for in-flight requests/SSE streams on
        # SIGTERM (reference: gunicorn graceful_timeout,
        # `/root/reference/gunicorn_config.py:40-47`)
        shutdown_timeout=float(os.environ.get("API_SHUTDOWN_TIMEOUT", "30")),
    )


if __name__ == "__main__":
    main()
