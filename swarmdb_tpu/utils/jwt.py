"""Minimal HS256 JSON Web Token implementation (stdlib only).

The reference uses python-jose/pyjwt-style HS256 tokens (`api.py:317-361`):
claims ``sub`` (agent id) and ``exp``. Neither library is in this image, and
HS256 is ~20 lines of hmac+base64url, so we implement exactly the subset the
wire API needs. Tokens interoperate with any standard JWT library.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Any, Dict, Optional


class JWTError(Exception):
    pass


class ExpiredTokenError(JWTError):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _b64url_decode(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def encode(claims: Dict[str, Any], secret: str) -> str:
    header = {"alg": "HS256", "typ": "JWT"}
    h = _b64url(json.dumps(header, separators=(",", ":")).encode())
    p = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{h}.{p}".encode("ascii")
    sig = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    return f"{h}.{p}.{_b64url(sig)}"


def decode(token: str, secret: str, verify_exp: bool = True) -> Dict[str, Any]:
    # Any malformation in an attacker-supplied token must surface as
    # JWTError (-> HTTP 401), never as a stray exception (-> HTTP 500):
    # non-ascii header chars, bad base64, non-dict payloads, non-numeric exp.
    try:
        try:
            h, p, s = token.split(".")
            signing_input = f"{h}.{p}".encode("ascii")
            provided = _b64url_decode(s)
        except JWTError:
            raise
        except Exception:
            raise JWTError("malformed token")
        expected = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
        if not hmac.compare_digest(expected, provided):
            raise JWTError("signature mismatch")
        try:
            header = json.loads(_b64url_decode(h))
            claims = json.loads(_b64url_decode(p))
        except Exception:
            raise JWTError("malformed payload")
        if not isinstance(header, dict) or not isinstance(claims, dict):
            raise JWTError("malformed payload")
        if header.get("alg") != "HS256":
            raise JWTError(f"unsupported alg: {header.get('alg')}")
        exp = claims.get("exp")
        if verify_exp and exp is not None:
            try:
                expired = time.time() > float(exp)
            except (TypeError, ValueError):
                raise JWTError("malformed exp claim")
            if expired:
                raise ExpiredTokenError("token expired")
        return claims
    except JWTError:
        raise
    except Exception as exc:  # absolute backstop
        raise JWTError(f"undecodable token: {type(exc).__name__}")


def create_access_token(
    subject: str, secret: str, expires_minutes: float = 30.0,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Reference `create_access_token` (`api.py:317-336`): sub + exp claims."""
    claims = {"sub": subject, "exp": time.time() + expires_minutes * 60.0}
    if extra:
        claims.update(extra)
    return encode(claims, secret)
