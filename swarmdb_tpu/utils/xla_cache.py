"""Persistent XLA compilation cache wiring.

The big-model jit variants (decode chunk, per-bucket prefills) each cost
10-30 s of XLA compile on first use. JAX's persistent compilation cache
stores the compiled executables on disk keyed by HLO hash, so every
process after the first (API server restarts, each bench mode, the
driver's scheduled run) deserializes instead of recompiling — measured on
this image's TPU backend, a cold 11 s compile becomes sub-second.

Opt-in via env (SWARMDB_COMPILE_CACHE=<dir>) or an explicit path; the
bench enables it by default. The reference has no compile step at all
(SURVEY §2.4 — no model code), so there is no counterpart knob.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("swarmdb_tpu.xla_cache")

_ENABLED_DIR: Optional[str] = None


def persistent_cache_programs(path: str) -> set:
    """Distinct compiled-program keys in a persistent-cache directory.

    The cache writes a ``<jit-name>-<hash>-cache`` / ``-atime`` file pair
    per program; this strips the suffix so one program counts once. Used
    by the precompile drift tests (compile-count == variant-count on a
    warm start) and handy for eyeballing what a warmup actually added:
    ``python -c "from swarmdb_tpu.utils.xla_cache import *; \
      print(sorted(persistent_cache_programs('.jax_cache')))"``."""
    try:
        names = os.listdir(path)
    except OSError:
        return set()
    return {n.rsplit("-", 1)[0] for n in names}


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (or the
    SWARMDB_COMPILE_CACHE env var). Returns the directory in effect, or
    None when unconfigured. Idempotent; safe to call before or after the
    backend initializes."""
    global _ENABLED_DIR
    path = path or os.environ.get("SWARMDB_COMPILE_CACHE")
    if not path:
        return _ENABLED_DIR
    if _ENABLED_DIR == path:
        return path
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything that took meaningful compile time; the tiny
        # helper jits (health probe, token scatter) stay out of the cache
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # jax pins the cache object to the dir in effect at FIRST use; a
        # later config update alone is silently ignored. The dir may have
        # been pinned by anyone (env var, direct config update, an earlier
        # call here), so reset unconditionally — a no-op when nothing is
        # pinned yet
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — private API, best effort
            logger.warning("could not reset pinned compilation cache; "
                           "new dir %s may not take effect", path)
        _ENABLED_DIR = path
        logger.info("persistent XLA compilation cache at %s", path)
    except Exception:  # noqa: BLE001 — cache is an optimization, not a dep
        logger.exception("failed to enable compilation cache at %s", path)
        return None
    return _ENABLED_DIR
