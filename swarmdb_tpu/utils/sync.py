"""One lock factory for the whole package (ISSUE 12).

Every module that used to call ``threading.Lock/RLock/Condition()``
directly now allocates through here with a stable **site label**
(``"backend.engine.Engine._cv"``). Two behaviors:

- default (``SWARMDB_LOCKCHECK`` unset/0): returns the plain
  ``threading`` classes — the factory is two dict-free statements, and
  the object handed back is *exactly* what the caller allocated before
  this PR existed (zero overhead, pinned by tests/test_lockcheck.py;
  the bench echo A/B covers the full record path).
- ``SWARMDB_LOCKCHECK=1``: returns the instrumented wrappers from
  :mod:`swarmdb_tpu.obs.lockcheck` — per-thread held sets, the runtime
  acquisition-order graph with inversion-cycle detection, per-site
  hold/contention stats. The chaos/HA/partition CI suites run under
  this flag so the hostile interleavings they generate assert lock
  ordering, not just liveness.

The flag is read per *allocation* (not per acquire): flipping the env
var mid-process affects locks created afterwards, which is what the
sanitizer tests rely on. The lockcheck import stays lazy so the off
path never pays it and the obs package can itself allocate through
this module during its own import.

Site label convention: ``<module>.<Class>.<attr>`` for instance locks,
``<module>.<function>.<name>`` for closure-shared locals — matching
the static checker's lock identities (analysis/lockorder.py), so a
runtime cycle report and an SWL302 finding name the same sites.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

__all__ = ["make_lock", "make_rlock", "make_condition",
           "lockcheck_enabled"]


def lockcheck_enabled() -> bool:
    return os.environ.get("SWARMDB_LOCKCHECK", "0") not in ("", "0")


def _checked(kind: str, site: str) -> Any:
    from swarmdb_tpu.obs import lockcheck

    return lockcheck.checked(kind, site)


def make_lock(site: str) -> Any:
    """A mutex for ``site`` (plain ``threading.Lock`` unless the
    sanitizer is on)."""
    if lockcheck_enabled():
        return _checked("lock", site)
    return threading.Lock()


def make_rlock(site: str) -> Any:
    if lockcheck_enabled():
        return _checked("rlock", site)
    return threading.RLock()


def make_condition(site: str, lock: Optional[Any] = None) -> Any:
    if lockcheck_enabled():
        from swarmdb_tpu.obs import lockcheck

        return lockcheck.CheckedCondition(site, lock=lock)
    return threading.Condition(lock)
