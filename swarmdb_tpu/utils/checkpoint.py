"""Weight checkpointing and HF-checkpoint import.

Two planes (SURVEY §5.4: the reference checkpoints broker state only —
model weights are the TPU build's addition, loaded as read-only serving
state):

- ``save_params`` / ``restore_params``: orbax-backed pytree checkpointing.
  Restore accepts a pytree of ``jax.sharding.NamedSharding`` so a 70B tree
  restores directly onto a mesh without any host materializing the full
  model (the same path ``parallel.build_sharded_model`` uses for random
  init).
- ``import_hf_llama`` / ``import_hf_mixtral``: map a locally available
  HuggingFace ``transformers`` checkpoint (torch CPU) into this
  framework's stacked-layer pytree layout (models/llama.py: weights are
  stacked [L, ...] and scanned).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.configs import ModelConfig


# ------------------------------------------------------------------- orbax


def save_params(params: Any, path: str) -> str:
    """Write a pytree checkpoint (orbax StandardCheckpointer)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params)
    ckptr.wait_until_finished()
    return path


def restore_params(path: str, target: Optional[Any] = None,
                   shardings: Optional[Any] = None) -> Any:
    """Restore a pytree checkpoint.

    ``target`` is a pytree of arrays or ShapeDtypeStructs giving the
    expected structure; with ``shardings`` (same structure, NamedShardings)
    each leaf is restored directly onto its mesh placement.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if target is None:
        return ckptr.restore(path)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), target
    )
    if shardings is not None:
        abstract = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, shardings,
        )
    return ckptr.restore(path, abstract)


# ---------------------------------------------------------------- HF import


def _t(w, dtype) -> jnp.ndarray:
    """torch tensor -> transposed jnp array (HF Linear stores [out, in])."""
    return jnp.asarray(np.asarray(w.detach().to("cpu").float()).T, dtype=dtype)


def _n(w, dtype) -> jnp.ndarray:
    """torch tensor -> jnp array, layout preserved."""
    return jnp.asarray(np.asarray(w.detach().to("cpu").float()), dtype=dtype)


def import_hf_llama(model, cfg: ModelConfig,
                    dtype: jnp.dtype = jnp.bfloat16) -> Dict[str, Any]:
    """Convert a transformers LlamaForCausalLM to the stacked pytree of
    ``models/llama.py`` (RoPE split-half convention matches HF rotate_half).
    """
    hf = model.model
    L = cfg.n_layers
    assert len(hf.layers) == L, (len(hf.layers), L)

    def stack(getter):
        return jnp.stack([getter(hf.layers[i]) for i in range(L)])

    params: Dict[str, Any] = {
        "embed": _n(hf.embed_tokens.weight, dtype),
        "layers": {
            "attn_norm": stack(lambda l: _n(l.input_layernorm.weight, dtype)),
            "wq": stack(lambda l: _t(l.self_attn.q_proj.weight, dtype)),
            "wk": stack(lambda l: _t(l.self_attn.k_proj.weight, dtype)),
            "wv": stack(lambda l: _t(l.self_attn.v_proj.weight, dtype)),
            "wo": stack(lambda l: _t(l.self_attn.o_proj.weight, dtype)),
            "mlp_norm": stack(
                lambda l: _n(l.post_attention_layernorm.weight, dtype)),
            "w_gate": stack(lambda l: _t(l.mlp.gate_proj.weight, dtype)),
            "w_up": stack(lambda l: _t(l.mlp.up_proj.weight, dtype)),
            "w_down": stack(lambda l: _t(l.mlp.down_proj.weight, dtype)),
        },
        "final_norm": _n(hf.norm.weight, dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _t(model.lm_head.weight, dtype)
    return params


def import_hf_mixtral(model, cfg: ModelConfig,
                      dtype: jnp.dtype = jnp.bfloat16) -> Dict[str, Any]:
    """Convert a transformers MixtralForCausalLM to the stacked pytree of
    ``models/mixtral.py`` (w1=gate, w3=up, w2=down per HF naming)."""
    hf = model.model
    L, E = cfg.n_layers, cfg.n_experts
    assert len(hf.layers) == L

    def stack(getter):
        return jnp.stack([getter(hf.layers[i]) for i in range(L)])

    def stack_experts(getter):
        return jnp.stack([
            jnp.stack([getter(hf.layers[i].block_sparse_moe.experts[e])
                       for e in range(E)])
            for i in range(L)
        ])

    return {
        "embed": _n(hf.embed_tokens.weight, dtype),
        "layers": {
            "attn_norm": stack(lambda l: _n(l.input_layernorm.weight, dtype)),
            "wq": stack(lambda l: _t(l.self_attn.q_proj.weight, dtype)),
            "wk": stack(lambda l: _t(l.self_attn.k_proj.weight, dtype)),
            "wv": stack(lambda l: _t(l.self_attn.v_proj.weight, dtype)),
            "wo": stack(lambda l: _t(l.self_attn.o_proj.weight, dtype)),
            "mlp_norm": stack(
                lambda l: _n(l.post_attention_layernorm.weight, dtype)),
            "router": stack(lambda l: _t(l.block_sparse_moe.gate.weight, dtype)),
            "w_gate": stack_experts(lambda e: _t(e.w1.weight, dtype)),
            "w_up": stack_experts(lambda e: _t(e.w3.weight, dtype)),
            "w_down": stack_experts(lambda e: _t(e.w2.weight, dtype)),
        },
        "final_norm": _n(hf.norm.weight, dtype),
        "lm_head": _t(model.lm_head.weight, dtype),
    }


def load_hf_checkpoint(path: str, cfg: ModelConfig,
                       dtype: jnp.dtype = jnp.bfloat16) -> Dict[str, Any]:
    """Load a local HF checkpoint directory and convert (zero-egress image:
    `path` must already be on disk)."""
    import transformers

    model = transformers.AutoModelForCausalLM.from_pretrained(path)
    if cfg.is_moe:
        return import_hf_mixtral(model, cfg, dtype)
    return import_hf_llama(model, cfg, dtype)
