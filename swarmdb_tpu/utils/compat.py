"""Version-compat shims for the moving parts of the JAX API.

The jax floor in pyproject.toml is deliberately permissive; the two
surfaces that changed across the supported range are wrapped here:

- ``shard_map``: top-level ``jax.shard_map`` with ``check_vma=`` (new) vs
  ``jax.experimental.shard_map.shard_map`` with ``check_rep=`` (0.4.x).
"""

from __future__ import annotations

from typing import Any


def shard_map(f: Any, *, mesh: Any, in_specs: Any, out_specs: Any) -> Any:
    """``shard_map`` with replication/VMA checking disabled, on any
    supported jax version."""
    try:
        from jax import shard_map as _sm  # jax >= 0.6-ish
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm  # 0.4.x
    for kw in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       **kw)
        except TypeError as exc:
            # only swallow the unknown-kwarg probe failure; a TypeError from
            # a correct-signature call (bad mesh/specs) is the real error
            if kw and next(iter(kw)) in str(exc):
                continue
            raise
    raise TypeError("no usable shard_map signature found")
