"""Production log sink: size-rotated, compressed, retention-bounded file
logging.

Counterpart of the reference's loguru file sink (`/root/reference/swarmdb/
 main.py:171-189`: 10 MB rotation, 7-day retention, zip compression) built
on stdlib logging so it composes with the rest of the process:

- ``LOG_FILE`` enables the sink (absent = console-only, unchanged).
- ``LOG_ROTATE_BYTES`` (default 10 MB) size-based rotation.
- ``LOG_BACKUP_COUNT`` (default 7) bounded retention — the oldest archive
  is deleted when the count is exceeded (the stdlib handler's own
  mechanism, equivalent to the reference's retention window).
- ``LOG_COMPRESS`` (default 1) gzips each rotated file.
"""

from __future__ import annotations

import gzip
import logging
import logging.handlers
import os
import shutil
from typing import Optional

DEFAULT_FORMAT = (
    "%(asctime)s | %(levelname)-8s | %(name)s:%(funcName)s:%(lineno)d "
    "- %(message)s"
)


class CompressedRotatingFileHandler(logging.handlers.RotatingFileHandler):
    """RotatingFileHandler whose archives are gzipped.

    Uses the documented namer/rotator hooks: archives are ``<file>.N.gz``
    and backupCount still bounds retention (rollover shifts .1.gz -> .2.gz
    etc. via the namer, so the stdlib deletion logic keeps working).
    """

    def __init__(self, *args, compress: bool = True, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        if compress:
            self.namer = lambda name: name + ".gz"
            self.rotator = self._gzip_rotator

    @staticmethod
    def _gzip_rotator(source: str, dest: str) -> None:
        with open(source, "rb") as fin, gzip.open(dest, "wb") as fout:
            shutil.copyfileobj(fin, fout)
        os.remove(source)


def configure_logging(
    log_file: Optional[str] = None,
    *,
    level: Optional[str] = None,
    rotate_bytes: Optional[int] = None,
    backup_count: Optional[int] = None,
    compress: Optional[bool] = None,
    fmt: str = DEFAULT_FORMAT,
) -> Optional[logging.Handler]:
    """Configure root logging; returns the file handler if one was added.

    Explicit arguments win over the LOG_* env vars; everything defaults to
    the reference deployment's values (10 MB / 7 archives / compressed).
    """
    level = level or os.environ.get("LOG_LEVEL", "INFO")
    logging.basicConfig(level=level)
    # basicConfig is a no-op when handlers already exist (embedding apps,
    # pytest): still honor the requested level
    logging.getLogger().setLevel(level)
    log_file = log_file or os.environ.get("LOG_FILE")
    if not log_file:
        return None
    if rotate_bytes is None:
        rotate_bytes = int(os.environ.get("LOG_ROTATE_BYTES",
                                          str(10 * 1024 * 1024)))
    if backup_count is None:
        backup_count = int(os.environ.get("LOG_BACKUP_COUNT", "7"))
    if compress is None:
        compress = os.environ.get("LOG_COMPRESS", "1") == "1"
    os.makedirs(os.path.dirname(os.path.abspath(log_file)), exist_ok=True)
    handler = CompressedRotatingFileHandler(
        log_file, maxBytes=rotate_bytes, backupCount=backup_count,
        compress=compress,
    )
    handler.setFormatter(logging.Formatter(fmt))
    handler.setLevel(level)
    logging.getLogger().addHandler(handler)
    return handler
