"""Stable hashing for partition routing.

The reference partitions with Python's builtin ``hash(agent_id)``
(` main.py:309-312`), which is salted per process (defect D6) — the same
agent lands on different partitions in different workers. We use FNV-1a
64-bit, which is deterministic across processes, hosts, and Python versions,
and matches the partitioner implemented in the C++ broker
(``broker/cpp/broker.cc``) so Python and native paths agree.
"""

from __future__ import annotations

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK64
    return h


def stable_partition(key: str, num_partitions: int) -> int:
    """Deterministic key → partition mapping (replaces ` main.py:309-312`)."""
    if num_partitions <= 0:
        raise ValueError(f"num_partitions must be positive, got {num_partitions}")
    return fnv1a64(key.encode("utf-8")) % num_partitions
