"""Incremental metrics: counters, gauges, and latency histograms.

The reference computes every statistic with O(total messages) full scans
(`get_stats` ` main.py:973-1024`, `get_agent_load` `:1049-1094`). Here the
hot-path counters are maintained incrementally so `/stats` is O(1), and the
north-star gauges (completed msgs/sec, p50 send→first-token) are first-class
(SURVEY §5.5).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Deque, Dict, Optional
from .sync import make_lock


class Counter:
    """A monotonically increasing counter, thread-safe."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0
        self._lock = make_lock("utils.metrics.Counter._lock")

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class RateGauge:
    """Events/sec over a trailing window (default 60 s, like the reference's
    per-agent msgs/sec at ` main.py:1075-1090`, but O(window) not O(history))."""

    def __init__(self, window_s: float = 60.0) -> None:
        self.window_s = window_s
        self._events: Deque[float] = deque()
        self._lock = make_lock("utils.metrics.RateGauge._lock")

    def mark(self, ts: Optional[float] = None) -> None:
        now = ts if ts is not None else time.time()
        with self._lock:
            self._events.append(now)
            self._evict(now)

    def rate(self) -> float:
        now = time.time()
        with self._lock:
            self._evict(now)
            return len(self._events) / self.window_s

    def _evict(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0] < cutoff:
            self._events.popleft()


class LatencyHistogram:
    """Sorted reservoir of recent latencies with percentile queries.

    Keeps the most recent ``capacity`` samples; p50/p95/p99 are exact over
    that window. Used for the north-star p50 send→first-token gauge.
    """

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = capacity
        self._ring: Deque[float] = deque(maxlen=capacity)
        self._lock = make_lock("utils.metrics.LatencyHistogram._lock")

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._ring.append(seconds)

    def values(self) -> list:
        """Sorted copy of the current sample window (bench reporting)."""
        with self._lock:
            return sorted(self._ring)

    def count(self) -> int:
        """O(1) sample count (len() of a deque is constant-time)."""
        with self._lock:
            return len(self._ring)

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._ring:
                return None
            data = sorted(self._ring)
        idx = min(len(data) - 1, max(0, int(round(q / 100.0 * (len(data) - 1)))))
        return data[idx]

    def summary(self) -> Dict[str, Optional[float]]:
        return {
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
            "count": float(self.count()),
        }


class MetricsRegistry:
    """Named counters/gauges/histograms, one per process."""

    def __init__(self) -> None:
        self.counters: Dict[str, Counter] = defaultdict(Counter)
        self.rates: Dict[str, RateGauge] = defaultdict(RateGauge)
        self.latencies: Dict[str, LatencyHistogram] = defaultdict(LatencyHistogram)

    def snapshot(self) -> Dict[str, object]:
        return {
            "counters": {k: c.value for k, c in self.counters.items()},
            "rates": {k: g.rate() for k, g in self.rates.items()},
            "latencies": {k: h.summary() for k, h in self.latencies.items()},
        }


GLOBAL_METRICS = MetricsRegistry()
