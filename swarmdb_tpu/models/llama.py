"""Llama-3 model family — functional JAX implementation.

Design (idiomatic TPU, not a torch port):
- Parameters are a plain pytree dict; per-layer weights are STACKED along a
  leading [L, ...] axis and the forward pass is one `lax.scan` over layers —
  one compiled layer body regardless of depth (fast compiles, natural hook
  for pipeline parallelism later).
- Same forward for prefill ([B, T] tokens) and decode ([B, 1]): each batch
  row carries its own absolute positions, and K/V are scattered into a
  fixed-shape slot cache — the continuous-batching engine admits/retires
  sequences by rewriting slot state, never by changing shapes.
- Tensor parallelism is expressed as PartitionSpecs over a 'model' mesh axis
  (`param_specs`): attention/MLP column-sharded in, row-sharded out, GSPMD
  inserts the all-reduces (SURVEY §2.4 TP row).

The reference has no model layer (SURVEY §2.4); this is the north-star
serving backend for Llama-3-8B/70B (BASELINE.json configs 2, 3, 5).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.layers import (
    gqa_attention,
    gqa_attention_chunked,
    gqa_attention_prefix,
    merge_chunk_kv,
    qkv_proj,
    rms_norm,
    rope_cos_sin,
    swiglu,
    write_kv_cache,
)
from .configs import ModelConfig

Params = Dict[str, Any]
KVCache = Tuple[jnp.ndarray, jnp.ndarray]  # each [L, B, S, Hkv, D]


# ---------------------------------------------------------------------- init


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random init (serving weights normally come from a checkpoint; random
    params exercise identical shapes/compute for tests and benches)."""
    if cfg.is_moe:
        raise ValueError(
            f"{cfg.name!r} is a MoE config (n_experts={cfg.n_experts}); "
            "use swarmdb_tpu.models.mixtral, not the dense Llama stack"
        )
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    Hq, Hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(dtype)

    ks = jax.random.split(k_layers, 7)
    params: Params = {
        "embed": dense(k_embed, (cfg.vocab_size, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), dtype),
            "wq": dense(ks[0], (L, D, Hq * hd), D),
            "wk": dense(ks[1], (L, D, Hkv * hd), D),
            "wv": dense(ks[2], (L, D, Hkv * hd), D),
            "wo": dense(ks[3], (L, Hq * hd, D), Hq * hd),
            "mlp_norm": jnp.ones((L, D), dtype),
            "w_gate": dense(ks[4], (L, D, F), D),
            "w_up": dense(ks[5], (L, D, F), D),
            "w_down": dense(ks[6], (L, F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(k_head, (D, cfg.vocab_size), D)
    return params


def param_specs(cfg: ModelConfig, model_axis: str = "model") -> Params:
    """PartitionSpecs for tensor parallelism over ``model_axis``.

    Megatron-style: QKV/gate/up column-parallel (shard output features),
    O/down row-parallel (shard input features) — one all-reduce per block,
    emitted by GSPMD. Embedding/head shard the vocab dimension.
    """
    m = model_axis
    specs: Params = {
        "embed": P(m, None),        # vocab-sharded
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, m),
            "wk": P(None, None, m),
            "wv": P(None, None, m),
            "wo": P(None, m, None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, m),
            "w_up": P(None, None, m),
            "w_down": P(None, m, None),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, m)
    return specs


def init_kv_cache(
    cfg: ModelConfig, batch: int, max_seq: int, dtype: jnp.dtype = jnp.bfloat16
) -> KVCache:
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def cache_specs(model_axis: str = "model") -> Tuple[P, P]:
    """KV cache shards its head dim over the model axis, batch over data."""
    spec = P(None, "data", None, model_axis, None)
    return spec, spec


def init_paged_cache(
    cfg: ModelConfig,
    batch: int,
    max_seq: int,
    num_pages: int,
    page_size: int,
    dtype: Optional[jnp.dtype] = None,
):
    """Block-paged KV pool (ops/paged_kv.py): HBM ∝ num_pages*page_size,
    not batch*max_seq. Returns {"k", "v", "page_table"}. ``dtype=None``
    resolves SWARMDB_KV_DTYPE (bf16 default; int8 yields QuantPool
    entries — see ops/paged_kv.py)."""
    from ..ops.paged_kv import init_paged_kv_cache

    return init_paged_kv_cache(
        cfg.n_layers, num_pages, page_size, cfg.n_kv_heads, cfg.head_dim,
        batch, max_seq, dtype,
    )


# ------------------------------------------------------------------- forward


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,       # [B, T] int32
    positions: jnp.ndarray,    # [B, T] int32 absolute positions per row
    cache: KVCache,            # ([L, B, S, Hkv, hd], ...)
    logits_at: Optional[jnp.ndarray] = None,  # [B] int32 row indices into T
) -> Tuple[jnp.ndarray, KVCache]:
    """One forward pass; returns fp32 logits and updated cache.

    Works for mixed prefill/decode batches: each row's ``positions`` are its
    own absolute offsets, and attention masks by position (ops/layers.py).

    ``logits_at`` computes the LM head ONLY at each row's named position,
    returning [B, V] instead of [B, T, V] — same math (head columns are
    per-position independent; only reduction tiling can differ) while
    skipping the full-bucket fp32 logits the prefill path would otherwise
    materialize (0.5 GB per admission wave at Bp=16, T=255, V=32k, and
    ~7% of prefill FLOPs).
    """
    if cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is MoE; use models.mixtral.forward")
    x = params["embed"][tokens]  # [B, T, D]; compute dtype = param dtype
    cache_k, cache_v = cache
    # RoPE terms depend only on positions: compute once, reuse in every
    # scanned layer (XLA can't hoist transcendentals out of the loop body)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    layer_params = params["layers"]

    def layer_step(x, scanned):
        lp, ck, cv = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        B, T = h.shape[0], h.shape[1]
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        ck, cv = write_kv_cache(ck, cv, k, v, positions)
        attn = gqa_attention(q, ck, cv, positions, window=cfg.sliding_window)
        attn_out = jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])
        x = x + attn_out
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(layer_step, x, (layer_params, cache_k, cache_v))

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params.get("lm_head")
    if head is None:  # tied embeddings
        head = params["embed"].T
    if logits_at is not None:
        x = x[jnp.arange(x.shape[0]), logits_at]         # [B, D]
        logits = jnp.einsum("bd,dv->bv", x, head,
                            preferred_element_type=jnp.float32)
        return logits, (new_k, new_v)
    logits = jnp.einsum("btd,dv->btv", x, head,
                        preferred_element_type=jnp.float32)
    return logits, (new_k, new_v)


def forward_prefix_pages(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,        # [Bp, T] SUFFIX tokens (padded)
    prefix_table: jnp.ndarray,  # [Bp, PP] int32 prefix-pool page ids
    prefix_lens: jnp.ndarray,   # [Bp] int32 reused prefix length (tokens)
    pool_k: jnp.ndarray,        # [L, P, ps, Hkv, D] prefix page pool
    pool_v: jnp.ndarray,
    logits_at: Optional[jnp.ndarray] = None,  # [B] int32 row indices into T
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Prefix-cache suffix prefill CORE: compute ONLY the suffix tokens,
    attending each row's reused prefix pages + the suffix itself
    (ops/layers.gqa_attention_prefix). Shared by the dense path (which
    composes lane images via ops/layers.compose_prefix_lane) and the
    paged path (which scatters the suffix straight into fresh pages).

    Returns (fp32 logits [Bp, T, V] — or [Bp, V] with ``logits_at``, see
    ``forward`` — plus sfx_k, sfx_v [L, Bp, T, Hkv, D]).
    """
    if cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is MoE; use models.mixtral")
    from ..ops.paged_kv import _dequantize_pages, is_quantized, pool_data

    Bp, T = tokens.shape
    quant = is_quantized(pool_k)
    L, P = pool_data(pool_k).shape[0], pool_data(pool_k).shape[1]
    ps = pool_data(pool_k).shape[2]
    PP = prefix_table.shape[1]
    Pt = PP * ps
    x = params["embed"][tokens]
    positions = prefix_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None]
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    # one fused gather per layer: flatten (L, P) so layer index l and the
    # page table combine into a single index array (a dynamic_slice of the
    # pool followed by a page gather may or may not fuse; this form always
    # reads only the needed pages). Quantized pools gather payload AND
    # scale rows, dequantizing to f32 right after the gather.
    from ..ops.paged_kv import pool_flat

    pool_k_flat = pool_flat(pool_k)
    pool_v_flat = pool_flat(pool_v)

    def _gather_pages(flat, idx):
        if quant:
            return _dequantize_pages(flat.data[idx], flat.scale[idx]
                                     ).reshape(Bp, Pt, cfg.n_kv_heads,
                                               cfg.head_dim)
        return flat[idx].reshape(Bp, Pt, cfg.n_kv_heads, cfg.head_dim)

    def layer_step(x, scanned):
        lp, l = scanned
        kp = _gather_pages(pool_k_flat, l * P + prefix_table)
        vp = _gather_pages(pool_v_flat, l * P + prefix_table)
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        attn = gqa_attention_prefix(q, kp, vp, k.astype(kp.dtype),
                                    v.astype(vp.dtype), prefix_lens,
                                    window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(Bp, T, -1), lp["wo"])
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, (k.astype(kp.dtype), v.astype(vp.dtype))

    x, (sfx_k, sfx_v) = jax.lax.scan(
        layer_step, x,
        (params["layers"], jnp.arange(L, dtype=jnp.int32)),
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    if logits_at is not None:
        x = x[jnp.arange(x.shape[0]), logits_at]
        logits = jnp.einsum("bd,dv->bv", x, head,
                            preferred_element_type=jnp.float32)
        return logits, sfx_k, sfx_v
    logits = jnp.einsum("btd,dv->btv", x, head,
                        preferred_element_type=jnp.float32)
    return logits, sfx_k, sfx_v


def forward_ragged_prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,      # [W] int32 packed token stream (rows concat)
    tok_row: jnp.ndarray,     # [W] int32 owning wave row (>= R = padding)
    tok_pos: jnp.ndarray,     # [W] int32 absolute position within the row
    row_tables: jnp.ndarray,  # [R, maxp] int32 page-pool ids per row
    starts: jnp.ndarray,      # [R] int32 row offset in the stream
    lens: jnp.ndarray,        # [R] int32 row token count (0 = dead row)
    prefix_lens: jnp.ndarray,  # [R] int32 tokens already in the row's pages
    pool_k: jnp.ndarray,      # [L, P, ps, Hkv, D] MAIN paged pool
    pool_v: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Packed ragged PREFILL forward (ISSUE 11): ONE no-padding token
    stream per admission wave — the wave's rows concatenated back to back,
    described by per-row ``(start, len, prefix_len)`` descriptors. Each
    token attends its own row's prefix KV straight out of the page pool
    (prefix-cache hits AND earlier chunks of a split prompt — the
    ``ops.layers.ragged_prefill_dispatch`` kernel reads pages in place,
    no ``paged_gather_kv`` densification) plus the row's suffix causally.

    The layer scan addresses the pool through its flattened [L*P] view
    with a per-layer table offset, so the kernel sees a single page axis
    (a reshape, not a copy). Returns (fp32 logits [R, V] at each row's
    LAST live token, sfx_k, sfx_v [L, W, Hkv, D] — packed, stream order,
    for ``ops.paged_kv.paged_write_ragged``).
    """
    if cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is MoE; ragged prefill is "
                         "dense-Llama-only for now")
    from ..ops.layers import ragged_prefill_dispatch
    from ..ops.paged_kv import pool_data, pool_dtype, pool_flat

    W = tokens.shape[0]
    L, P = pool_data(pool_k).shape[0], pool_data(pool_k).shape[1]
    x = params["embed"][tokens][None]                    # [1, W, D]
    cos, sin = rope_cos_sin(tok_pos[None], cfg.head_dim, cfg.rope_theta)
    pool_k_flat = pool_flat(pool_k)
    pool_v_flat = pool_flat(pool_v)
    kdt, vdt = pool_dtype(pool_k), pool_dtype(pool_v)
    tables = row_tables.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    lens = lens.astype(jnp.int32)
    plens = prefix_lens.astype(jnp.int32)

    def layer_step(x, scanned):
        lp, l = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        # suffix K/V cast to the pool's LOGICAL dtype BEFORE attention
        # (matching forward_prefix_pages): what this wave attends is
        # bit-identical to what later waves/decodes read back from the
        # pages — under int8 pools the cast targets the dequant dtype
        # and the residual quantization error is bounded by the parity
        # suite instead (tests/test_kv_quant.py)
        ks = k[0].astype(kdt)
        vs = v[0].astype(vdt)
        attn = ragged_prefill_dispatch(
            q[0], ks, vs, pool_k_flat, pool_v_flat, tables + l * P,
            starts, lens, plens, tok_row, window=cfg.sliding_window)
        x = x + jnp.einsum("wh,hd->wd", attn.reshape(W, -1), lp["wo"])[None]
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, (ks, vs)

    x, (sfx_k, sfx_v) = jax.lax.scan(
        layer_step, x,
        (params["layers"], jnp.arange(L, dtype=jnp.int32)),
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    last_w = starts + jnp.maximum(lens - 1, 0)           # dead rows -> 0
    logits = jnp.einsum("rd,dv->rv", x[0, last_w], head,
                        preferred_element_type=jnp.float32)
    return logits, sfx_k, sfx_v


def forward_prefix_lane(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,        # [Bp, T] SUFFIX tokens (padded)
    prefix_table: jnp.ndarray,  # [Bp, PP] int32 prefix-pool page ids
    prefix_lens: jnp.ndarray,   # [Bp] int32 reused prefix length (tokens)
    pool_k: jnp.ndarray,        # [L, P, ps, Hkv, D] prefix page pool
    pool_v: jnp.ndarray,
    lane_pages: int,            # static: output lane length in pages
    logits_at: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dense-cache prefix prefill: ``forward_prefix_pages`` + per-row lane
    composition (ops/layers.compose_prefix_lane) ready for one uniform
    slot-cache insert. Returns (fp32 logits, lane_k, lane_v).
    """
    from ..ops.layers import compose_prefix_lane

    logits, sfx_k, sfx_v = forward_prefix_pages(
        params, cfg, tokens, prefix_table, prefix_lens, pool_k, pool_v,
        logits_at=logits_at)
    lane_k, lane_v = compose_prefix_lane(
        pool_k, pool_v, prefix_table, prefix_lens, sfx_k, sfx_v, lane_pages)
    return logits, lane_k, lane_v


def init_prefix_pool(
    cfg: ModelConfig, num_pages: int, page_size: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Zeroed prefix-cache page pool (page 0 = trash)."""
    shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def init_chunk_kv(
    cfg: ModelConfig, batch: int, chunk: int, dtype: jnp.dtype = jnp.bfloat16
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-chunk K/V accumulator for the two-segment decode (zeros; shape
    [L, B, Kc, Hkv, D])."""
    shape = (cfg.n_layers, batch, chunk, cfg.n_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def forward_chunked(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,       # [B, 1] int32 — one decode step
    positions: jnp.ndarray,    # [B, 1] int32 absolute positions
    cache: KVCache,            # FROZEN during the chunk
    chunk_kv: Tuple[jnp.ndarray, jnp.ndarray],  # [L, B, Kc, Hkv, D] each
    step: jnp.ndarray,         # scalar int32 — index within the chunk
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Decode step against a frozen cache + in-chunk K/V buffer.

    The engine's chunked decode loop (Engine._decode) calls this K times
    per chunk, then folds chunk_kv into the big cache with
    ``merge_chunk_kv`` — one full-cache write per CHUNK, not per step
    (ops/layers.gqa_attention_chunked has the profile numbers). This
    step's K/V is written at chunk index ``step`` via dynamic_update_slice
    (uniform index across rows, no scatter).
    """
    if cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is MoE; use models.mixtral")
    x = params["embed"][tokens]  # [B, 1, D]
    cache_k, cache_v = cache
    chunk_k, chunk_v = chunk_kv
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    def layer_step(x, scanned):
        lp, ck, cv, hk, hv = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        B, T = h.shape[0], h.shape[1]
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        hk = jax.lax.dynamic_update_slice(hk, k.astype(hk.dtype),
                                          (0, step, 0, 0))
        hv = jax.lax.dynamic_update_slice(hv, v.astype(hv.dtype),
                                          (0, step, 0, 0))
        attn = gqa_attention_chunked(q, ck, cv, hk, hv, positions, step,
                                     window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, (hk, hv)

    x, (new_hk, new_hv) = jax.lax.scan(
        layer_step, x, (params["layers"], cache_k, cache_v, chunk_k, chunk_v)
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params.get("lm_head")
    if head is None:  # tied embeddings
        head = params["embed"].T
    logits = jnp.einsum("btd,dv->btv", x, head,
                        preferred_element_type=jnp.float32)
    return logits, (new_hk, new_hv)


def merge_chunk(
    cache: KVCache,
    chunk_kv: Tuple[jnp.ndarray, jnp.ndarray],
    start_positions: jnp.ndarray,  # [B]
) -> KVCache:
    """Fold a finished chunk's K/V into the slot cache (ops/layers)."""
    ck, cv = cache
    hk, hv = chunk_kv
    return merge_chunk_kv(ck, cv, hk, hv, start_positions)


def merge_chunk_scatter(
    cache: KVCache,
    chunk_kv: Tuple[jnp.ndarray, jnp.ndarray],
    start_positions: jnp.ndarray,  # [B]
) -> KVCache:
    """Scatter-form merge (ops/layers.merge_chunk_kv_scatter); selected
    by SWARMDB_MERGE=scatter — see that function for the trade."""
    from ..ops.layers import merge_chunk_kv_scatter

    ck, cv = cache
    hk, hv = chunk_kv
    return merge_chunk_kv_scatter(ck, cv, hk, hv, start_positions)


def forward_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,       # [B, 1] int32 — DECODE steps only
    positions: jnp.ndarray,    # [B, 1] int32 absolute positions per row
    cache,                     # {"k": [L,P,ps,Hkv,D], "v": ..., "page_table"}
):
    """Decode forward over the block-paged KV pool (ops/paged_kv.py).

    Prefill stays on the dense bucket path (`forward` with a temp cache);
    the engine scatters the prefix into pages at admission
    (ops.paged_kv.paged_insert_prefill). Attention uses the ragged Pallas
    kernel on TPU (reads only live pages) with an XLA gather fallback.
    Returns fp32 logits [B, 1, V] and the updated cache dict.
    """
    if cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is MoE; use models.mixtral.forward_paged")
    from ..ops.layers import paged_attention_dispatch
    from ..ops.paged_kv import paged_write_decode

    x = params["embed"][tokens]  # [B, 1, D]
    table = cache["page_table"]
    # rolling-KV conversations carry a per-row RoPE offset: kept pages'
    # K were rope'd at their original absolute positions, so queries
    # must be too (RoPE scores depend only on position differences).
    # ``positions`` stays LOGICAL (page writes + masks)
    pos0 = cache.get("pos0")
    rope_pos = positions if pos0 is None else positions + pos0[:, None]
    cos, sin = rope_cos_sin(rope_pos, cfg.head_dim, cfg.rope_theta)

    def layer_step(x, scanned):
        lp, kp, vp = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        B, T = h.shape[0], h.shape[1]
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        kp, vp = paged_write_decode(kp, vp, k, v, positions, table)
        attn = paged_attention_dispatch(
            q, kp, vp, table, positions, window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, (kp, vp)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = jnp.einsum("btd,dv->btv", x, head,
                        preferred_element_type=jnp.float32)
    out = {"k": new_k, "v": new_v, "page_table": table}
    if pos0 is not None:
        out["pos0"] = pos0
    return logits, out


def forward_paged_chunked(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,       # [B, 1]
    positions: jnp.ndarray,    # [B, 1]
    cache,                     # {"k","v","page_table"} — FROZEN this chunk
    chunk_kv: Tuple[jnp.ndarray, jnp.ndarray],  # [L, B, Kc, Hkv, D] each
    step: jnp.ndarray,         # scalar int32
):
    """Two-segment chunked decode over the PAGED pool: the pool stays
    frozen for the chunk's K steps (one bulk page write per chunk via
    ``merge_paged_chunk``), this step's K/V lands in the chunk buffer,
    and attention spans live pages + chunk buffer under one softmax
    (ops/layers.paged_attention_dispatch_chunked)."""
    if cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is MoE; use models.mixtral")
    from ..ops.layers import paged_attention_dispatch_chunked

    x = params["embed"][tokens]
    table = cache["page_table"]
    chunk_k, chunk_v = chunk_kv
    pos0 = cache.get("pos0")  # rolling-KV RoPE offset (see forward_paged)
    rope_pos = positions if pos0 is None else positions + pos0[:, None]
    cos, sin = rope_cos_sin(rope_pos, cfg.head_dim, cfg.rope_theta)

    def layer_step(x, scanned):
        lp, kp, vp, hk, hv = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        B, T = h.shape[0], h.shape[1]
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        hk = jax.lax.dynamic_update_slice(hk, k.astype(hk.dtype),
                                          (0, step, 0, 0))
        hv = jax.lax.dynamic_update_slice(hv, v.astype(hv.dtype),
                                          (0, step, 0, 0))
        attn = paged_attention_dispatch_chunked(
            q, kp, vp, table, hk, hv, positions, step,
            window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, (hk, hv)

    x, (new_hk, new_hv) = jax.lax.scan(
        layer_step, x,
        (params["layers"], cache["k"], cache["v"], chunk_k, chunk_v),
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = jnp.einsum("btd,dv->btv", x, head,
                        preferred_element_type=jnp.float32)
    return logits, (new_hk, new_hv)


def merge_paged_chunk(cache, chunk_kv, start_positions: jnp.ndarray):
    """Fold a finished chunk's K/V into the page pool — one bulk write
    (ops/paged_kv.paged_write_chunk)."""
    from ..ops.paged_kv import paged_write_chunk

    hk, hv = chunk_kv
    new_k, new_v = paged_write_chunk(
        cache["k"], cache["v"], hk, hv, start_positions,
        cache["page_table"],
    )
    out = {"k": new_k, "v": new_v, "page_table": cache["page_table"]}
    if "pos0" in cache:
        out["pos0"] = cache["pos0"]
    return out


# ----------------------------------------------------- pipeline parallelism


def param_specs_pp(cfg: ModelConfig, pipe_axis: str = "pipe") -> Params:
    """PartitionSpecs for pipeline parallelism: the stacked [L, ...] layer
    arrays shard their LAYER axis over ``pipe_axis`` (each stage holds
    L/pipe layers); embedding/norms/head are replicated. This is the
    storage layout ``forward_pipelined`` consumes — the stacked-layer
    design makes PP a leading-axis sharding, not a model rewrite."""
    p = pipe_axis
    specs: Params = {
        "embed": P(None, None),
        "layers": jax.tree.map(lambda _: P(p), {
            "attn_norm": 0, "wq": 0, "wk": 0, "wv": 0, "wo": 0,
            "mlp_norm": 0, "w_gate": 0, "w_up": 0, "w_down": 0,
        }),
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, None)
    return specs


def forward_pipelined(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,      # [B, T]
    positions: jnp.ndarray,   # [B, T]
    mesh,                     # jax.sharding.Mesh with a 'pipe' axis
    *,
    microbatches: Optional[int] = None,
    pipe_axis: str = "pipe",
) -> Tuple[jnp.ndarray, KVCache]:
    """Pipeline-parallel prefill: GPipe-style microbatch rotation.

    Layers shard over ``pipe_axis`` (SURVEY §2.4 PP row); the batch splits
    into M microbatches that flow through the stage ring via
    ``lax.ppermute`` — at steady state every stage computes a different
    microbatch, with the classic (P-1)/(M+P-1) bubble at the edges.
    Stage 0 embeds, the last stage applies the head; invalid edge steps
    compute masked garbage that is never stored. All collectives are the
    forward neighbor ppermute plus one psum to replicate the logits.

    Returns fp32 logits [B, T, V] and prompt K/V [L, B, T, Hkv, hd]
    (layer axis pipe-sharded on device). Requires n_layers % pipe == 0
    and B % microbatches == 0. This is the PREFILL path; decode keeps
    TP/DP (single-token PP would serialize on inter-stage latency).
    """
    from ..utils.compat import shard_map

    if cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is MoE; PP is dense-only for now")
    n_stages = mesh.shape[pipe_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by pipe={n_stages}")
    B, T = tokens.shape
    M = microbatches or min(B, max(2, n_stages))
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    Bm = B // M

    def stage_fwd(params, tokens, positions):
        stage = jax.lax.axis_index(pipe_axis)
        n_p = jax.lax.psum(1, pipe_axis)
        lp = params["layers"]  # local [L/P, ...] slices
        L_local = lp["attn_norm"].shape[0]
        mb_tok = tokens.reshape(M, Bm, T)
        mb_pos = positions.reshape(M, Bm, T)

        def run_layers(x, pos):
            cos, sin = rope_cos_sin(pos, cfg.head_dim, cfg.rope_theta)

            def layer_step(x, layer):
                h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
                b, t = h.shape[0], h.shape[1]
                q, k, v = qkv_proj(h, layer, cfg.n_heads, cfg.n_kv_heads,
                                   cfg.head_dim, cos, sin)
                attn = gqa_attention(q, k, v, pos, window=cfg.sliding_window)
                x = x + jnp.einsum("bth,hd->btd", attn.reshape(b, t, -1),
                                   layer["wo"])
                h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
                x = x + swiglu(h2, layer["w_gate"], layer["w_up"],
                               layer["w_down"])
                return x, (k, v)

            return jax.lax.scan(layer_step, x, lp)

        state = jnp.zeros((Bm, T, cfg.dim), params["embed"].dtype)
        ks_all = jnp.zeros((L_local, M, Bm, T, cfg.n_kv_heads, cfg.head_dim),
                           params["embed"].dtype)
        vs_all = jnp.zeros_like(ks_all)
        # accumulate the LAST stage's post-norm activations, not logits: a
        # [M, Bm, T, dim] carry + one dim-sized psum beats a fp32
        # [M, Bm, T, V] carry + V-sized psum by V/dim (16-64x), and the
        # head matmul then runs once after the scan instead of per step
        act_acc = jnp.zeros((M, Bm, T, cfg.dim), params["embed"].dtype)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step(carry, t_idx):
            state, ks_all, vs_all, act_acc = carry
            m_in = t_idx - stage                      # microbatch here now
            m_cl = jnp.clip(m_in, 0, M - 1)
            valid = (m_in >= 0) & (m_in < M)
            tok_m = jax.lax.dynamic_index_in_dim(mb_tok, m_cl, 0, False)
            pos_m = jax.lax.dynamic_index_in_dim(mb_pos, m_cl, 0, False)
            inject = params["embed"][tok_m]           # stage-0 entry point
            x = jnp.where(stage == 0, inject, state)
            x, (ks, vs) = run_layers(x, pos_m)

            sel = valid
            old_k = jax.lax.dynamic_index_in_dim(ks_all, m_cl, 1, False)
            old_v = jax.lax.dynamic_index_in_dim(vs_all, m_cl, 1, False)
            ks_all = jax.lax.dynamic_update_index_in_dim(
                ks_all, jnp.where(sel, ks, old_k), m_cl, 1)
            vs_all = jax.lax.dynamic_update_index_in_dim(
                vs_all, jnp.where(sel, vs, old_v), m_cl, 1)

            xn = rms_norm(x, params["final_norm"], cfg.norm_eps)
            old_a = jax.lax.dynamic_index_in_dim(act_acc, m_cl, 0, False)
            keep = sel & (stage == n_p - 1)
            act_acc = jax.lax.dynamic_update_index_in_dim(
                act_acc, jnp.where(keep, xn, old_a), m_cl, 0)

            state = jax.lax.ppermute(x, pipe_axis, perm)
            return (state, ks_all, vs_all, act_acc), None

        (state, ks_all, vs_all, act_acc), _ = jax.lax.scan(
            step, (state, ks_all, vs_all, act_acc),
            jnp.arange(M + n_stages - 1, dtype=jnp.int32),
        )
        # activations live only on the last stage (zeros elsewhere): one
        # psum replicates them, then every stage applies the (replicated)
        # head identically; K/V stay pipe-sharded on their layer axis
        act = jax.lax.psum(act_acc, pipe_axis)
        head = params.get("lm_head")
        if head is None:
            head = params["embed"].T
        logits = jnp.einsum("mbtd,dv->mbtv", act, head,
                            preferred_element_type=jnp.float32)
        ks_out = ks_all.reshape(L_local, B, T, cfg.n_kv_heads, cfg.head_dim)
        vs_out = vs_all.reshape(L_local, B, T, cfg.n_kv_heads, cfg.head_dim)
        return logits.reshape(B, T, cfg.vocab_size), ks_out, vs_out

    from jax.sharding import PartitionSpec as P_

    sharded = shard_map(
        stage_fwd,
        mesh=mesh,
        in_specs=(param_specs_pp(cfg, pipe_axis), P_(), P_()),
        out_specs=(P_(), P_(pipe_axis), P_(pipe_axis)),
    )
    logits, ks, vs = sharded(params, tokens, positions)
    return logits, (ks, vs)


# ------------------------------------------- sequence-parallel long prefill


def forward_seq_parallel(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,      # [B, T] with T = seq_axis_size * T_local
    positions: jnp.ndarray,   # [B, T] absolute positions
    mesh,                     # jax.sharding.Mesh
    seq_axis: str = "data",
) -> Tuple[jnp.ndarray, KVCache]:
    """Long-prompt prefill with the SEQUENCE sharded over a mesh axis.

    Context parallelism (SURVEY §5.7 design hook, made real): each device
    holds T/axis_size tokens; attention is `ops.ring_attention` — K/V
    chunks rotate over ICI with ppermute while softmax accumulates online,
    so peak memory per device is O(T/axis) and no [T, T] scores exist.
    During prefill of one long prompt the batch axis is idle, so the
    ``data`` axis doubles as the ring (no dedicated mesh axis needed).

    Returns fp32 logits [B, T, V] and the prompt KV [L, B, T, Hkv, D],
    both seq-sharded on device; callers either read the last-token logits
    or scatter the KV into a slot cache for decode.
    """
    from jax.sharding import PartitionSpec as P

    from ..ops.ring_attention import ring_attention
    from ..utils.compat import shard_map

    def local_fwd(params, tokens, positions):
        x = params["embed"][tokens]
        cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

        def layer_step(x, lp):
            h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
            B, T = h.shape[0], h.shape[1]
            q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                               cfg.head_dim, cos, sin)
            attn = ring_attention(q, k, v, positions, positions, seq_axis,
                                  window=cfg.sliding_window)
            x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])
            h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
            x = x + swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
            return x, (k, v)

        x, (ks, vs) = jax.lax.scan(layer_step, x, params["layers"])
        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        head = params.get("lm_head")
        if head is None:
            head = params["embed"].T
        logits = jnp.einsum("btd,dv->btv", x, head,
                            preferred_element_type=jnp.float32)
        return logits, ks, vs

    sharded = shard_map(
        local_fwd,
        mesh=mesh,
        in_specs=(P(), P(None, seq_axis), P(None, seq_axis)),
        out_specs=(
            P(None, seq_axis, None),
            P(None, None, seq_axis, None, None),
            P(None, None, seq_axis, None, None),
        ),
    )
    logits, ks, vs = sharded(params, tokens, positions)
    return logits, (ks, vs)

