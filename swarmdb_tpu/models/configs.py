"""Model family configurations.

The north star (BASELINE.json) names three serving backends: Llama-3-8B,
Llama-3-70B (TP on v5p-16), and Mixtral-8x7B (EP). The reference contains no
model code at all (SURVEY §2.4) — these are the TPU build's first-class
additions. Architecture constants follow the public model cards.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    ffn_dim: int
    norm_eps: float = 1e-5
    rope_theta: float = 500_000.0
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    # MoE (Mixtral-style); n_experts=0 => dense FFN
    n_experts: int = 0
    experts_per_token: int = 2
    # Mistral-style sliding-window attention; None = full causal.
    # (Mixtral-8x7B's official config disables it — null — so the
    # registry entry keeps None; the plumbing exists for windowed configs.)
    sliding_window: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


LLAMA3_8B = ModelConfig(
    name="llama3-8b",
    vocab_size=128_256,
    dim=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    ffn_dim=14_336,
    rope_theta=500_000.0,
)

LLAMA3_70B = ModelConfig(
    name="llama3-70b",
    vocab_size=128_256,
    dim=8192,
    n_layers=80,
    n_heads=64,
    n_kv_heads=8,
    ffn_dim=28_672,
    rope_theta=500_000.0,
)

MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    vocab_size=32_000,
    dim=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    ffn_dim=14_336,
    norm_eps=1e-5,
    rope_theta=1_000_000.0,
    max_seq_len=32_768,
    n_experts=8,
    experts_per_token=2,
)

# Small configs for tests / CPU drives / the single-chip bench.
TINY_DEBUG = ModelConfig(
    name="tiny-debug",
    vocab_size=512,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    ffn_dim=128,
    max_seq_len=256,
    rope_theta=10_000.0,
)

TINY_MOE = ModelConfig(
    name="tiny-moe",
    vocab_size=512,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    ffn_dim=128,
    max_seq_len=256,
    rope_theta=10_000.0,
    n_experts=4,
    experts_per_token=2,
)

# ~1B-class config for meaningful single-chip benchmarking without 8B HBM cost.
LLAMA_1B_BENCH = ModelConfig(
    name="llama-1b-bench",
    vocab_size=32_000,
    dim=2048,
    n_layers=16,
    n_heads=16,
    n_kv_heads=8,
    ffn_dim=5632,
    max_seq_len=4096,
    rope_theta=500_000.0,
)

REGISTRY = {
    c.name: c
    for c in (LLAMA3_8B, LLAMA3_70B, MIXTRAL_8X7B, TINY_DEBUG, TINY_MOE, LLAMA_1B_BENCH)
}


def get_config(name: str, **overrides) -> ModelConfig:
    if name not in REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(REGISTRY)}")
    cfg = REGISTRY[name]
    return replace(cfg, **overrides) if overrides else cfg
