"""Mixtral-style sparse Mixture-of-Experts family — functional JAX.

Same skeleton as ``models/llama.py`` (stacked layers + lax.scan, slot KV
cache, GQA attention with per-row positions) with the dense FFN replaced by
a top-k routed MoE block. Two numerically-equivalent dispatch forms:

- ``einsum``: the classic capacity-based one-hot dispatch (router -> top-k
  -> position-in-expert via cumsum -> [N, E, C] dispatch/combine tensors ->
  expert-major einsums). This is the GSPMD-native form: with tokens sharded
  over 'data' and expert weights over an 'expert' mesh axis, XLA lowers the
  dispatch/combine einsums to all-to-alls over ICI (SURVEY §2.4 EP row;
  BASELINE config 4 — Mixtral-8x7B tool-use backend). It is also ruinously
  expensive off the EP path: the [N, k, E, C] intermediates grow with
  N^2 (C ∝ N), and at a [16, 256] prefill the dispatch einsums cost ~10x
  the expert matmuls themselves (PROFILE r6: the 5.6x tooluse gap was
  almost entirely this term — 1766 ms vs 24 ms per block on the CPU A/B).
- ``scatter``: same routing decisions (same capacity, same overflow drops,
  same gates) realized as a token scatter into per-expert queues and a
  gather back — O(N·k·D) data movement, no one-hot tensors. Used on
  single-device / pure-DP engines; selected by default
  (SWARMDB_MOE_DISPATCH overrides; ``parallel/serving`` pins ``einsum``
  whenever the expert axis is actually sharded).

Tokens over capacity are dropped (contribute zero; the residual connection
carries them) in both forms.

No reference counterpart: the reference has no model code (SURVEY §2.4).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.layers import (
    gqa_attention,
    gqa_attention_chunked,
    qkv_proj,
    rms_norm,
    rope_cos_sin,
    write_kv_cache,
)
from .configs import ModelConfig

Params = Dict[str, Any]
KVCache = Tuple[jnp.ndarray, jnp.ndarray]

DEFAULT_CAPACITY_FACTOR = 2.0


# ---------------------------------------------------------------------- init


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    if not cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is dense; use models.llama")
    L, D, F, E = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.n_experts
    Hq, Hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(dtype)

    ks = jax.random.split(k_layers, 9)
    params: Params = {
        "embed": dense(k_embed, (cfg.vocab_size, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), dtype),
            "wq": dense(ks[0], (L, D, Hq * hd), D),
            "wk": dense(ks[1], (L, D, Hkv * hd), D),
            "wv": dense(ks[2], (L, D, Hkv * hd), D),
            "wo": dense(ks[3], (L, Hq * hd, D), Hq * hd),
            "mlp_norm": jnp.ones((L, D), dtype),
            "router": dense(ks[4], (L, D, E), D),
            "w_gate": dense(ks[5], (L, E, D, F), D),
            "w_up": dense(ks[6], (L, E, D, F), D),
            "w_down": dense(ks[7], (L, E, F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype),
        "lm_head": dense(k_head, (D, cfg.vocab_size), D),
    }
    return params


def param_specs(cfg: ModelConfig, model_axis: str = "model",
                expert_axis: str = "expert") -> Params:
    """TP over ``model_axis`` + EP over ``expert_axis``: attention is
    Megatron-sharded as in Llama; expert weights shard their leading expert
    dim so each device owns E/ep experts, and the dispatch/combine einsums
    become all-to-alls."""
    m, e = model_axis, expert_axis
    return {
        "embed": P(m, None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, m),
            "wk": P(None, None, m),
            "wv": P(None, None, m),
            "wo": P(None, m, None),
            "mlp_norm": P(None, None),
            "router": P(None, None, None),
            "w_gate": P(None, e, None, m),
            "w_up": P(None, e, None, m),
            "w_down": P(None, e, m, None),
        },
        "final_norm": P(None),
        "lm_head": P(None, m),
    }


def init_kv_cache(
    cfg: ModelConfig, batch: int, max_seq: int, dtype: jnp.dtype = jnp.bfloat16
) -> KVCache:
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


# ---------------------------------------------------------------- MoE block


def _default_dispatch() -> str:
    """Module default for the MoE dispatch form (read at TRACE time, so a
    jitted caller latches the value its first call saw). ``scatter`` is
    strictly cheaper off the EP path; ``parallel/serving`` pins ``einsum``
    explicitly when the expert axis is sharded (the all-to-all lowering
    needs the einsum form)."""
    return os.environ.get("SWARMDB_MOE_DISPATCH", "scatter")


def moe_block(
    x: jnp.ndarray,          # [B, T, D]
    router_w: jnp.ndarray,   # [D, E]
    w_gate: jnp.ndarray,     # [E, D, F]
    w_up: jnp.ndarray,       # [E, D, F]
    w_down: jnp.ndarray,     # [E, F, D]
    top_k: int,
    capacity_factor: float = DEFAULT_CAPACITY_FACTOR,
    dispatch: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k routed expert FFN with capacity-based dispatch.

    Returns (output [B, T, D], router aux: mean expert load [E] for
    balance metrics). Static shapes: capacity C = ceil(N * top_k / E *
    capacity_factor); overflow tokens are dropped (zero contribution).
    ``dispatch`` picks the einsum (EP-shardable) or scatter (single-device
    fast path) realization — same routing, same values (module docstring).
    """
    B, T, D = x.shape
    E = router_w.shape[-1]
    N = B * T
    C = max(1, int(N * top_k * capacity_factor / E))
    if dispatch is None:
        dispatch = _default_dispatch()

    xf = x.reshape(N, D)
    router_logits = jnp.einsum(
        "nd,de->ne", xf.astype(jnp.float32), router_w.astype(jnp.float32)
    )

    # top-k gating, Mixtral convention: softmax over the SELECTED logits
    top_logits, top_idx = jax.lax.top_k(router_logits, top_k)      # [N, k]
    gates = jax.nn.softmax(top_logits, axis=-1)                    # [N, k]

    # expert assignment one-hots [N, k, E]
    assign = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)

    # position of each (token, choice) within its expert queue: cumsum over
    # the flattened (k-major) token order
    flat_assign = assign.reshape(N * top_k, E)
    pos_in_expert = (jnp.cumsum(flat_assign, axis=0) - flat_assign)  # [N*k, E]
    pos = jnp.sum(pos_in_expert * flat_assign, axis=-1).reshape(N, top_k)
    pos = pos.astype(jnp.int32)
    within_cap = pos < C
    load = jnp.mean(jnp.sum(assign, axis=1), axis=0)               # [E]

    if dispatch == "scatter":
        # token scatter into per-expert queues. (expert, pos) pairs are
        # unique by construction (pos = running count within its expert),
        # so the set never collides; over-capacity choices target column C
        # which mode="drop" discards.
        e_idx = top_idx.reshape(-1)                                # [N*k]
        c_idx = jnp.where(within_cap, pos, C).reshape(-1)
        tok_rows = jnp.repeat(jnp.arange(N), top_k)                # [N*k]
        xe = jnp.zeros((E, C, D), x.dtype).at[e_idx, c_idx].set(
            xf[tok_rows], mode="drop")
        g = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, w_gate))
        u = jnp.einsum("ecd,edf->ecf", xe, w_up)
        ye = jnp.einsum("ecf,efd->ecd", g * u, w_down)             # [E, C, D]
        # gather each (token, choice)'s result back, gate-weighted;
        # over-capacity choices read a clamped row and are masked to zero
        yk = ye[e_idx, jnp.minimum(c_idx, C - 1)]                  # [N*k, D]
        yk = yk * (within_cap.reshape(-1)[:, None]
                   * gates.reshape(-1)[:, None]).astype(x.dtype)
        y = jnp.zeros((N, D), x.dtype).at[tok_rows].add(yk)
        return y.reshape(B, T, D), load

    # dispatch [N, E, C] (0/1) and combine [N, E, C] (gate-weighted)
    pos_oh = jax.nn.one_hot(pos, C, dtype=jnp.float32)             # [N, k, C]
    disp_k = assign[:, :, :, None] * pos_oh[:, :, None, :]         # [N, k, E, C]
    disp_k = disp_k * within_cap[:, :, None, None]
    dispatch_t = jnp.sum(disp_k, axis=1)                           # [N, E, C]
    combine = jnp.sum(disp_k * gates[:, :, None, None], axis=1)    # [N, E, C]

    # expert-major compute (bf16 matmuls on the MXU)
    xe = jnp.einsum("nd,nec->ecd", xf, dispatch_t.astype(x.dtype))  # [E, C, D]
    g = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, w_gate))
    u = jnp.einsum("ecd,edf->ecf", xe, w_up)
    ye = jnp.einsum("ecf,efd->ecd", g * u, w_down)                 # [E, C, D]
    y = jnp.einsum("ecd,nec->nd", ye, combine.astype(x.dtype))

    return y.reshape(B, T, D), load


# ------------------------------------------------------------------- forward


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    cache: KVCache,
    logits_at: Optional[jnp.ndarray] = None,
    moe_dispatch: Optional[str] = None,
) -> Tuple[jnp.ndarray, KVCache]:
    """Forward pass; same contract as ``llama.forward`` (fp32 logits +
    updated cache, head-at-last-position via ``logits_at``), with
    per-layer MoE FFN."""
    if not cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is dense; use models.llama.forward")
    x = params["embed"][tokens]
    cache_k, cache_v = cache
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    def layer_step(x, scanned):
        lp, ck, cv = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        B, T = h.shape[0], h.shape[1]
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        ck, cv = write_kv_cache(ck, cv, k, v, positions)
        attn = gqa_attention(q, ck, cv, positions, window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])

        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        # the router-load aux is for direct moe_block callers (tests,
        # balance metrics); the serving forward keeps the llama cache-only
        # scan contract and drops it here
        moe_out, _load = moe_block(
            h2, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            top_k=cfg.experts_per_token, dispatch=moe_dispatch,
        )
        x = x + moe_out
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache_k, cache_v)
    )

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if logits_at is not None:
        x = x[jnp.arange(x.shape[0]), logits_at]
        logits = jnp.einsum("bd,dv->bv", x, params["lm_head"],
                            preferred_element_type=jnp.float32)
        return logits, (new_k, new_v)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, (new_k, new_v)


# chunk-KV / prefix-pool helpers are attention-side and identical across
# families — shared with the dense stack (one definition, review finding r4)
from .llama import (  # noqa: E402, F401
    init_chunk_kv,
    init_prefix_pool,
    merge_chunk,
    merge_chunk_scatter,
    merge_paged_chunk,
)


def forward_prefix_pages(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,        # [Bp, T] SUFFIX tokens (padded)
    prefix_table: jnp.ndarray,  # [Bp, PP] int32 prefix-pool page ids
    prefix_lens: jnp.ndarray,   # [Bp] int32 reused prefix length (tokens)
    pool_k: jnp.ndarray,        # [L, P, ps, Hkv, D]
    pool_v: jnp.ndarray,
    logits_at: Optional[jnp.ndarray] = None,
    moe_dispatch: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Prefix-cache suffix prefill core (see ``llama.forward_prefix_pages``
    for the design); MoE FFN unchanged. Returns (fp32 logits, sfx_k,
    sfx_v [L, Bp, T, Hkv, D])."""
    if not cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is dense; use models.llama")
    from ..ops.layers import gqa_attention_prefix

    from ..ops.paged_kv import (_dequantize_pages, is_quantized, pool_data,
                                pool_flat)

    Bp, T = tokens.shape
    quant = is_quantized(pool_k)
    L, P = pool_data(pool_k).shape[0], pool_data(pool_k).shape[1]
    ps = pool_data(pool_k).shape[2]
    Pt = prefix_table.shape[1] * ps
    x = params["embed"][tokens]
    positions = prefix_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None]
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    pool_k_flat = pool_flat(pool_k)
    pool_v_flat = pool_flat(pool_v)

    def _gather_pages(flat, idx):
        if quant:
            return _dequantize_pages(flat.data[idx], flat.scale[idx]
                                     ).reshape(Bp, Pt, cfg.n_kv_heads,
                                               cfg.head_dim)
        return flat[idx].reshape(Bp, Pt, cfg.n_kv_heads, cfg.head_dim)

    def layer_step(x, scanned):
        lp, l = scanned
        kp = _gather_pages(pool_k_flat, l * P + prefix_table)
        vp = _gather_pages(pool_v_flat, l * P + prefix_table)
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        attn = gqa_attention_prefix(q, kp, vp, k.astype(kp.dtype),
                                    v.astype(vp.dtype), prefix_lens,
                                    window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(Bp, T, -1), lp["wo"])
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        moe_out, _load = moe_block(
            h2, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            top_k=cfg.experts_per_token, dispatch=moe_dispatch,
        )
        x = x + moe_out
        return x, (k.astype(kp.dtype), v.astype(vp.dtype))

    x, (sfx_k, sfx_v) = jax.lax.scan(
        layer_step, x,
        (params["layers"], jnp.arange(L, dtype=jnp.int32)),
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if logits_at is not None:
        x = x[jnp.arange(x.shape[0]), logits_at]
        logits = jnp.einsum("bd,dv->bv", x, params["lm_head"],
                            preferred_element_type=jnp.float32)
        return logits, sfx_k, sfx_v
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, sfx_k, sfx_v


def forward_prefix_lane(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    prefix_table: jnp.ndarray,
    prefix_lens: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    lane_pages: int,
    logits_at: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dense-cache prefix prefill: core + shared lane composition (see
    ``llama.forward_prefix_lane``)."""
    from ..ops.layers import compose_prefix_lane

    logits, sfx_k, sfx_v = forward_prefix_pages(
        params, cfg, tokens, prefix_table, prefix_lens, pool_k, pool_v,
        logits_at=logits_at)
    lane_k, lane_v = compose_prefix_lane(
        pool_k, pool_v, prefix_table, prefix_lens, sfx_k, sfx_v, lane_pages)
    return logits, lane_k, lane_v


def forward_paged_chunked(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,       # [B, 1]
    positions: jnp.ndarray,    # [B, 1]
    cache,                     # {"k","v","page_table"} — FROZEN this chunk
    chunk_kv: Tuple[jnp.ndarray, jnp.ndarray],
    step: jnp.ndarray,
    moe_dispatch: Optional[str] = None,
):
    """Two-segment chunked decode over the paged pool (see
    ``llama.forward_paged_chunked``); MoE FFN unchanged."""
    if not cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is dense; use models.llama")
    from ..ops.layers import paged_attention_dispatch_chunked

    x = params["embed"][tokens]
    table = cache["page_table"]
    chunk_k, chunk_v = chunk_kv
    pos0 = cache.get("pos0")  # rolling-KV RoPE offset (llama.forward_paged)
    rope_pos = positions if pos0 is None else positions + pos0[:, None]
    cos, sin = rope_cos_sin(rope_pos, cfg.head_dim, cfg.rope_theta)

    def layer_step(x, scanned):
        lp, kp, vp, hk, hv = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        B, T = h.shape[0], h.shape[1]
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        hk = jax.lax.dynamic_update_slice(hk, k.astype(hk.dtype),
                                          (0, step, 0, 0))
        hv = jax.lax.dynamic_update_slice(hv, v.astype(hv.dtype),
                                          (0, step, 0, 0))
        attn = paged_attention_dispatch_chunked(
            q, kp, vp, table, hk, hv, positions, step,
            window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        moe_out, _load = moe_block(
            h2, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            top_k=cfg.experts_per_token, dispatch=moe_dispatch,
        )
        x = x + moe_out
        return x, (hk, hv)

    x, (new_hk, new_hv) = jax.lax.scan(
        layer_step, x,
        (params["layers"], cache["k"], cache["v"], chunk_k, chunk_v),
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, (new_hk, new_hv)


def forward_chunked(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,       # [B, 1]
    positions: jnp.ndarray,    # [B, 1]
    cache: KVCache,            # FROZEN during the chunk
    chunk_kv: Tuple[jnp.ndarray, jnp.ndarray],
    step: jnp.ndarray,         # scalar int32
    moe_dispatch: Optional[str] = None,
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Two-segment chunked decode step (see ``llama.forward_chunked``);
    MoE FFN unchanged."""
    if not cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is dense; use models.llama")
    x = params["embed"][tokens]
    cache_k, cache_v = cache
    chunk_k, chunk_v = chunk_kv
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    def layer_step(x, scanned):
        lp, ck, cv, hk, hv = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        B, T = h.shape[0], h.shape[1]
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        hk = jax.lax.dynamic_update_slice(hk, k.astype(hk.dtype),
                                          (0, step, 0, 0))
        hv = jax.lax.dynamic_update_slice(hv, v.astype(hv.dtype),
                                          (0, step, 0, 0))
        attn = gqa_attention_chunked(q, ck, cv, hk, hv, positions, step,
                                     window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        moe_out, _load = moe_block(
            h2, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            top_k=cfg.experts_per_token, dispatch=moe_dispatch,
        )
        x = x + moe_out
        return x, (hk, hv)

    x, (new_hk, new_hv) = jax.lax.scan(
        layer_step, x, (params["layers"], cache_k, cache_v, chunk_k, chunk_v)
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, (new_hk, new_hv)


def init_paged_cache(
    cfg: ModelConfig,
    batch: int,
    max_seq: int,
    num_pages: int,
    page_size: int,
    dtype: Optional[jnp.dtype] = None,
):
    """Block-paged KV pool; see ``llama.init_paged_cache``.

    ``dtype=None`` resolves from ``SWARMDB_KV_DTYPE`` (int8 → quantized
    ``QuantPool``)."""
    from ..ops.paged_kv import init_paged_kv_cache

    return init_paged_kv_cache(
        cfg.n_layers, num_pages, page_size, cfg.n_kv_heads, cfg.head_dim,
        batch, max_seq, dtype,
    )


def forward_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,     # [B, 1] — DECODE steps only
    positions: jnp.ndarray,  # [B, 1]
    cache,                   # {"k", "v", "page_table"}
    moe_dispatch: Optional[str] = None,
):
    """Decode forward over the block-paged KV pool; MoE FFN unchanged.
    Same contract as ``llama.forward_paged``."""
    if not cfg.is_moe:
        raise ValueError(f"{cfg.name!r} is dense; use models.llama.forward_paged")
    from ..ops.layers import paged_attention_dispatch
    from ..ops.paged_kv import paged_write_decode

    x = params["embed"][tokens]
    table = cache["page_table"]
    pos0 = cache.get("pos0")  # rolling-KV RoPE offset (llama.forward_paged)
    rope_pos = positions if pos0 is None else positions + pos0[:, None]
    cos, sin = rope_cos_sin(rope_pos, cfg.head_dim, cfg.rope_theta)

    def layer_step(x, scanned):
        lp, kp, vp = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        B, T = h.shape[0], h.shape[1]
        q, k, v = qkv_proj(h, lp, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cos, sin)
        kp, vp = paged_write_decode(kp, vp, k, v, positions, table)
        attn = paged_attention_dispatch(
            q, kp, vp, table, positions, window=cfg.sliding_window)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), lp["wo"])
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        moe_out, _load = moe_block(
            h2, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            top_k=cfg.experts_per_token, dispatch=moe_dispatch,
        )
        x = x + moe_out
        return x, (kp, vp)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    out = {"k": new_k, "v": new_v, "page_table": table}
    if pos0 is not None:
        out["pos0"] = pos0
    return logits, out
