"""Observability: request-span tracing + engine flight recorder.

Stdlib-only (no jax import) so the broker/runtime layers can record
spans in processes that never touch a device, and so swarmlint's CI job
can import the package without the ML stack.

- :mod:`.tracer` — per-thread ring-buffer span tracer, Chrome
  trace-event export (``GET /admin/trace/export``, bounded).
- :mod:`.flight` — fixed-size rings of engine-step and request records,
  dumped on watchdog restart and via ``GET /admin/flight``.
- :mod:`.propagate` — cluster-wide trace context (carried on the data
  plane / cluster-client / replication wires) and the per-node trace
  merge behind ``GET /admin/cluster/trace``.
- :mod:`.metrics` — lock-free fixed-bucket latency histograms exported
  in Prometheus histogram format from ``/metrics``, with per-bucket
  trace-id exemplars in OpenMetrics syntax.
- :mod:`.analyze` — offline trace/flight analyzer
  (``python -m swarmdb_tpu.obs.analyze``): per-completion cost
  decomposition and A/B regression attribution.
- :mod:`.sentinel` — the ONLINE counterpart (``GET /admin/slo``):
  rolling-window SLO monitor that learns a baseline, runs the analyzer's
  attributor in-process on breach, and auto-dumps flight + trace
  evidence tagged with the alert id.
- :mod:`.profiler` — swarmprof (``GET /admin/profile``): always-on
  device-time profiler — XLA cost-model harvest at warmup, per-variant
  invocation/device-time accounting, MFU/roofline classification,
  per-lane duty cycles, and the dispatch-shape (wave kind x width)
  profile.
- :mod:`.memprof` — swarmmem (``GET /admin/mem``): always-on KV/prefix
  memory accountant — pool occupancy decomposition + residency ages,
  the per-conversation hot/warm/cold temperature ledger, SHARDS-sampled
  miss-ratio curves over prefix-cache accesses, and the warm-tier /
  cold-resume what-if models ROADMAP item 3 is sized against.
"""

from . import propagate
from .flight import FlightRecorder
from .memprof import MemProfiler, memprof, memprof_enabled
from .metrics import HISTOGRAMS, Histogram, HistogramRegistry
from .profiler import KernelProfiler, profile_enabled, profiler
from .sentinel import SLOConfig, SLOSentinel
from .tracer import TRACER, SpanTracer

__all__ = ["FlightRecorder", "SpanTracer", "TRACER", "propagate",
           "HISTOGRAMS", "Histogram", "HistogramRegistry",
           "SLOConfig", "SLOSentinel",
           "KernelProfiler", "profile_enabled", "profiler",
           "MemProfiler", "memprof", "memprof_enabled"]
