"""Observability: request-span tracing + engine flight recorder.

Stdlib-only (no jax import) so the broker/runtime layers can record
spans in processes that never touch a device, and so swarmlint's CI job
can import the package without the ML stack.

- :mod:`.tracer` — per-thread ring-buffer span tracer, Chrome
  trace-event export (``GET /admin/trace/export``).
- :mod:`.flight` — fixed-size rings of engine-step and request records,
  dumped on watchdog restart and via ``GET /admin/flight``.
"""

from .flight import FlightRecorder
from .tracer import TRACER, SpanTracer

__all__ = ["FlightRecorder", "SpanTracer", "TRACER"]
