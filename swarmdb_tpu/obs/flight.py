"""Always-on flight recorder: the last N engine steps + last M requests.

When the engine dies (watchdog restart, in-loop error recovery) the
question is always "what was it doing right before?" — and the metrics
counters only answer "how much, ever". The flight recorder keeps two
fixed-size rings that are cheap enough to feed on every engine-loop
iteration:

- **step records** — one per engine step while work exists: batch
  occupancy (per shard on DP-sharded pools), queue depth by priority,
  pipeline depth in flight, and the cumulative counters that explain
  throughput (prompt/generated tokens, prefill padding waste, prefix
  hit/miss tokens, sanctioned host syncs, compiled-variant count,
  restarts).
- **request records** — one per retirement: the request's timeline
  (submitted → admitted → first token → retired), priority, prompt
  length, generated count, finish reason.
- **event records** — rare discrete facts from OTHER subsystems (HA
  detector transitions, promotions, fencing, chaos injections): written
  from arbitrary threads under a small lock (events are per-incident,
  not per-step, so the lock never sits on a hot path).

The step/request rings are written ONLY by the engine thread (no locks
on the record path); readers snapshot racily, which at worst tears one
record. Dumps are triggered automatically by :meth:`Engine.restart` (the
watchdog path), by HA promotions/deposals, and on demand via ``GET
/admin/flight``; ``bench.py`` deposits one per mode under
``bench_logs/``.

Knobs: ``SWARMDB_FLIGHT_STEPS`` (ring size, default 512),
``SWARMDB_FLIGHT_REQUESTS`` (default 256), ``SWARMDB_FLIGHT_EVENTS``
(default 256), ``SWARMDB_FLIGHT_DIR`` (where automatic dumps land;
unset = in-memory ``last_dump`` only).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional
from ..utils.sync import make_lock

logger = logging.getLogger("swarmdb_tpu.obs")

__all__ = ["FlightRecorder"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# process-monotonic dump sequence, shared by every recorder instance:
# itertools.count's __next__ is a single C call, so concurrent dumpers
# can never draw the same number
_DUMP_SEQ = itertools.count(1)


class _DictRing:
    """Fixed-size single-writer ring of dict records."""

    __slots__ = ("records", "idx", "cap")

    def __init__(self, cap: int) -> None:
        self.records: List[Optional[Dict[str, Any]]] = [None] * cap
        self.idx = 0
        self.cap = cap

    def put(self, rec: Dict[str, Any]) -> None:
        self.records[self.idx % self.cap] = rec
        self.idx += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        idx = self.idx
        records = list(self.records)
        if idx <= self.cap:
            out = records[:idx]
        else:
            cut = idx % self.cap
            out = records[cut:] + records[:cut]
        return [r for r in out if r is not None]


class FlightRecorder:
    def __init__(self, n_steps: Optional[int] = None,
                 n_requests: Optional[int] = None,
                 n_events: Optional[int] = None) -> None:
        if n_steps is None:
            n_steps = _env_int("SWARMDB_FLIGHT_STEPS", 512)
        if n_requests is None:
            n_requests = _env_int("SWARMDB_FLIGHT_REQUESTS", 256)
        if n_events is None:
            n_events = _env_int("SWARMDB_FLIGHT_EVENTS", 256)
        self._steps = _DictRing(max(8, n_steps))
        self._requests = _DictRing(max(8, n_requests))
        self._events = _DictRing(max(8, n_events))
        # events come from arbitrary threads (HA detector/promotion,
        # chaos) — rare, so a lock is fine HERE and only here
        self._events_lock = make_lock("obs.flight.FlightRecorder._events_lock")
        # free-form identity (mesh shape, shard count, model) set by the
        # engine builder; rides every dump
        self.meta: Dict[str, Any] = {}
        self.last_dump: Optional[Dict[str, Any]] = None
        self.last_dump_path: Optional[str] = None
        # with the lock sanitizer on (SWARMDB_LOCKCHECK=1), inversion
        # violations land in this event ring as `lockcheck.inversion`
        # instants — every subsystem's recorder registers itself so the
        # cycle shows up next to whatever the subsystem was doing
        if os.environ.get("SWARMDB_LOCKCHECK", "0") not in ("", "0"):
            from . import lockcheck

            lockcheck.registry().attach_flight(self)

    # ---------------------------------------------------------- record path

    def record_step(self, rec: Dict[str, Any]) -> None:
        """One engine-step record (engine thread only — no locks)."""
        self._steps.put(rec)

    def record_request(self, rec: Dict[str, Any]) -> None:
        """One completed/failed request timeline (engine thread only)."""
        self._requests.put(rec)

    def record_event(self, rec: Dict[str, Any]) -> None:
        """One discrete incident (HA transition, chaos injection) — any
        thread; locked because events have no single owner."""
        with self._events_lock:
            self._events.put(rec)

    # -------------------------------------------------------------- reading

    def steps(self) -> List[Dict[str, Any]]:
        return self._steps.snapshot()

    def requests(self) -> List[Dict[str, Any]]:
        return self._requests.snapshot()

    def events(self) -> List[Dict[str, Any]]:
        return self._events.snapshot()

    def dump(self, reason: str = "on_demand") -> Dict[str, Any]:
        return {
            "reason": reason,
            "dumped_at": time.time(),
            "meta": dict(self.meta),
            "steps": self.steps(),
            "requests": self.requests(),
            "events": self.events(),
        }

    def _dump_identity(self) -> str:
        """Node identity for dump filenames: the recorder's own meta (set
        by HANode / the engine builder), else the process's configured
        node id, else the pid — never empty, filename-safe."""
        raw = (str(self.meta.get("node_id") or "")
               or os.environ.get("SWARMDB_NODE_ID")
               or f"p{os.getpid()}")
        return re.sub(r"[^A-Za-z0-9_.-]", "_", raw)

    def dump_to(self, directory: str, reason: str = "on_demand") -> str:
        """Write a dump file under ``directory`` and return its path.

        The filename carries the node identity and a process-monotonic
        sequence number, not just the millisecond stamp: two
        near-simultaneous dumpers (watchdog restart racing an HA
        promotion, two nodes sharing SWARMDB_FLIGHT_DIR) used to
        collide on the same millisecond and silently overwrite each
        other's post-mortem (ISSUE 6 satellite)."""
        os.makedirs(directory, exist_ok=True)
        payload = self.dump(reason)
        path = os.path.join(
            directory,
            f"flight_{int(payload['dumped_at'] * 1000)}_"
            f"{self._dump_identity()}_{next(_DUMP_SEQ)}_{reason}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        self.last_dump = payload
        self.last_dump_path = path
        return path

    def auto_dump(self, reason: str,
                  directory: Optional[str] = None) -> Optional[str]:
        """Best-effort dump for failure paths (watchdog restart, engine
        error): never raises — the recovery it instruments must survive a
        full disk or an unwritable directory. ``SWARMDB_FLIGHT_DIR``
        overrides the configured directory (CI uploads one fixed dir);
        with neither set, only the in-memory ``last_dump`` is kept."""
        directory = os.environ.get("SWARMDB_FLIGHT_DIR") or directory
        try:
            if directory:
                path = self.dump_to(directory, reason)
                logger.info("flight record dumped to %s (%s)", path, reason)
                self._profile_dump(reason, directory)
                self._mem_dump(reason, directory)
                return path
            self.last_dump = self.dump(reason)
            return None
        except Exception:
            logger.exception("flight-record dump failed (%s)", reason)
            try:
                self.last_dump = self.dump(reason)
            except Exception:
                pass
            return None

    @staticmethod
    def _profile_dump(reason: str, directory: str) -> None:
        """swarmprof dump riding every flight auto-dump (ISSUE 15): the
        failure paths that ship flight evidence — watchdog restarts,
        sentinel alerts, CI failure artifacts — ship the kernel-level
        device-time picture too. Best-effort, never raises."""
        try:
            from .profiler import profile_enabled, profiler

            if profile_enabled():
                profiler().auto_dump(reason, directory)
        except Exception:
            logger.exception("profile dump failed (%s)", reason)

    @staticmethod
    def _mem_dump(reason: str, directory: str) -> None:
        """swarmmem snapshot riding every flight auto-dump (ISSUE 17):
        the same failure artifacts carry the pool occupancy /
        temperature / miss-ratio picture. Best-effort, never raises."""
        try:
            from .memprof import memprof, memprof_enabled

            if memprof_enabled():
                memprof().auto_dump(reason, directory)
        except Exception:
            logger.exception("mem dump failed (%s)", reason)
