"""Fixed-bucket latency histograms for /metrics (ISSUE 6 tentpole, part 2).

``utils/metrics.py``'s ``LatencyHistogram`` is a locked sample reservoir:
good for bench-window percentiles, wrong for a scraped endpoint — the
/metrics export was all gauges and summaries over a 4096-sample ring, so
p50/p99 over time were invisible outside bench runs. This module is the
Prometheus-native counterpart: **fixed bucket boundaries, cumulative
counts, no locks on the record path**.

Record-path contract (these run inside the engine decode loop and the
data-plane client):

- ``observe()`` is allocation-free: a C-level ``bisect`` over a static
  boundary tuple plus two integer adds into a preallocated list. No
  dict lookup, no string formatting, no lock.
- Increments are deliberately unguarded. CPython's GIL makes each
  ``counts[i] += 1`` a read-modify-write that can lose a count under
  contention — at worst one observation, never a crash or a torn
  bucket, matching the tracer's benign-racy-read stance. Scrapes read
  a snapshot copy.
- Histograms are bound ONCE (module constants below, or attributes set
  at engine init) and the bound object is what hot paths call.
  swarmlint SWL503 polices the anti-pattern: a per-call registry/dict
  lookup (``REGISTRY.get("x").observe(v)``, ``latencies["x"].observe``)
  or a per-call ``Histogram(...)`` inside ``# swarmlint: hot`` code.

Bucket boundaries are STABLE — dashboards and recording rules key on
``le`` values, so changing a ladder is a breaking change. Two ladders:

- ``LADDER_FAST`` (0.1 ms … 2.5 s): decode-chunk latency, data-plane
  RTT — things that should live in single-digit milliseconds.
- ``LADDER_WIDE`` (1 ms … 60 s): TTFT, queue wait, replication commit
  wait — things that legitimately stretch under load.

``SWARMDB_HISTOGRAMS=0`` disables recording (the bench echo A/B flips
this together with the tracer to measure the combined overhead against
the ≤5% budget).

**Exemplars** (ISSUE 7): each bucket optionally retains the trace id of
the most recent observation that landed in it, so a tail bucket links
directly to a real request timeline (``/admin/trace/export?trace_id=``,
or the merged cluster trace). The retention is three preallocated
parallel slot lists written in-place — no dict, no tuple, no string
built per observation (swarmlint SWL504 polices this in
``# swarmlint: hot`` exemplar/sentinel code). Rendered in OpenMetrics
exemplar syntax (``... # {trace_id="..."} <value> <ts>``) appended to
the affected ``_bucket`` lines, and surfaced with export links at
``GET /admin/slo``. ``SWARMDB_EXEMPLARS=0`` disables retention without
touching the counts.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Tuple
from ..utils.sync import make_lock

__all__ = ["Histogram", "HistogramRegistry", "HISTOGRAMS",
           "LADDER_FAST", "LADDER_WIDE",
           "HIST_TTFT", "HIST_DECODE_CHUNK", "HIST_QUEUE_WAIT",
           "HIST_DATAPLANE_RTT", "HIST_REPLICATION_COMMIT"]

#: seconds; upper bounds of each bucket (an implicit +Inf bucket follows)
LADDER_FAST: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5)
LADDER_WIDE: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0)


class Histogram:
    """One fixed-bucket histogram; single-object record path."""

    __slots__ = ("name", "help", "boundaries", "counts", "total", "sum_s",
                 "enabled", "exemplars_enabled",
                 "_ex_rids", "_ex_vals", "_ex_ts")

    def __init__(self, name: str, boundaries: Tuple[float, ...],
                 help_text: str = "") -> None:
        self.name = name
        self.help = help_text
        self.boundaries = tuple(float(b) for b in boundaries)
        if list(self.boundaries) != sorted(set(self.boundaries)):
            raise ValueError(f"histogram {name}: boundaries must be "
                             "strictly increasing")
        # per-bucket (non-cumulative) counts + the +Inf bucket at [-1]
        self.counts: List[int] = [0] * (len(self.boundaries) + 1)
        self.total = 0
        self.sum_s = 0.0
        self.enabled = True
        # per-bucket exemplar slots (most recent rid to land in each
        # bucket): three parallel preallocated lists so retention is a
        # slot write, never a dict/tuple build per observation
        self.exemplars_enabled = (
            os.environ.get("SWARMDB_EXEMPLARS", "1") != "0")
        n = len(self.counts)
        self._ex_rids: List[Optional[str]] = [None] * n
        self._ex_vals: List[float] = [0.0] * n
        self._ex_ts: List[float] = [0.0] * n

    # swarmlint: hot
    def observe(self, seconds: float, rid: Optional[str] = None) -> None:
        """Record one latency (hot path: no locks, no allocation beyond
        CPython's arithmetic; a lost count under a write race is the
        accepted failure mode). ``rid`` — the observation's trace id —
        is retained as that bucket's exemplar (in-place slot write)."""
        if not self.enabled:
            return
        i = bisect_left(self.boundaries, seconds)
        self.counts[i] += 1
        self.total += 1
        self.sum_s += seconds
        if rid is not None and self.exemplars_enabled:
            self._ex_rids[i] = rid
            self._ex_vals[i] = seconds
            self._ex_ts[i] = time.time()

    # -------------------------------------------------------------- reading

    def snapshot(self) -> Dict[str, object]:
        counts = list(self.counts)  # one-shot copy; benign race
        return {
            "name": self.name,
            "boundaries": list(self.boundaries),
            "counts": counts,
            "count": sum(counts),
            "sum_s": self.sum_s,
        }

    def exemplars(self) -> List[Dict[str, Any]]:
        """Retained bucket exemplars, tail-first (highest bucket first —
        the slow requests are the ones worth opening). Each entry names
        the bucket's ``le`` bound, the trace id, the observed value, and
        its age; the caller turns the trace id into an export link."""
        now = time.time()
        out: List[Dict[str, Any]] = []
        for i in range(len(self.counts) - 1, -1, -1):
            rid = self._ex_rids[i]
            if rid is None:
                continue
            le = ("+Inf" if i == len(self.boundaries)
                  else f"{self.boundaries[i]:g}")
            out.append({
                "le": le,
                "trace_id": rid,
                "value_s": round(self._ex_vals[i], 6),
                "age_s": round(max(0.0, now - self._ex_ts[i]), 3),
            })
        return out

    def render_prometheus(self, prefix: str = "swarmdb_",
                          exemplars: bool = False) -> List[str]:
        """Prometheus text-exposition histogram block (cumulative
        ``_bucket{le=...}`` counts + ``_sum`` + ``_count``). With
        ``exemplars=True``, buckets that retained one get the
        OpenMetrics exemplar suffix
        (``# {trace_id="..."} <value> <timestamp>``)."""
        n = f"{prefix}{self.name}"
        lines = [f"# TYPE {n} histogram"]
        snap = self.snapshot()

        def _ex(i: int) -> str:
            if not exemplars or self._ex_rids[i] is None:
                return ""
            return (f' # {{trace_id="{self._ex_rids[i]}"}} '
                    f"{self._ex_vals[i]:.6g} {self._ex_ts[i]:.3f}")

        cum = 0
        for i, (bound, c) in enumerate(zip(self.boundaries,
                                           snap["counts"])):
            cum += c
            lines.append(f'{n}_bucket{{le="{bound:g}"}} {cum}{_ex(i)}')
        cum += snap["counts"][-1]
        lines.append(f'{n}_bucket{{le="+Inf"}} {cum}'
                     f"{_ex(len(self.boundaries))}")
        lines.append(f"{n}_sum {snap['sum_s']:.6f}")
        lines.append(f"{n}_count {cum}")
        return lines

    def reset(self) -> None:
        self.counts = [0] * (len(self.boundaries) + 1)
        self.total = 0
        self.sum_s = 0.0
        n = len(self.counts)
        self._ex_rids = [None] * n
        self._ex_vals = [0.0] * n
        self._ex_ts = [0.0] * n


class HistogramRegistry:
    """Named histograms, registered once at import/init time (the
    registration lock never sits on a record path — hot paths hold the
    returned Histogram object)."""

    def __init__(self, enabled: Optional[bool] = None) -> None:
        if enabled is None:
            enabled = os.environ.get("SWARMDB_HISTOGRAMS", "1") != "0"
        self._lock = make_lock("obs.metrics.HistogramRegistry._lock")
        # swarmlint: guarded-by[self._lock]: _hists
        self._hists: Dict[str, Histogram] = {}
        self.enabled = bool(enabled)

    def register(self, name: str, boundaries: Tuple[float, ...],
                 help_text: str = "") -> Histogram:
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = Histogram(name, boundaries, help_text)
                hist.enabled = self.enabled
                self._hists[name] = hist
            return hist

    def get(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(name)

    def all(self) -> List[Histogram]:
        with self._lock:
            return list(self._hists.values())

    def set_enabled(self, enabled: bool) -> None:
        """Flip recording everywhere (bench echo A/B; mirrors
        ``SpanTracer.set_enabled``)."""
        self.enabled = bool(enabled)
        for hist in self.all():
            hist.enabled = self.enabled

    def set_exemplars_enabled(self, enabled: bool) -> None:
        """Flip exemplar retention everywhere (the bench echo A/B
        toggles this together with the tracer/histograms/sentinel so the
        ≤5% overhead budget covers the slot writes too)."""
        for hist in self.all():
            hist.exemplars_enabled = bool(enabled)

    def exemplars(self) -> Dict[str, List[Dict[str, Any]]]:
        """name -> tail-first exemplar list, omitting empty histograms
        (the ``/admin/slo`` exemplar surface)."""
        out: Dict[str, List[Dict[str, Any]]] = {}
        for hist in sorted(self.all(), key=lambda h: h.name):
            ex = hist.exemplars()
            if ex:
                out[hist.name] = ex
        return out

    def render_prometheus(self, prefix: str = "swarmdb_",
                          exemplars: bool = False) -> List[str]:
        lines: List[str] = []
        for hist in sorted(self.all(), key=lambda h: h.name):
            lines.extend(hist.render_prometheus(prefix,
                                                exemplars=exemplars))
        return lines

    def reset(self) -> None:
        for hist in self.all():
            hist.reset()


#: process-global registry, exported at /metrics next to the counters
HISTOGRAMS = HistogramRegistry()

# The serving-path histograms (README "Observability" documents the
# ladders; tests pin them — treat boundary changes as breaking):
HIST_TTFT = HISTOGRAMS.register(
    "ttft_seconds", LADDER_WIDE,
    "submit -> first emitted token, per engine request")
HIST_QUEUE_WAIT = HISTOGRAMS.register(
    "queue_wait_seconds", LADDER_WIDE,
    "submit -> admission into a decode slot")
HIST_DECODE_CHUNK = HISTOGRAMS.register(
    "decode_chunk_seconds", LADDER_FAST,
    "decode-chunk dispatch -> host-processed")
HIST_DATAPLANE_RTT = HISTOGRAMS.register(
    "dataplane_rtt_seconds", LADDER_FAST,
    "data-plane client op round-trip (excludes server-side blocking "
    "wait ops)")
HIST_REPLICATION_COMMIT = HISTOGRAMS.register(
    "replication_commit_seconds", LADDER_WIDE,
    "append -> acks=all durable watermark passed it (replication lag "
    "as writers experience it)")
HIST_PUBLISH = HISTOGRAMS.register(
    "broker_publish_seconds", LADDER_FAST,
    "runtime send -> broker accepted the produce (the echo-mode record "
    "path, so the bench A/B overhead budget covers histogram recording)")
