"""swarmprof: always-on device-time profiler (ISSUE 15 tentpole).

Every obs layer so far measures HOST wall time; MFU was one analytic
number computed at bench end from token rates. Nothing attributed device
time to the compiled programs that actually consume it — the ragged
prefill kernel (PR 11) had never been measured below whole-mode
granularity, and ROADMAP item 2's "raise SWARMDB_RAGGED_MIN_WIDTH if
tiny flush waves show up in the dispatch profile" was blocked on a
dispatch profile that did not exist. This module is that profiler:

- **Cost harvest at warmup/compile time.** The engine lowers every
  variant of its warmup call plan ONCE (``jax.stages.Lowered
  .cost_analysis()`` — the XLA cost model, no compile, no execution) and
  registers per-variant static facts here: FLOPs and bytes accessed per
  invocation. Harvest never runs on a serving path — swarmlint SWL506
  flags ``cost_analysis()``/``lower()`` calls inside ``# swarmlint:
  hot`` code, and :attr:`KernelProfiler.harvest_calls` lets a test
  assert ZERO harvests after warmup.
- **Runtime accounting.** Dispatch sites record (variant key, duration)
  pairs: wall-around-dispatch on the CPU fallback (where a jit call's
  wall time ~= device time), and on the device-resident decode path the
  emission-ring CHUNK BOUNDARIES — each ordered-callback delta is one
  chunk's device wall time, so the resident session is profiled with
  zero extra syncs (``block_until_ready``-free by construction). The
  record path is two ``monotonic_ns`` reads + a dict lookup + integer
  adds (benign-racy, the histogram stance); ``SWARMDB_PROFILE=0``
  removes even that — disabled engines hold the shared
  :class:`NullLane` (type identity pinned by test) and dispatch sites
  see ``enabled == False``.
- **Derived per variant**: achieved FLOP/s over its accumulated device
  time, MFU against a per-platform peak table, arithmetic intensity
  (FLOPs/byte), and the roofline class — compute-bound when AI clears
  the platform ridge (peak FLOPs / peak bytes/s), memory-bound below.
- **Dispatch-shape profile**: per (wave kind, width) — waves, packed vs
  padding tokens, and the variant keys serving that shape, joined to
  their invocation counts / cumulative device seconds in the report.
  Tiny ragged flush waves (width <= ``SWARMDB_PROF_TINY_WIDTH``) become
  a named, queryable signal instead of folklore.
- **Per-lane duty cycles**: each engine's :class:`LaneProfile`
  accumulates busy device time; duty = busy / elapsed-since-serving
  (clamped to 1 — pipelined chunks legitimately overlap). The direct
  measure of PR 7/8's admission-overlap win: a lane admitting while its
  siblings decode shows every lane's duty high, a serialized pool shows
  one busy lane and N-1 idle ones.

Surfaces: ``GET /admin/profile`` (503 when off), ``swarmdb_mfu`` /
``swarmdb_lane_duty_cycle{lane=}`` /
``swarmdb_kernel_device_seconds_total{variant=}`` on /metrics, device
tracks merged into the Chrome trace export, ``kernel_profile`` blocks
on bench records, ``obs/analyze.py --roofline`` over profile dumps, a
sentinel MFU/duty-cycle SLO, and profile dumps riding every flight
auto-dump (the CI failure artifact ships them).

Stdlib-only (the obs-package contract): the engine does the jax-side
lowering and hands numbers in.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.sync import make_lock

logger = logging.getLogger("swarmdb_tpu.obs")

__all__ = ["KernelProfiler", "LaneProfile", "NullLane", "profiler",
           "profile_enabled", "platform_peaks"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: Optional[float]) -> Optional[float]:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def profile_enabled() -> bool:
    """One switch for the whole layer (README env catalog:
    ``SWARMDB_PROFILE``, default ON — the profiler is an always-on
    flight instrument, not a debugging session)."""
    return os.environ.get("SWARMDB_PROFILE", "1") != "0"


#: peak dense bf16 FLOP/s and HBM bytes/s per chip, public spec sheets
#: (the FLOPs column mirrors bench.py's _CHIP_PEAK_FLOPS — keep in sync)
_PLATFORM_PEAKS: Tuple[Tuple[str, float, float], ...] = (
    ("v6e", 918e12, 1640e9), ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9), ("v5litepod", 197e12, 819e9),
    ("v5lite", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 46e12, 700e9),
)

#: CPU fallback: a container core's rough f32 FMA peak (order-of-
#: magnitude only — CPU MFU is a liveness proof, not a perf claim; the
#: real numbers come from silicon, like every bench headline)
_CPU_PEAK_FLOPS = 5e10
_CPU_PEAK_BW = 2e10


def platform_peaks(platform: str, device_kind: str = "") -> Dict[str, float]:
    """{peak_flops, peak_bytes_per_s, ridge_flops_per_byte} for a jax
    platform/device-kind pair. ``SWARMDB_PEAK_FLOPS`` /
    ``SWARMDB_PEAK_BW`` override both columns (heterogeneous fleets,
    new chips the table predates)."""
    flops: Optional[float] = None
    bw: Optional[float] = None
    kind = (device_kind or "").lower().replace(" ", "").replace("tpu", "")
    if platform == "tpu" or kind:
        for key, f, b in _PLATFORM_PEAKS:
            if key in kind:
                flops, bw = f, b
                break
    if flops is None:
        flops, bw = _CPU_PEAK_FLOPS, _CPU_PEAK_BW
    flops = _env_float("SWARMDB_PEAK_FLOPS", flops)
    bw = _env_float("SWARMDB_PEAK_BW", bw)
    return {
        "peak_flops": flops,
        "peak_bytes_per_s": bw,
        "ridge_flops_per_byte": (flops / bw) if bw else None,
    }


class _Variant:
    """One compiled-program family member: static cost facts from the
    warmup harvest + runtime invocation/device-time accumulators (the
    adds are deliberately unguarded — GIL-atomic enough, a lost count
    under a write race is the accepted failure mode)."""

    __slots__ = ("name", "flops", "bytes_accessed", "invocations",
                 "device_ns", "meta")

    def __init__(self, name: str) -> None:
        self.name = name
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.invocations = 0
        self.device_ns = 0
        self.meta: Dict[str, Any] = {}


class NullLane:
    """The flag-off lane handle: every dispatch site reads ``enabled``
    (one attribute) and skips the clock reads entirely. A singleton —
    the SWARMDB_PROFILE=0 type-identity test pins that disabled engines
    share exactly this object."""

    __slots__ = ()
    enabled = False
    label = "off"

    def set_label(self, label: str) -> None:  # pragma: no cover - trivial
        pass

    def dispatch(self, key: str, t0_ns: int, dur_ns: int) -> None:
        pass

    def wave(self, kind: str, width: int, packed: int, padding: int,
             variant_key: Optional[str] = None) -> None:
        pass

    def suspend(self) -> None:
        pass

    def resume(self) -> None:
        pass


NULL_LANE = NullLane()


class LaneProfile:
    """Per-engine (= per-lane) device-time accumulator + a bounded ring
    of recent dispatches for the Chrome-trace device tracks. Written by
    the lane's engine thread and its emission-callback thread; the
    races are benign (the flight-recorder stance: rings are evidence)."""

    __slots__ = ("label", "pool", "enabled", "busy_ns", "serving_since_ns",
                 "_reg", "_ring", "_ring_idx", "_ring_cap")

    def __init__(self, reg: "KernelProfiler", label: str,
                 ring_cap: int) -> None:
        self.label = label
        self.pool: Optional[str] = None
        self.enabled = True
        self.busy_ns = 0
        self.serving_since_ns = time.monotonic_ns()
        self._reg = reg
        self._ring_cap = max(16, ring_cap)
        # (key, t0_ns, dur_ns) slots, preallocated — recent dispatches
        # become "device:<lane>" tracks in the Chrome trace export
        self._ring: List[Optional[Tuple[str, int, int]]] = \
            [None] * self._ring_cap
        self._ring_idx = 0

    def set_label(self, label: str) -> None:
        self.label = label

    def set_pool(self, pool: Optional[str]) -> None:
        """Name the lane's fleet role (swarmfleet pool map) so duty
        cycles and the roofline report group by pool."""
        self.pool = pool

    # ---------------------------------------------------------- record path

    # swarmlint: hot
    def dispatch(self, key: str, t0_ns: int, dur_ns: int) -> None:
        """Attribute one dispatch's device time to ``key`` (wall-around-
        dispatch, or an emission-ring chunk delta). Two dict/int ops on
        the variant + two on the lane + one ring slot write."""
        if not self.enabled:
            return
        v = self._reg.variant(key)
        v.invocations += 1
        v.device_ns += dur_ns
        self.busy_ns += dur_ns
        i = self._ring_idx % self._ring_cap
        self._ring[i] = (key, t0_ns, dur_ns)
        self._ring_idx += 1

    # swarmlint: hot
    def wave(self, kind: str, width: int, packed: int, padding: int,
             variant_key: Optional[str] = None) -> None:
        """One admission wave's shape into the dispatch profile (per
        wave, not per token — a handful of ops on the prefill path)."""
        if not self.enabled:
            return
        self._reg.record_wave(kind, width, packed, padding, variant_key)

    # ------------------------------------------------------------ lifecycle

    def suspend(self) -> None:
        """Stop recording (warmup: compile stalls must not count as
        device time, or the first MFU window reads 30 s of XLA compile
        as kernel work)."""
        self.enabled = False

    def resume(self) -> None:
        """Re-enable AND re-anchor the duty-cycle clock: elapsed starts
        when serving starts, not when the engine object was built."""
        self.busy_ns = 0
        self.serving_since_ns = time.monotonic_ns()
        self.enabled = profile_enabled() and self._reg.enabled

    # -------------------------------------------------------------- reading

    def duty_cycle(self, now_ns: Optional[int] = None) -> float:
        """Busy fraction since serving started, clamped to 1 (pipelined
        chunks overlap, so busy can legitimately exceed wall)."""
        now_ns = now_ns or time.monotonic_ns()
        elapsed = max(1, now_ns - self.serving_since_ns)
        return min(1.0, self.busy_ns / elapsed)

    def recent(self) -> List[Tuple[str, int, int]]:
        """Oldest-first snapshot of the dispatch ring."""
        idx = self._ring_idx
        ring = list(self._ring)
        if idx <= self._ring_cap:
            out = ring[:idx]
        else:
            cut = idx % self._ring_cap
            out = ring[cut:] + ring[:cut]
        return [r for r in out if r is not None]


# process-monotonic dump sequence (concurrent dumpers never collide)
_DUMP_SEQ = itertools.count(1)


class KernelProfiler:
    """Process-global registry: variants, lanes, dispatch shapes, the
    platform peak table — and every derived surface (report, Prometheus
    lines, Chrome device tracks, dumps)."""

    def __init__(self, enabled: Optional[bool] = None) -> None:
        self.enabled = profile_enabled() if enabled is None else enabled
        self._lock = make_lock("obs.profiler.KernelProfiler._lock")
        # swarmlint: guarded-by[self._lock]: _vars, _lanes, _waves
        self._vars: Dict[str, _Variant] = {}
        self._lanes: List[LaneProfile] = []
        # (kind, width) -> [waves, packed, padding, {variant_key: waves}]
        self._waves: Dict[Tuple[str, int], List[Any]] = {}
        # swarmlint: guarded-by[self._lock]: _vmem_est
        # variant key (or "kernel:<tag>") -> (static bytes, budget bytes)
        # — SWL903 estimates folded in by ops.layers._record_static_vmem
        self._vmem_est: Dict[str, Tuple[int, int]] = {}
        self.harvest_calls = 0
        self.platform: Optional[str] = None
        self.device_kind: str = ""
        self._ring_cap = _env_int("SWARMDB_PROFILE_RING", 1024)
        self._tiny_width = _env_int("SWARMDB_PROF_TINY_WIDTH", 8)
        # clock anchor pair (monotonic <-> epoch) for trace merging
        self._anchor_mono_ns = time.monotonic_ns()
        self._anchor_epoch = time.time()

    # ------------------------------------------------------------ wiring

    def lane(self, label: Optional[str] = None):
        """A recording handle for one engine. Flag off -> the shared
        :class:`NullLane` (type identity pinned by test)."""
        if not (self.enabled and profile_enabled()):
            return NULL_LANE
        with self._lock:
            lane = LaneProfile(self, label or f"lane{len(self._lanes)}",
                               self._ring_cap)
            self._lanes.append(lane)
        return lane

    def set_platform(self, platform: str, device_kind: str = "") -> None:
        self.platform = platform
        self.device_kind = device_kind or ""

    def set_enabled(self, enabled: bool) -> None:
        """Flip recording everywhere (bench echo A/B; mirrors
        ``SpanTracer.set_enabled``). Lanes suspended here keep their
        duty anchor — the A/B toggles are seconds apart."""
        self.enabled = bool(enabled)
        with self._lock:
            lanes = list(self._lanes)
        for lane in lanes:
            lane.enabled = bool(enabled) and profile_enabled()

    def variant(self, key: str) -> _Variant:
        # racy fast path: dict.get is GIL-atomic and a miss re-checks
        # under the lock — the record path never blocks on registration
        v = self._vars.get(key)  # swarmlint: disable=SWL301 -- lock-free read fast path; miss re-checks under the lock
        if v is None:
            with self._lock:
                v = self._vars.get(key)
                if v is None:
                    v = _Variant(key)
                    self._vars[key] = v
        return v

    # ----------------------------------------------------------- harvest

    def record_variant(self, key: str, flops: Optional[float],
                       bytes_accessed: Optional[float],
                       meta: Optional[Dict[str, Any]] = None) -> None:
        """One harvested cost-model row (warmup only: the engine lowers
        the variant and hands the numbers in; ``harvest_calls`` lets the
        zero-harvest-post-warmup test hold the line)."""
        self.harvest_calls += 1
        v = self.variant(key)
        v.flops = float(flops) if flops and flops > 0 else None
        v.bytes_accessed = (float(bytes_accessed)
                            if bytes_accessed and bytes_accessed > 0
                            else None)
        if meta:
            v.meta.update(meta)

    def record_vmem_estimate(self, key: str, est_bytes: int,
                             budget_bytes: int) -> None:
        """Static (SWL903) VMEM footprint for a variant, recorded at
        dispatch trace time. Deliberately a SIDE table, not
        ``record_variant``: that would mark the variant harvested and
        starve the real XLA cost-model harvest. ``key`` is either the
        exact variant key (``prefill.ragged[w64]``) or a
        ``kernel:<tag>`` alias matched against ``meta["kernel"]``."""
        with self._lock:
            self._vmem_est[key] = (int(est_bytes), int(budget_bytes))

    def harvested(self, key: str) -> bool:
        """Whether a variant already carries cost-model facts (lane
        groups harvest once per variant, not once per lane). A racy
        read: the worst case is one redundant harvest."""
        v = self._vars.get(key)  # swarmlint: disable=SWL301 -- racy read; worst case one redundant harvest
        return v is not None and v.flops is not None

    def record_wave(self, kind: str, width: int, packed: int, padding: int,
                    variant_key: Optional[str] = None) -> None:
        # racy fast path, same shape as variant(): miss re-checks locked
        entry = self._waves.get((kind, width))  # swarmlint: disable=SWL301 -- lock-free read fast path; miss re-checks under the lock
        if entry is None:
            with self._lock:
                entry = self._waves.setdefault((kind, width),
                                               [0, 0, 0, {}])
        entry[0] += 1
        entry[1] += packed
        entry[2] += padding
        if variant_key is not None:
            entry[3][variant_key] = entry[3].get(variant_key, 0) + 1

    # ----------------------------------------------------------- reading

    def peaks(self) -> Dict[str, float]:
        return platform_peaks(self.platform or "", self.device_kind)

    def _variant_row(self, v: _Variant, peaks: Dict[str, float],
                     vmem: Optional[Dict[str, Tuple[int, int]]] = None,
                     ) -> Dict[str, Any]:
        dev_s = v.device_ns / 1e9
        row: Dict[str, Any] = {
            "variant": v.name,
            "invocations": v.invocations,
            "device_s": round(dev_s, 6),
            "flops_per_call": v.flops,
            "bytes_per_call": v.bytes_accessed,
        }
        if v.meta:
            row["meta"] = dict(v.meta)
        if v.flops and v.invocations and dev_s > 0:
            achieved = v.flops * v.invocations / dev_s
            row["achieved_flops_per_s"] = round(achieved, 1)
            if peaks.get("peak_flops"):
                row["mfu"] = round(achieved / peaks["peak_flops"], 6)
        if v.flops and v.bytes_accessed:
            ai = v.flops / v.bytes_accessed
            row["arithmetic_intensity"] = round(ai, 3)
            ridge = peaks.get("ridge_flops_per_byte")
            if ridge:
                row["roofline"] = ("compute-bound" if ai >= ridge
                                   else "memory-bound")
        if vmem:
            est = vmem.get(v.name)
            if est is None and v.meta.get("kernel"):
                est = vmem.get("kernel:" + str(v.meta["kernel"]))
            if est is not None:
                row["vmem_est_bytes"] = est[0]
                row["vmem_budget_bytes"] = est[1]
                if est[1] > 0:
                    row["vmem_utilization"] = round(est[0] / est[1], 4)
        return row

    def variants_report(self) -> List[Dict[str, Any]]:
        """All variants, most device time first."""
        peaks = self.peaks()
        with self._lock:
            vs = list(self._vars.values())
            vmem = dict(self._vmem_est)
        rows = [self._variant_row(v, peaks, vmem) for v in vs]
        rows.sort(key=lambda r: -r["device_s"])
        return rows

    def lanes_report(self) -> List[Dict[str, Any]]:
        now_ns = time.monotonic_ns()
        with self._lock:
            lanes = list(self._lanes)
        return [{
            "lane": lane.label,
            "pool": lane.pool,
            "busy_s": round(lane.busy_ns / 1e9, 6),
            "elapsed_s": round(
                max(0, now_ns - lane.serving_since_ns) / 1e9, 3),
            "duty_cycle": round(lane.duty_cycle(now_ns), 6),
        } for lane in lanes]

    # variant-name families per fleet role: with role-typed pools these
    # partition the registry (prefill lanes only ever dispatch prefill-
    # family variants and vice versa), so per-pool MFU is exact there
    _POOL_FAMILIES = {
        "prefill": ("prefill",),
        "decode": ("decode", "resident"),
    }

    def pools_report(self) -> List[Dict[str, Any]]:
        """Per-pool rollup (swarmfleet): duty cycles aggregated over the
        pool's lanes + the pool's variant-family MFU. Empty list when no
        lane carries a pool label (colocated mode)."""
        now_ns = time.monotonic_ns()
        peaks = self.peaks()
        with self._lock:
            lanes = [l for l in self._lanes if l.pool is not None]
            vs = list(self._vars.values())
        if not lanes:
            return []
        out: List[Dict[str, Any]] = []
        for pool in sorted({l.pool for l in lanes}):
            members = [l for l in lanes if l.pool == pool]
            duties = [l.duty_cycle(now_ns) for l in members]
            row: Dict[str, Any] = {
                "pool": pool,
                "lanes": [l.label for l in members],
                "busy_s": round(sum(l.busy_ns for l in members) / 1e9, 6),
                "duty_cycle_min": round(min(duties), 6),
                "duty_cycle_mean": round(sum(duties) / len(duties), 6),
            }
            fams = self._POOL_FAMILIES.get(pool)
            if fams and peaks.get("peak_flops"):
                fam_vs = [v for v in vs
                          if v.name.startswith(fams) and v.flops]
                flops = sum(v.flops * v.invocations for v in fam_vs)
                dev_s = sum(v.device_ns for v in fam_vs) / 1e9
                if flops and dev_s > 0:
                    row["mfu"] = round(
                        flops / dev_s / peaks["peak_flops"], 6)
            out.append(row)
        return out

    def dispatch_profile(self) -> List[Dict[str, Any]]:
        """The wave-shape histogram, tiny ragged flush waves named. Each
        row joins its serving variants' invocation counts and cumulative
        device seconds, so "the w=1 flush waves cost X ms total" is one
        lookup."""
        with self._lock:
            waves = {k: (e[0], e[1], e[2], dict(e[3]))
                     for k, e in self._waves.items()}
        out: List[Dict[str, Any]] = []
        for (kind, width), (n, packed, padding, keys) in sorted(
                waves.items()):
            row: Dict[str, Any] = {
                "kind": kind, "width": width, "waves": n,
                "packed_tokens": packed, "padding_tokens": padding,
            }
            if kind == "ragged" and width <= self._tiny_width:
                row["tiny_flush"] = True
            if keys:
                dev_s = 0.0
                inv = 0
                for key in keys:
                    # read-only join against live counters (benign race)
                    v = self._vars.get(key)  # swarmlint: disable=SWL301 -- read-only snapshot join; torn read costs one stale count
                    if v is not None:
                        dev_s += v.device_ns / 1e9
                        inv += v.invocations
                row["variants"] = sorted(keys)
                row["variant_invocations"] = inv
                row["variant_device_s"] = round(dev_s, 6)
            out.append(row)
        return out

    def tiny_flush_waves(self) -> int:
        """Ragged waves at or under SWARMDB_PROF_TINY_WIDTH — the
        ROADMAP item 2 signal ("raise SWARMDB_RAGGED_MIN_WIDTH if tiny
        flush waves show up")."""
        with self._lock:
            return sum(e[0] for (kind, width), e in self._waves.items()
                       if kind == "ragged" and width <= self._tiny_width)

    def mfu(self) -> Optional[float]:
        """Aggregate harvested-FLOPs MFU: total executed FLOPs over
        total accumulated device time, vs one chip's peak. Overlapping
        lanes make device time additive across devices, so this is the
        per-device mean — conservative by construction."""
        peaks = self.peaks()
        if not peaks.get("peak_flops"):
            return None
        with self._lock:
            vs = list(self._vars.values())
        flops = sum(v.flops * v.invocations for v in vs if v.flops)
        dev_s = sum(v.device_ns for v in vs if v.flops) / 1e9
        if not flops or dev_s <= 0:
            return None
        return flops / dev_s / peaks["peak_flops"]

    def counters_snapshot(self) -> Dict[str, Any]:
        """Cumulative totals for window-delta consumers (the SLO
        sentinel): executed FLOPs, device seconds, per-lane busy ns."""
        with self._lock:
            vs = list(self._vars.values())
            lanes = list(self._lanes)
        return {
            "flops_total": sum(v.flops * v.invocations
                               for v in vs if v.flops),
            "device_s_total": sum(v.device_ns for v in vs) / 1e9,
            "lane_busy_ns": {lane.label: lane.busy_ns for lane in lanes},
            "mono_ns": time.monotonic_ns(),
        }

    def report(self) -> Dict[str, Any]:
        """The ``GET /admin/profile`` payload / dump body."""
        agg = self.mfu()
        return {
            "kind": "swarmdb.profile",
            "version": 1,
            "enabled": self.enabled and profile_enabled(),
            "platform": self.platform,
            "device_kind": self.device_kind,
            "peaks": self.peaks(),
            "harvest_calls": self.harvest_calls,
            "mfu": round(agg, 6) if agg is not None else None,
            "variants": self.variants_report(),
            "lanes": self.lanes_report(),
            "pools": self.pools_report(),
            "dispatch_profile": self.dispatch_profile(),
            "tiny_flush_waves": self.tiny_flush_waves(),
        }

    def kernel_profile(self, top: int = 8) -> Dict[str, Any]:
        """The bench-record block (per-mode, beside ``ph``): top
        device-time variants + lane duty cycles, small enough to ride a
        JSON line."""
        rows = self.variants_report()[:top]
        out = {
            "platform": self.platform,
            "mfu": (round(self.mfu(), 6)
                    if self.mfu() is not None else None),
            "variants": rows,
            "lanes": self.lanes_report(),
            "tiny_flush_waves": self.tiny_flush_waves(),
        }
        pools = self.pools_report()
        if pools:
            out["pools"] = pools
        return out

    # -------------------------------------------------------- prometheus

    def prometheus_lines(self) -> List[str]:
        """``swarmdb_mfu`` / ``swarmdb_lane_duty_cycle{lane=}`` /
        ``swarmdb_kernel_device_seconds_total{variant=}`` /
        ``swarmdb_kernel_invocations_total{variant=}`` for /metrics."""
        lines: List[str] = []
        agg = self.mfu()
        lines.append("# TYPE swarmdb_mfu gauge")
        lines.append(f"swarmdb_mfu {round(agg, 6) if agg else 0.0}")
        lines.append("# TYPE swarmdb_lane_duty_cycle gauge")
        for row in self.lanes_report():
            lbl = f'lane="{row["lane"]}"'
            if row.get("pool"):
                # fleet mode: pool idleness is a first-class label
                lbl += f',pool="{row["pool"]}"'
            lines.append(f"swarmdb_lane_duty_cycle{{{lbl}}} "
                         f"{row['duty_cycle']}")
        lines.append("# TYPE swarmdb_kernel_device_seconds_total counter")
        lines.append("# TYPE swarmdb_kernel_invocations_total counter")
        for row in self.variants_report():
            lbl = f'{{variant="{row["variant"]}"}}'
            lines.append(
                f"swarmdb_kernel_device_seconds_total{lbl} "
                f"{row['device_s']}")
            lines.append(
                f"swarmdb_kernel_invocations_total{lbl} "
                f"{row['invocations']}")
        return lines

    # ------------------------------------------------------- trace merge

    def merge_chrome_trace(self, trace: Dict[str, Any]) -> Dict[str, Any]:
        """Append per-lane device-time tracks to a Chrome trace export
        (``SpanTracer.to_chrome_trace`` output, mutated in place). The
        export's timestamps are microseconds relative to ITS anchor
        epoch (``metadata.anchor_epoch_s``); the profiler re-anchors its
        monotonic dispatch stamps through its own (mono, epoch) pair, so
        device tracks line up with the host spans they explain."""
        meta = trace.get("metadata") or {}
        anchor_epoch = meta.get("anchor_epoch_s")
        if anchor_epoch is None:
            return trace
        pid = os.getpid()
        events = trace.setdefault("traceEvents", [])
        with self._lock:
            lanes = list(self._lanes)
        n_tracks = 0
        for i, lane in enumerate(lanes):
            recent = lane.recent()
            if not recent:
                continue
            tid = 900000 + i  # device tracks, far from real thread ids
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": f"device:{lane.label}"},
            })
            n_tracks += 1
            for key, t0_ns, dur_ns in recent:
                epoch0 = (self._anchor_epoch
                          + (t0_ns - self._anchor_mono_ns) / 1e9)
                events.append({
                    "name": key, "cat": "device", "ph": "X", "pid": pid,
                    "tid": tid,
                    "ts": (epoch0 - anchor_epoch) * 1e6,
                    "dur": max(0.0, dur_ns / 1e3),
                })
        meta["device_tracks"] = n_tracks
        return trace

    # -------------------------------------------------------------- dumps

    def _dump_identity(self) -> str:
        raw = os.environ.get("SWARMDB_NODE_ID") or f"p{os.getpid()}"
        return re.sub(r"[^A-Za-z0-9_.-]", "_", raw)

    def dump_to(self, directory: str, reason: str = "on_demand") -> str:
        """Write the report under ``directory`` (atomic, collision-free
        filename) and return the path. ``profile_*.json`` files next to
        flight dumps are listed by ``obs/analyze.py`` and consumed by
        its ``--roofline`` mode."""
        os.makedirs(directory, exist_ok=True)
        payload = self.report()
        payload["dumped_at"] = time.time()
        payload["node"] = self._dump_identity()
        payload["reason"] = reason
        path = os.path.join(
            directory,
            f"profile_{self._dump_identity()}_{next(_DUMP_SEQ)}_"
            f"{reason}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path

    def auto_dump(self, reason: str,
                  directory: Optional[str] = None) -> Optional[str]:
        """Best-effort dump for failure paths (rides every flight
        auto-dump): never raises, returns the path or None."""
        directory = os.environ.get("SWARMDB_FLIGHT_DIR") or directory
        if not directory or not (self.enabled and profile_enabled()):
            return None
        try:
            return self.dump_to(directory, reason)
        except Exception:
            logger.exception("profile dump failed (%s)", reason)
            return None

    def reset(self) -> None:
        """Drop everything (tests / bench sub-run isolation). Existing
        lane handles keep recording into the registry; their stats
        re-anchor."""
        with self._lock:
            self._vars.clear()
            self._waves.clear()
            self._vmem_est.clear()
            lanes = list(self._lanes)
        for lane in lanes:
            lane.busy_ns = 0
            lane.serving_since_ns = time.monotonic_ns()
            lane._ring = [None] * lane._ring_cap
            lane._ring_idx = 0
        self.harvest_calls = 0


_PROFILER: Optional[KernelProfiler] = None
_PROFILER_LOCK = make_lock("obs.profiler._PROFILER_LOCK")


def profiler() -> KernelProfiler:
    """The process-global profiler (lazy — brokers/analyzers that never
    serve a token pay nothing)."""
    global _PROFILER
    if _PROFILER is None:
        with _PROFILER_LOCK:
            if _PROFILER is None:
                _PROFILER = KernelProfiler()
    return _PROFILER
