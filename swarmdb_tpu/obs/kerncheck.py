"""Runtime Pallas kernel sanitizer — the dynamic half of swarmkern
(ISSUE 16; static half: analysis/kernelcheck.py).

The static pass proves what it can about ``pl.pallas_call`` sites from
the AST: block bounds over the grid, output-coverage, VMEM budgets.
It deliberately goes quiet wherever an index map or a store guard is
DATA-dependent (page tables, ragged descriptors) — exactly the part a
paged-attention kernel lives on. This module owns that remainder: when
``SWARMDB_KERNCHECK=1``, the TPU-gated dispatchers in ``ops/layers.py``
/ ``ops/paged_kv.py`` come from checked factories that shadow every
concrete (non-traced) call through a **host-side grid interpreter**
over the real kernel function:

- every Ref the kernel touches is a bounds-checked numpy-backed
  stand-in (:class:`ShadowRef`): an out-of-range block or ``pl.ds``
  slice is a violation naming the offending *grid coordinate* and the
  slice, instead of the silent clamp TPU hardware performs,
- the output buffer is pre-poisoned with a canary (``CANARY``); after
  the grid completes, every row a descriptor declares live must have
  been overwritten — surviving canary is a ``short-write`` violation
  (the runtime face of SWL905),
- per grid step the interpreter diffs the output block: an element
  changed by two different outer grid rows (the init cell ``(0, .., 0)``
  exempt — the zero-fill idiom) is a ``write-race`` violation naming
  both writers (the runtime face of SWL902),
- the shadow result is compared against the dispatched result — a
  free differential check of kernel-vs-dispatch parity on the live
  descriptors; :func:`differential_ragged_prefill` /
  :func:`differential_paged_decode` run the same comparison over
  randomized descriptor soups (mixed lens, page-boundary crossings,
  empty rows, split rows) for the CI harness.

Violations are recorded once, written to attached flight recorders as
``kerncheck.violation`` instants, dumped immediately to
``kerncheck_<node>.json`` in ``SWARMDB_FLIGHT_DIR`` (a SIGKILLed chaos
victim never reaches atexit), surfaced at ``GET /admin/kerncheck``,
and exported on ``/metrics`` as ``swarmdb_kernel_violations_total`` —
the same contract as lockcheck/pagecheck.

With the flag off (default) the checked factories return the plain
dispatch functions UNTOUCHED (type identity pinned by
tests/test_kernelcheck.py) and this module is never imported on the
serving path.

The registry's mutex is a *leaf* lock: no user code runs under it.
The pallas-shim patch lock (``_PATCH_MU``) serializes shadow runs —
``pl.program_id``/``pl.num_programs``/``pl.when``/``pl.ds`` are
module attributes the kernels resolve at call time, so the interpreter
swaps them for concrete evaluators for the duration of a run.
"""

from __future__ import annotations

import atexit
import contextlib
import functools
import json
import logging
import os
import re
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("swarmdb_tpu.obs")

__all__ = ["enabled", "registry", "KernCheckRegistry", "ShadowRef",
           "CANARY", "parity_tol",
           "shadow_ragged_prefill", "shadow_paged_decode",
           "shadow_paged_write_ragged", "check_wave_descriptors",
           "differential_ragged_prefill", "differential_paged_decode",
           "checked_ragged_prefill_dispatch",
           "checked_paged_attention_dispatch",
           "checked_paged_write_ragged"]

# float canary pre-poisoning shadow outputs: exactly representable in
# bf16/f32 and far outside attention's output range (softmax-weighted
# averages of unit-scale values), so surviving canary == never written
CANARY = -16384.0

# parity tolerance between the shadow fold (fp32 online softmax) and
# the dispatched path (kernel or dense reference): both accumulate in
# fp32 but tile reductions differently; bf16 outputs round to ~1e-2
_PARITY_TOL = 2e-2

# int8 pools: the shadow dequantizes at the boundary while the kernel
# dequantizes per-tile (same values, different mult order), and every
# scale product rounds through bf16 once more — a hair looser
_PARITY_TOL_INT8 = 6e-2


def parity_tol(dtype_name: Optional[str] = None) -> float:
    """Shadow-vs-dispatch tolerance for the ACTIVE pool dtype
    (``SWARMDB_KV_DTYPE``); pass ``dtype_name`` to override."""
    if dtype_name is None:
        from ..ops.paged_kv import kv_dtype_name

        try:
            dtype_name = kv_dtype_name()
        except ValueError:
            dtype_name = "bf16"
    return _PARITY_TOL_INT8 if dtype_name == "int8" else _PARITY_TOL


def _dequant_pools(k_pages, v_pages):
    """QuantPool -> plain f32 pools (identity on plain arrays): the
    shadow interpreter runs the full-precision kernel on boundary-
    dequantized pages — the same values the quant kernel produces
    in-tile, so parity still binds the dispatched path."""
    from ..ops.paged_kv import _dequantize_pages, is_quantized

    if is_quantized(k_pages):
        k_pages = _dequantize_pages(k_pages.data, k_pages.scale)
        v_pages = _dequantize_pages(v_pages.data, v_pages.scale)
    return k_pages, v_pages


def enabled() -> bool:
    return os.environ.get("SWARMDB_KERNCHECK", "0") not in ("", "0")


def _max_shadow_width() -> int:
    """Shadow runs cost O(grid * block) host work — bound the packed
    width they chase so a production-sized wave doesn't stall serving."""
    try:
        return int(os.environ.get("SWARMDB_KERNCHECK_MAX_W", "512"))
    except ValueError:
        return 512


def _short_stack(skip: int = 3, limit: int = 5) -> List[str]:
    out = []
    for fr in reversed(traceback.extract_stack()[:-skip]):
        if fr.filename.endswith(("kerncheck.py",)):
            continue
        out.append(f"{os.path.basename(fr.filename)}:{fr.lineno} "
                   f"{fr.name}")
        if len(out) >= limit:
            break
    return out


# violation kind -> the static rule it is the runtime face of
_KIND_RULE = {
    "oob-block": "SWL901",
    "oob-ref": "SWL901",
    "write-race": "SWL902",
    "short-write": "SWL905",
}


class KernCheckRegistry:
    """Process-global kernel-sanitizer state (violations + check tallies)."""

    def __init__(self) -> None:
        # leaf lock: no user code runs under it
        self._mu = threading.Lock()
        self._violations: List[Dict[str, Any]] = []
        self._violation_keys: set = set()
        self._checks: Dict[str, int] = {}
        self._flights: List[Any] = []
        self._atexit_armed = False

    # ------------------------------------------------------------ wiring

    def attach_flight(self, recorder: Any) -> None:
        with self._mu:
            if recorder not in self._flights:
                self._flights.append(recorder)
            if not self._atexit_armed:
                self._atexit_armed = True
                atexit.register(self._atexit_dump)

    def note_check(self, check: str) -> None:
        """Tally one shadow pass (coverage evidence for the report)."""
        with self._mu:
            self._checks[check] = self._checks.get(check, 0) + 1

    # ----------------------------------------------------------- events

    def record(self, kind: str, kernel: str, message: str,
               where: Optional[Dict[str, Any]] = None) -> None:
        """Record one violation (dedup by kind/kernel/site) and emit the
        side effects (flight instants, immediate dump) OUTSIDE the
        mutex."""
        key = (kind, kernel, str(sorted((where or {}).items()))[:160])
        with self._mu:
            if key in self._violation_keys:
                return
            self._violation_keys.add(key)
            v = {
                "kind": kind,
                "rule": _KIND_RULE.get(kind),
                "kernel": kernel,
                "message": message,
                "where": dict(where or {}),
                "thread": threading.current_thread().name,
                "stack": _short_stack(),
                "detected_at": time.time(),
            }
            self._violations.append(v)
        self._emit(v)

    def _emit(self, violation: Dict[str, Any]) -> None:
        logger.warning("kerncheck: %s violation in %s: %s",
                       violation["kind"], violation["kernel"],
                       violation["message"])
        # swarmlint: disable=SWL303 -- benign racy snapshot of an append-only list: flight rings take their own locks, so iterating under _mu would re-enter
        for fl in list(self._flights):
            try:
                fl.record_event({
                    "kind": "kerncheck.violation",
                    "ts": time.time(),
                    "violation_kind": violation["kind"],
                    "kernel": violation["kernel"],
                    "rule": violation["rule"],
                })
            except Exception:
                pass
        directory = os.environ.get("SWARMDB_FLIGHT_DIR")
        if directory:
            try:
                self.dump_to(directory)
            except Exception:
                logger.exception("kerncheck dump failed")

    # ------------------------------------------------------------ reading

    def _node_identity(self) -> str:
        raw = (os.environ.get("SWARMDB_NODE_ID") or f"p{os.getpid()}")
        return re.sub(r"[^A-Za-z0-9_.-]", "_", raw)

    def violations(self) -> List[Dict[str, Any]]:
        with self._mu:
            return [dict(v) for v in self._violations]

    def report(self) -> Dict[str, Any]:
        with self._mu:
            violations = [dict(v) for v in self._violations]
            checks = dict(self._checks)
        return {
            "enabled": enabled(),
            "node": self._node_identity(),
            "checks": checks,
            "violations": violations,
            "generated_at": time.time(),
        }

    def prometheus_lines(self, prefix: str = "swarmdb_") -> List[str]:
        with self._mu:
            n = len(self._violations)
            checks = dict(self._checks)
        lines = [f"# TYPE {prefix}kernel_violations_total counter",
                 f"{prefix}kernel_violations_total {n}",
                 f"# TYPE {prefix}kernel_checks_total counter"]
        for k in sorted(checks):
            lines.append(
                f'{prefix}kernel_checks_total{{check="{k}"}} {checks[k]}')
        return lines

    def dump_to(self, directory: str) -> str:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, f"kerncheck_{self._node_identity()}.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.report(), f, indent=1)
        os.replace(tmp, path)
        return path

    def _atexit_dump(self) -> None:
        directory = os.environ.get("SWARMDB_FLIGHT_DIR")
        if not directory:
            return
        try:
            self.dump_to(directory)
        except Exception:  # pragma: no cover - shutdown best-effort
            pass

    def reset(self) -> None:
        """Tests only — forget violations, tallies, and flights."""
        with self._mu:
            self._violations.clear()
            self._violation_keys.clear()
            self._checks.clear()
            self._flights.clear()


_REGISTRY = KernCheckRegistry()


def registry() -> KernCheckRegistry:
    return _REGISTRY


# ------------------------------------------------------ shadow machinery

# serializes shadow runs: the interpreter swaps pl.program_id /
# pl.num_programs / pl.when / pl.ds for concrete evaluators while a
# kernel body executes on the host
_PATCH_MU = threading.RLock()


@contextlib.contextmanager
def _patched_pallas(state: Dict[str, Any]):
    from jax.experimental import pallas as pl

    with _PATCH_MU:
        saved = (pl.program_id, pl.num_programs, pl.when, pl.ds)

        def _program_id(i: int) -> int:
            return state["coords"][i]

        def _num_programs(i: int) -> int:
            return state["grid"][i]

        def _when(cond):
            def deco(fn):
                if bool(cond):
                    fn()
                return fn
            return deco

        def _ds(start, size):
            return slice(int(start), int(start) + int(size))

        pl.program_id = _program_id
        pl.num_programs = _num_programs
        pl.when = _when
        pl.ds = _ds
        try:
            yield
        finally:
            (pl.program_id, pl.num_programs, pl.when, pl.ds) = saved


class ShadowRef:
    """Bounds-checked numpy-backed stand-in for a pallas Ref. Every
    index (int, slice, ``pl.ds``) is validated against the block shape;
    out-of-range access records an ``oob-ref`` violation naming the
    current grid coordinate and the slice, then clamps so the shadow
    run can finish and surface everything at once."""

    def __init__(self, arr: np.ndarray, name: str, kernel: str,
                 state: Dict[str, Any]) -> None:
        self._arr = arr
        self._name = name
        self._kernel = kernel
        self._state = state

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._arr.shape

    @property
    def dtype(self):
        return self._arr.dtype

    def __jax_array__(self):
        # jnp.zeros_like(acc_ref) etc. inside kernel bodies
        import jax.numpy as jnp

        return jnp.asarray(np.asarray(self._arr))

    def _resolve(self, idx: Any) -> Tuple[Any, ...]:
        if not isinstance(idx, tuple):
            idx = (idx,)
        if any(v is Ellipsis for v in idx):
            k = next(i for i, v in enumerate(idx) if v is Ellipsis)
            fill = len(self._arr.shape) - (len(idx) - 1)
            idx = idx[:k] + (slice(None),) * fill + idx[k + 1:]
        out: List[Any] = []
        for ax, v in enumerate(idx):
            dim = self._arr.shape[ax]
            if isinstance(v, slice):
                start = 0 if v.start is None else int(v.start)
                stop = dim if v.stop is None else int(v.stop)
                if start < 0 or stop > dim:
                    self._oob(ax, f"[{start}:{stop})", dim)
                    start = max(0, min(start, dim))
                    stop = max(start, min(stop, dim))
                out.append(slice(start, stop))
            else:
                i = int(v)
                if not 0 <= i < dim:
                    self._oob(ax, str(i), dim)
                    i = max(0, min(i, dim - 1))
                out.append(i)
        return tuple(out)

    def _oob(self, axis: int, what: str, dim: int) -> None:
        coords = tuple(self._state.get("coords", ()))
        registry().record(
            "oob-ref", self._kernel,
            f"ref '{self._name}' axis {axis} index {what} outside "
            f"[0,{dim}) at grid cell {coords} — the kernel would read or "
            f"write past its block (TPU clamps silently; this is the "
            f"runtime face of SWL901)",
            {"ref": self._name, "axis": axis, "grid": list(coords),
             "slice": what})

    def __getitem__(self, idx: Any):
        import jax.numpy as jnp

        return jnp.asarray(np.asarray(self._arr[self._resolve(idx)]))

    def __setitem__(self, idx: Any, value: Any) -> None:
        s = self._resolve(idx)
        self._arr[s] = np.asarray(value, dtype=self._arr.dtype)


def _run_grid(kernel: Callable, kernel_name: str,
              grid: Tuple[int, ...],
              scalars: Sequence[Tuple[str, np.ndarray]],
              inputs: Sequence[Tuple[str, np.ndarray, Tuple[int, ...],
                                     Callable]],
              out: Tuple[str, np.ndarray, Tuple[int, ...], Callable],
              scratch: Sequence[np.ndarray]
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Interpret ``kernel`` over ``grid`` (row-major, last axis minor —
    the TPU order) against numpy backing stores with bounds-checked
    block selection, recording oob-block / oob-ref / write-race
    violations as it goes. Returns (output backing store, per-element
    last-writer map: -1 = only ever touched by the init cell)."""
    reg = registry()
    state: Dict[str, Any] = {"grid": grid, "coords": (0,) * len(grid)}
    scalar_refs = [ShadowRef(arr, name, kernel_name, state)
                   for name, arr in scalars]
    out_name, out_buf, out_bs, out_map = out
    scratch_refs = [ShadowRef(arr, f"scratch{i}", kernel_name, state)
                    for i, arr in enumerate(scratch)]
    # element-granular last-changer for the race check: -1 = untouched
    last_writer = np.full(out_buf.shape, -1, np.int64)

    def block_view(name: str, arr: np.ndarray, bs: Tuple[int, ...],
                   idx: Sequence[Any]) -> Tuple[np.ndarray,
                                                Tuple[slice, ...]]:
        slices: List[slice] = []
        for ax, (i, b) in enumerate(zip(idx, bs)):
            start = int(i) * b
            if start < 0 or start + b > arr.shape[ax]:
                reg.record(
                    "oob-block", kernel_name,
                    f"operand '{name}' block axis {ax}: index map "
                    f"selected [{start},{start + b}) outside "
                    f"[0,{arr.shape[ax]}) at grid cell "
                    f"{tuple(state['coords'])} — an out-of-bounds page "
                    f"id or block index (runtime face of SWL901)",
                    {"operand": name, "axis": ax,
                     "grid": list(state["coords"]),
                     "slice": f"[{start},{start + b})"})
                start = max(0, min(start, arr.shape[ax] - b))
            slices.append(slice(start, start + b))
        t = tuple(slices)
        return arr[t], t

    with _patched_pallas(state):
        for coords in np.ndindex(*grid):
            state["coords"] = coords
            in_refs = []
            for name, arr, bs, imap in inputs:
                idx = imap(*coords, *scalar_refs)
                view, _ = block_view(name, arr, bs, idx)
                in_refs.append(ShadowRef(view, name, kernel_name, state))
            oidx = out_map(*coords, *scalar_refs)
            oview, oslices = block_view(out_name, out_buf, out_bs, oidx)
            pre = oview.copy()
            kernel(*scalar_refs, *in_refs,
                   ShadowRef(oview, out_name, kernel_name, state),
                   *scratch_refs)
            changed = np.asarray(pre != oview)
            # the all-zero grid cell writing CONSTANT zeros is the
            # zero-fill init idiom — exempt from writer tracking so a
            # later per-row finalize is not a "race" against it and a
            # row it alone touched still counts as unwritten. An init
            # cell writing real (non-zero) values is an ordinary writer.
            is_zero_fill = (all(c == 0 for c in coords) and changed.any()
                            and not np.asarray(
                                oview, np.float32)[changed].any())
            if changed.any() and not is_zero_fill:
                writer = (int(np.ravel_multi_index(coords[:-1],
                                                   grid[:-1]))
                          if len(grid) > 1 else 0)
                lw = last_writer[oslices]
                prev = lw[changed]
                clash = (prev >= 0) & (prev != writer)
                if clash.any():
                    others = sorted(set(int(p) for p in prev[clash]))[:4]
                    reg.record(
                        "write-race", kernel_name,
                        f"grid cell {coords} changed "
                        f"{int(clash.sum())} output element(s) of "
                        f"'{out_name}' last written by outer grid "
                        f"row(s) {others} — two grid rows racing on a "
                        f"shared output block (runtime face of SWL902)",
                        {"grid": list(coords), "operand": out_name,
                         "previous_writers": others})
                lw[changed] = writer
    return out_buf, last_writer


# --------------------------------------------------- kernel shadow runs

def shadow_ragged_prefill(q, sfx_k, sfx_v, k_pages, v_pages, row_tables,
                          starts, lens, prefix_lens, *, window=None,
                          tile: int = 128,
                          kernel: Optional[Callable] = None) -> np.ndarray:
    """Shadow the ragged paged prefill kernel over concrete descriptors:
    bounds-checked refs, write-race diffing, and the canary short-write
    check against the per-row (start, len) descriptors. ``kernel``
    overrides the kernel body (the drill seeds sabotaged variants).
    Returns the shadow output [W, Hq, D]."""
    from ..ops import attention_pallas as ap

    q = np.asarray(q)
    W, Hq, D = q.shape
    k_pages = np.asarray(k_pages)
    _, ps, Hkv, _ = k_pages.shape
    row_tables = np.asarray(row_tables, np.int32)
    R, maxp = row_tables.shape
    starts = np.asarray(starts, np.int32)
    lens = np.asarray(lens, np.int32)
    plens = np.asarray(prefix_lens, np.int32)
    Tk = min(tile, W)
    n_st = -(-W // Tk)
    name = "ragged_paged_prefill_attention"
    if kernel is None:
        kernel = functools.partial(
            ap._ragged_prefill_kernel, page_size=ps, n_kv_heads=Hkv,
            n_pages=maxp, tile=Tk, window=window)

    def stream_map(r, j, table_ref, starts_ref, lens_ref, plens_ref):
        return (0, 0, 0)

    def kv_map(r, j, table_ref, starts_ref, lens_ref, plens_ref):
        import jax.numpy as jnp

        last_live = ap._last_live_page(plens_ref[r], ps)
        return (table_ref[r, jnp.minimum(j, last_live)], 0, 0, 0)

    out = np.full((W, Hq, D), CANARY, q.dtype)
    G = Hq // Hkv
    out, writers = _run_grid(
        kernel, name, (R, maxp + n_st),
        [("table", row_tables), ("starts", starts), ("lens", lens),
         ("plens", plens)],
        [("q", q, (W, Hq, D), stream_map),
         ("sfx_k", np.asarray(sfx_k), (W, Hkv, D), stream_map),
         ("sfx_v", np.asarray(sfx_v), (W, Hkv, D), stream_map),
         ("k_pages", k_pages, (1, ps, Hkv, D), kv_map),
         ("v_pages", np.asarray(v_pages), (1, ps, Hkv, D), kv_map)],
        ("o", out, (W, Hq, D), stream_map),
        [np.zeros((Hkv, W * G, D), np.float32),
         np.full((Hkv, W * G, 128), -1e30, np.float32),
         np.zeros((Hkv, W * G, 128), np.float32)])
    _coverage_rows(name, out, writers, starts, lens)
    return out


def shadow_paged_decode(q, k_pages, v_pages, page_table, lengths, *,
                        window=None,
                        kernel: Optional[Callable] = None) -> np.ndarray:
    """Shadow the ragged paged DECODE kernel (grid (B, maxp)); canary
    check: every slot's [Hq, D] output row must be overwritten."""
    from ..ops import attention_pallas as ap

    q = np.asarray(q)
    B, Hq, D = q.shape
    k_pages = np.asarray(k_pages)
    _, ps, Hkv, _ = k_pages.shape
    table = np.asarray(page_table, np.int32)
    maxp = table.shape[1]
    lengths = np.asarray(lengths, np.int32)
    name = "paged_decode_gqa_attention"
    if kernel is None:
        kernel = functools.partial(
            ap._paged_attn_kernel, page_size=ps, n_kv_heads=Hkv,
            window=window)

    def q_map(b, j, table_ref, len_ref):
        return (b, 0, 0)

    def kv_map(b, j, table_ref, len_ref):
        import jax.numpy as jnp

        last_live = ap._last_live_page(len_ref[b], ps)
        return (table_ref[b, jnp.minimum(j, last_live)], 0, 0, 0)

    out = np.full((B, Hq, D), CANARY, q.dtype)
    G = Hq // Hkv
    out, writers = _run_grid(
        kernel, name, (B, maxp),
        [("table", table), ("lengths", lengths)],
        [("q", q, (1, Hq, D), q_map),
         ("k_pages", k_pages, (1, ps, Hkv, D), kv_map),
         ("v_pages", np.asarray(v_pages), (1, ps, Hkv, D), kv_map)],
        ("o", out, (1, Hq, D), q_map),
        [np.zeros((Hkv, G, D), np.float32),
         np.full((Hkv, G, 128), -1e30, np.float32),
         np.zeros((Hkv, G, 128), np.float32)])
    _coverage_rows(name, out, writers,
                   np.arange(B, dtype=np.int32),
                   (lengths > 0).astype(np.int32))
    return out


def _coverage_rows(kernel: str, out: np.ndarray, writers: np.ndarray,
                   starts: np.ndarray, lens: np.ndarray) -> None:
    """Output-coverage check (the runtime face of SWL905). A
    descriptor-live row fails if EITHER the pre-poisoned canary survives
    in its lanes, OR no grid cell past the exempt init cell ``(0,..,0)``
    ever changed them — the zero-fill idiom wipes the canary at (0, 0),
    so surviving-canary alone cannot see a skipped finalize there."""
    canary = np.asarray(out, np.float32) == CANARY
    for r in range(len(lens)):
        if lens[r] <= 0:
            continue
        s, e = int(starts[r]), int(starts[r]) + int(lens[r])
        region = canary[s:e]
        unwritten = (writers[s:e] < 0).all()
        if region.any() or unwritten:
            why = (f"still carries the canary in "
                   f"{int(region.sum())} element(s)" if region.any()
                   else "was only ever touched by the init cell's "
                        "zero-fill")
            registry().record(
                "short-write", kernel,
                f"row {r} (stream [{s},{e})) {why} — the kernel "
                f"finished the grid without writing output this row's "
                f"descriptor declares live (runtime face of SWL905)",
                {"row": r, "start": s, "len": int(lens[r])})


# -------------------------------------------- descriptor + write shadow

def check_wave_descriptors(tok_row, tok_pos, row_tables, num_pages: int,
                           page_size: int) -> int:
    """Host-side sanity over a ragged wave's WRITE descriptors (the
    ``paged_write_ragged`` operands the engine builds): live tokens must
    target in-range, non-trash pages, and no two live tokens may land on
    the same (page, offset) cell. Returns the number of violations."""
    tok_row = np.asarray(tok_row)
    tok_pos = np.asarray(tok_pos)
    row_tables = np.asarray(row_tables)
    R, maxp = row_tables.shape
    registry().note_check("wave-descriptors")
    before = len(registry().violations())
    live = ((tok_row >= 0) & (tok_row < R)
            & (tok_pos >= 0) & (tok_pos < maxp * page_size))
    if live.any():
        rows = tok_row[live]
        cols = tok_pos[live] // page_size
        pages = row_tables[rows, cols]
        offs = tok_pos[live] % page_size
        oob = (pages < 0) | (pages >= num_pages)
        if oob.any():
            which = np.nonzero(oob)[0][:4]
            registry().record(
                "oob-block", "paged_write_ragged",
                f"live token(s) at stream offset(s) "
                f"{[int(np.nonzero(live)[0][i]) for i in which]} target "
                f"page id(s) {[int(pages[i]) for i in which]} outside "
                f"the pool [0,{num_pages}) — the scatter would write "
                f"out of bounds (runtime face of SWL901)",
                {"pages": [int(pages[i]) for i in which]})
        trash = (pages == 0) & ~oob
        if trash.any():
            which = np.nonzero(trash)[0][:4]
            registry().record(
                "oob-block", "paged_write_ragged",
                f"live token(s) target trash page 0 (stream offset(s) "
                f"{[int(np.nonzero(live)[0][i]) for i in which]}) — a "
                f"row table handed the write path an unallocated page",
                {"rows": [int(rows[i]) for i in which]})
        cell = pages.astype(np.int64) * page_size + offs
        ok = ~oob
        uniq, counts = np.unique(cell[ok], return_counts=True)
        dup = uniq[counts > 1]
        if dup.size:
            registry().record(
                "write-race", "paged_write_ragged",
                f"{int(dup.size)} (page, offset) cell(s) written by "
                f"more than one live token (first: page "
                f"{int(dup[0] // page_size)} offset "
                f"{int(dup[0] % page_size)}) — colliding descriptors "
                f"would leave the pool dependent on scatter order "
                f"(runtime face of SWL902)",
                {"cells": [int(d) for d in dup[:4]]})
    return len(registry().violations()) - before


def shadow_paged_write_ragged(k_pages, v_pages, sfx_k, sfx_v, tok_row,
                              tok_pos, row_tables) -> int:
    """Numpy replay of ``ops.paged_kv.paged_write_ragged`` semantics +
    descriptor checks; parity against the jax result is asserted by the
    checked wrapper. Returns the number of violations recorded."""
    n = check_wave_descriptors(tok_row, tok_pos, row_tables,
                               np.asarray(k_pages).shape[1],
                               np.asarray(k_pages).shape[2])
    return n


# ------------------------------------------------- differential harness

def _random_ragged_case(rng: np.random.Generator):
    """One randomized ragged-prefill scenario: mixed row lengths, page-
    boundary-crossing prefixes, empty rows, and a split row (nonzero
    prefix_len mid-page — the continuation shape a wave split leaves)."""
    import jax.numpy as jnp

    Hkv, G, D, ps, maxp = 2, 2, 8, 4, 3
    Hq = Hkv * G
    R = 4
    P = 2 + R * maxp
    lens = np.zeros(R, np.int32)
    plens = np.zeros(R, np.int32)
    live = rng.permutation(R)[: int(rng.integers(2, R + 1))]
    for r in live:
        lens[r] = int(rng.integers(1, 7))
        # mix: fresh rows, page-aligned prefixes, mid-page splits
        plens[r] = int(rng.choice([0, ps, ps + 1, 2 * ps - 1]))
        plens[r] = min(plens[r], maxp * ps - lens[r])
    starts = np.zeros(R, np.int32)
    acc = 0
    for r in range(R):
        if lens[r]:
            starts[r] = acc
            acc += int(lens[r])
    W = max(8, -(-acc // 8) * 8)
    tables = np.zeros((R, maxp), np.int32)
    free = list(range(1, P))
    rng.shuffle(free)
    for r in range(R):
        need = max(1, -(-int(plens[r] + lens[r]) // ps))
        for c in range(need):
            tables[r, c] = free.pop()
    tok_row = np.full(W, R, np.int32)
    for r in range(R):
        if lens[r]:
            tok_row[starts[r]:starts[r] + lens[r]] = r
    q = jnp.asarray(rng.standard_normal((W, Hq, D)), jnp.float32)
    sfx_k = jnp.asarray(rng.standard_normal((W, Hkv, D)), jnp.float32)
    sfx_v = jnp.asarray(rng.standard_normal((W, Hkv, D)), jnp.float32)
    k_pages = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)),
                          jnp.float32)
    v_pages = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)),
                          jnp.float32)
    return (q, sfx_k, sfx_v, k_pages, v_pages, jnp.asarray(tables),
            jnp.asarray(starts), jnp.asarray(lens), jnp.asarray(plens),
            tok_row)


def differential_ragged_prefill(seed: int = 0, rounds: int = 4,
                                tol: float = _PARITY_TOL,
                                quantized: bool = False) -> int:
    """Randomized kernel-vs-dense-reference parity over ragged
    descriptor soups; a mismatch on any live token is a ``parity``
    violation. Returns the number of mismatching rounds.
    ``quantized=True`` int8-quantizes the random pools and pits the
    quant kernel (in-tile dequant) against the quantized XLA reference
    — the two dequantize identically, so the plain tolerance holds."""
    from ..ops.attention_pallas import (
        ragged_paged_prefill_attention,
        ragged_paged_prefill_attention_quant)
    from ..ops.layers import ragged_prefill_attention_reference
    from ..ops.paged_kv import QuantPool, _quantize_pages

    rng = np.random.default_rng(seed)
    bad = 0
    for i in range(rounds):
        (q, sk, sv, kp, vp, tables, starts, lens, plens,
         tok_row) = _random_ragged_case(rng)
        import jax.numpy as jnp

        if quantized:
            registry().note_check("differential.ragged-prefill.int8")
            kq, ks = _quantize_pages(kp)
            vq, vs = _quantize_pages(vp)
            got = np.asarray(ragged_paged_prefill_attention_quant(
                q, sk, sv, kq, ks, vq, vs, tables, starts, lens, plens,
                interpret=True))
            kp, vp = QuantPool(kq, ks), QuantPool(vq, vs)
        else:
            registry().note_check("differential.ragged-prefill")
            got = np.asarray(ragged_paged_prefill_attention(
                q, sk, sv, kp, vp, tables, starts, lens, plens,
                interpret=True))

        want = np.asarray(ragged_prefill_attention_reference(
            q, sk, sv, kp, vp, tables, starts, lens, plens,
            jnp.asarray(tok_row)))
        live = np.asarray(tok_row) < tables.shape[0]
        err = float(np.max(np.abs(got[live] - want[live]))) \
            if live.any() else 0.0
        if err > tol:
            bad += 1
            registry().record(
                "parity", "ragged_paged_prefill_attention",
                f"differential round {i} (seed {seed}): kernel vs dense "
                f"reference disagree by {err:.3e} (> {tol}) on live "
                f"tokens — descriptor handling diverged",
                {"round": i, "seed": seed, "max_err": err})
    return bad


def differential_paged_decode(seed: int = 0, rounds: int = 4,
                              tol: float = _PARITY_TOL,
                              quantized: bool = False) -> int:
    """Randomized parity of the paged decode kernel against the XLA
    page-gather path (mixed lengths incl. empty slots);
    ``quantized=True`` runs the int8 kernel against the quantized
    gather path."""
    import jax.numpy as jnp

    from ..ops.attention_pallas import (paged_decode_gqa_attention,
                                        paged_decode_gqa_attention_quant)
    from ..ops.layers import gqa_attention
    from ..ops.paged_kv import (QuantPool, _quantize_pages,
                                paged_gather_kv)

    rng = np.random.default_rng(seed)
    bad = 0
    for i in range(rounds):
        B, Hkv, G, D, ps, maxp = 4, 2, 2, 8, 4, 3
        Hq = Hkv * G
        P = 1 + B * maxp
        lengths = rng.integers(0, maxp * ps + 1, B).astype(np.int32)
        table = np.zeros((B, maxp), np.int32)
        free = list(range(1, P))
        rng.shuffle(free)
        for b in range(B):
            for c in range(max(1, -(-int(lengths[b]) // ps))):
                table[b, c] = free.pop()
        q = jnp.asarray(rng.standard_normal((B, Hq, D)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)),
                         jnp.float32)
        vp = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)),
                         jnp.float32)
        if quantized:
            registry().note_check("differential.paged-decode.int8")
            kq, ks = _quantize_pages(kp)
            vq, vs = _quantize_pages(vp)
            got = np.asarray(paged_decode_gqa_attention_quant(
                q, kq, ks, vq, vs, jnp.asarray(table),
                jnp.asarray(lengths), interpret=True))
            kp, vp = QuantPool(kq, ks), QuantPool(vq, vs)
        else:
            registry().note_check("differential.paged-decode")
            got = np.asarray(paged_decode_gqa_attention(
                q, kp, vp, jnp.asarray(table), jnp.asarray(lengths),
                interpret=True))
        kg, vg = paged_gather_kv(kp, vp, jnp.asarray(table))
        want = np.asarray(gqa_attention(
            q[:, None], kg, vg,
            jnp.asarray(lengths - 1)[:, None])[:, 0])
        liveb = lengths > 0
        err = float(np.max(np.abs(got[liveb] - want[liveb]))) \
            if liveb.any() else 0.0
        if err > tol:
            bad += 1
            registry().record(
                "parity", "paged_decode_gqa_attention",
                f"differential round {i} (seed {seed}): kernel vs "
                f"gather path disagree by {err:.3e} (> {tol})",
                {"round": i, "seed": seed, "max_err": err})
    return bad


# ----------------------------------------------------- checked factories

def _any_tracer(*xs: Any) -> bool:
    import jax

    return any(isinstance(x, jax.core.Tracer) for x in xs)


def checked_ragged_prefill_dispatch(fn: Callable) -> Callable:
    """Wrap ``ops.layers.ragged_prefill_dispatch`` with the shadow
    harness. Flag off: returns ``fn`` itself (type identity)."""
    if not enabled():
        return fn

    @functools.wraps(fn)
    def wrapper(q, sfx_k, sfx_v, k_pages, v_pages, row_tables, starts,
                lens, prefix_lens, tok_row, *, window=None):
        from ..ops.paged_kv import pool_data

        out = fn(q, sfx_k, sfx_v, k_pages, v_pages, row_tables, starts,
                 lens, prefix_lens, tok_row, window=window)
        if (_any_tracer(q, pool_data(k_pages), row_tables)
                or q.shape[0] > _max_shadow_width()):
            return out
        try:
            registry().note_check("shadow.ragged-prefill")
            kp, vp = _dequant_pools(k_pages, v_pages)
            shadow = shadow_ragged_prefill(
                q, sfx_k, sfx_v, kp, vp, row_tables, starts,
                lens, prefix_lens, window=window)
            _parity("ragged_paged_prefill_attention", shadow,
                    np.asarray(out), np.asarray(starts),
                    np.asarray(lens), tol=parity_tol())
        except Exception:
            logger.exception("kerncheck ragged-prefill shadow failed")
        return out

    return wrapper


def checked_paged_attention_dispatch(fn: Callable) -> Callable:
    """Wrap ``ops.layers.paged_attention_dispatch``; flag off returns
    ``fn`` itself."""
    if not enabled():
        return fn

    @functools.wraps(fn)
    def wrapper(q, k_pages, v_pages, page_table, q_positions, *,
                window=None):
        from ..ops.paged_kv import pool_data

        out = fn(q, k_pages, v_pages, page_table, q_positions,
                 window=window)
        if (_any_tracer(q, pool_data(k_pages), page_table)
                or q.shape[0] > _max_shadow_width()):
            return out
        try:
            registry().note_check("shadow.paged-decode")
            lengths = (np.asarray(q_positions)[:, 0] + 1).astype(np.int32)
            kp, vp = _dequant_pools(k_pages, v_pages)
            shadow = shadow_paged_decode(
                np.asarray(q)[:, 0], kp, vp, page_table,
                lengths, window=window)
            B = shadow.shape[0]
            _parity("paged_decode_gqa_attention", shadow,
                    np.asarray(out)[:, 0],
                    np.arange(B, dtype=np.int32), np.ones(B, np.int32),
                    tol=parity_tol())
        except Exception:
            logger.exception("kerncheck paged-decode shadow failed")
        return out

    return wrapper


def checked_paged_write_ragged(fn: Callable) -> Callable:
    """Wrap ``ops.paged_kv.paged_write_ragged`` with descriptor checks
    + numpy scatter replay parity; flag off returns ``fn`` itself."""
    if not enabled():
        return fn

    @functools.wraps(fn)
    def wrapper(k_pages, v_pages, sfx_k, sfx_v, tok_row, tok_pos,
                row_tables):
        from ..ops.paged_kv import is_quantized, pool_data

        out = fn(k_pages, v_pages, sfx_k, sfx_v, tok_row, tok_pos,
                 row_tables)
        if _any_tracer(pool_data(k_pages), sfx_k, tok_row, row_tables):
            return out
        try:
            registry().note_check("shadow.paged-write-ragged")
            n = check_wave_descriptors(
                tok_row, tok_pos, row_tables,
                pool_data(k_pages).shape[1],
                pool_data(k_pages).shape[2])
            if n == 0:
                if is_quantized(k_pages):
                    _replay_write_parity_quant(sfx_k, tok_row, tok_pos,
                                               row_tables, out[0])
                else:
                    _replay_write_parity(k_pages, sfx_k, tok_row,
                                         tok_pos, row_tables, out[0])
        except Exception:
            logger.exception("kerncheck paged-write shadow failed")
        return out

    return wrapper


def _replay_write_parity(k_pages, sfx_k, tok_row, tok_pos, row_tables,
                         out_k) -> None:
    """Replay the ragged scatter in numpy (in stream order; collision-
    free per the descriptor check) and compare the K result."""
    kp = np.array(np.asarray(k_pages), copy=True)
    sk = np.asarray(sfx_k)
    tok_row = np.asarray(tok_row)
    tok_pos = np.asarray(tok_pos)
    tables = np.asarray(row_tables)
    R, maxp = tables.shape
    ps = kp.shape[2]
    for t in range(tok_row.shape[0]):
        r = int(np.clip(tok_row[t], 0, R - 1))
        col = int(np.clip(tok_pos[t] // ps, 0, maxp - 1))
        page = int(tables[r, col])
        dead = (tok_pos[t] >= maxp * ps or tok_row[t] < 0
                or tok_row[t] >= R)
        if dead:
            page, off = 0, 0
        else:
            off = int(tok_pos[t] % ps)
        kp[:, page, off] = sk[:, t].astype(kp.dtype)
    got = np.asarray(out_k)
    if not np.array_equal(
            np.asarray(got, np.float32), np.asarray(kp, np.float32)):
        ndiff = int(np.sum(np.asarray(got, np.float32)
                           != np.asarray(kp, np.float32)))
        registry().record(
            "parity", "paged_write_ragged",
            f"scatter result differs from the per-token replay in "
            f"{ndiff} element(s) — positional write math diverged",
            {"ndiff": ndiff})


def _replay_write_parity_quant(sfx_k, tok_row, tok_pos, row_tables,
                               out_k) -> None:
    """Positional check for the QUANTIZED ragged write: dequantize each
    live token's landing slot from the written pool and compare to the
    suffix value. The window requant is not bit-replayed — instead the
    round-to-nearest bound (half a scale step per element) pins the
    slot: a token scattered to the wrong (page, offset) misses its
    value by far more than scale/2."""
    tok_row = np.asarray(tok_row)
    tok_pos = np.asarray(tok_pos)
    tables = np.asarray(row_tables)
    R, maxp = tables.shape
    data = np.asarray(out_k.data)           # [L, P, ps, Hkv, D] int8
    scale = np.asarray(out_k.scale, np.float32)  # [L, P, Hkv]
    ps = data.shape[2]
    sk = np.asarray(sfx_k, np.float32)      # [L, W, Hkv, D]
    worst = 0.0
    for t in range(tok_row.shape[0]):
        if not (0 <= tok_row[t] < R and 0 <= tok_pos[t] < maxp * ps):
            continue                        # dead token -> trash page
        page = int(tables[int(tok_row[t]), int(tok_pos[t]) // ps])
        off = int(tok_pos[t]) % ps
        s = scale[:, page]                  # [L, Hkv]
        deq = data[:, page, off].astype(np.float32) * s[..., None]
        err = np.abs(deq - sk[:, t])
        # per-(layer, head) budget: half a quant step + fp slack
        over = err - (0.5 * s[..., None] + 1e-6)
        worst = max(worst, float(np.max(over)))
    if worst > 0.0:
        registry().record(
            "parity", "paged_write_ragged",
            f"quantized scatter: a live token's dequantized slot "
            f"misses its suffix value by {worst:.3e} beyond the "
            f"half-step rounding budget — positional write math or "
            f"scale bookkeeping diverged",
            {"max_over": worst})


def _parity(kernel: str, shadow: np.ndarray, dispatched: np.ndarray,
            starts: np.ndarray, lens: np.ndarray,
            tol: float = _PARITY_TOL) -> None:
    """Compare shadow vs dispatched output on descriptor-live rows."""
    worst = 0.0
    for r in range(len(lens)):
        if lens[r] <= 0:
            continue
        s, e = int(starts[r]), int(starts[r]) + int(lens[r])
        a = np.asarray(shadow[s:e], np.float32)
        b = np.asarray(dispatched[s:e], np.float32)
        worst = max(worst, float(np.max(np.abs(a - b))))
    if worst > tol:
        registry().record(
            "parity", kernel,
            f"shadow interpreter vs dispatched output disagree by "
            f"{worst:.3e} (> {tol}) on live rows — the dispatched path "
            f"and the kernel math diverged",
            {"max_err": worst})
