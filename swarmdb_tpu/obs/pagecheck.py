"""Runtime page sanitizer — ASan for the KV page pool (swarmpage
dynamic half, ISSUE 13).

The static pass (analysis/pagelife.py) reasons about handle *sites*;
it cannot see instances (lane A's pool vs lane B's), pages that escape
into registries, or lifetimes created by data (migration replay,
prefix eviction churn, squeeze-pool faults). This module is the other
half: when ``SWARMDB_PAGECHECK=1``, every page pool the package
allocates through the factories in ``ops/paged_kv.py`` /
``ops/prefix_cache.py`` is a thin checked subclass that maintains
**shadow state per page**:

- a state machine — ``free`` / ``owned`` (by a slot) / ``cached``
  (prefix-cache custody) / ``reserved`` (chaos withdrawal) /
  ``trash`` (page 0, never allocatable) — with pin counts overlaid;
  double-free, free-of-pinned, allocation of a live page, and
  unpin-without-pin are violations,
- an **alloc epoch** per page plus per-slot **row stamps**: when a
  slot's table row is built, the registry records each referenced
  page's epoch; the engine validates the stamps at dispatch, so a page
  freed and re-allocated between admission and dispatch (the stale-
  table race) is an ``epoch-mismatch`` violation,
- **ownership metadata** (owner slot, request id, lane, acquiring
  stack) so a referenced page owned by another conversation — the
  cross-lane aliasing a migrated ``resume_pages`` list can cause — is
  a ``stale-reference`` violation naming both owners,
- a **canary**: the engine poisons freed pages' device K/V with a
  sentinel pattern and verifies it intact on re-allocation
  (``ops.paged_kv.canary_fill/canary_check``), catching writes-after-
  free that no host-side bookkeeping can see.

Violations are recorded once, written to attached flight recorders as
``pagecheck.violation`` instants, dumped immediately to
``pagecheck_<node>.json`` in ``SWARMDB_FLIGHT_DIR`` (a SIGKILLed chaos
victim never reaches atexit — the violation is the post-mortem),
surfaced at ``GET /admin/pagecheck``, and exported on ``/metrics`` as
``swarmdb_page_violations_total`` + ``swarmdb_page_state{state=}``.

With the flag off (default) the factories return the plain allocator
classes and this module is never imported — zero overhead by
construction (type identity pinned by tests/test_pagecheck.py; the
bench echo A/B covers the full serving path).

The registry's mutex is a *leaf* lock (taken under the allocator's
lock, never the reverse; no user code runs under it), so the sanitizer
cannot introduce the lock inversions its sibling (lockcheck) hunts.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import re
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("swarmdb_tpu.obs")

__all__ = ["enabled", "registry", "PageCheckRegistry", "PoolHandle",
           "CheckedPageAllocator", "CheckedShardedPageAllocator",
           "CheckedPrefixLRU"]


def enabled() -> bool:
    return os.environ.get("SWARMDB_PAGECHECK", "0") not in ("", "0")


def _short_stack(skip: int = 3, limit: int = 5) -> List[str]:
    out = []
    for fr in reversed(traceback.extract_stack()[:-skip]):
        if fr.filename.endswith(("pagecheck.py",)):
            continue
        out.append(f"{os.path.basename(fr.filename)}:{fr.lineno} "
                   f"{fr.name}")
        if len(out) >= limit:
            break
    return out


class _Page:
    __slots__ = ("state", "epoch", "owner_slot", "owner_rid", "pins",
                 "stack", "poisoned")

    def __init__(self, state: str = "free") -> None:
        self.state = state
        self.epoch = 0
        self.owner_slot: Optional[int] = None
        self.owner_rid: Optional[str] = None
        self.pins = 0
        self.stack: List[str] = []
        self.poisoned = False


class _Pool:
    def __init__(self, pool_id: int, label: str, num_pages: int,
                 trash: Sequence[int]) -> None:
        self.pool_id = pool_id
        self.label = label
        self.num_pages = num_pages
        self.pages: Dict[int, _Page] = {
            p: _Page("trash" if p in set(trash) else "free")
            for p in range(num_pages)}
        # slot -> [(page, epoch)] recorded when the row was built
        self.row_stamps: Dict[int, List[Tuple[int, int]]] = {}
        self.owner_rids: Dict[int, str] = {}
        # conversation keys whose pages were demoted to the warm tier
        # (cleared by on_promote / on_host_drop)
        self.host_keys: set = set()
        self.lane: Optional[str] = None
        self.churn_allocated = 0
        self.churn_freed = 0

    def state_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for pg in self.pages.values():
            key = "pinned" if pg.pins > 0 else pg.state
            out[key] = out.get(key, 0) + 1
        return out


class PageCheckRegistry:
    """Process-global shadow state over every checked pool."""

    def __init__(self) -> None:
        # leaf lock (module docstring): taken under pool locks, never
        # holds one, no user code runs under it
        self._mu = threading.Lock()
        self._pools: Dict[int, _Pool] = {}
        self._next_pool = 0
        self._epoch = 0
        self._violations: List[Dict[str, Any]] = []
        self._violation_keys: set = set()
        self._flights: List[Any] = []
        self._atexit_armed = False

    # ------------------------------------------------------------ wiring

    def attach_flight(self, recorder: Any) -> None:
        with self._mu:
            if recorder not in self._flights:
                self._flights.append(recorder)

    def register_pool(self, num_pages: int, trash: Sequence[int],
                      label: Optional[str] = None) -> "PoolHandle":
        with self._mu:
            pool_id = self._next_pool
            self._next_pool += 1
            pool = _Pool(pool_id, label or f"pool{pool_id}", num_pages,
                         trash)
            self._pools[pool_id] = pool
            if not self._atexit_armed:
                self._atexit_armed = True
                atexit.register(self._atexit_dump)
        return PoolHandle(self, pool_id)

    # ----------------------------------------------------------- events
    # All on_* methods may be called under the owning allocator's lock;
    # violation side effects (flight instants, dump) run OUTSIDE _mu.

    # swarmlint: holds[self._mu]
    def _violation(self, pool: _Pool, kind: str, message: str,
                   pages: Sequence[int]) -> Optional[Dict[str, Any]]:
        """Called under ``self._mu``; dedup by (pool, kind, pages)."""
        key = (pool.pool_id, kind, tuple(sorted(pages)[:8]))
        if key in self._violation_keys:
            return None
        self._violation_keys.add(key)
        v = {
            "kind": kind,
            "pool": pool.label,
            "lane": pool.lane,
            "pages": sorted(pages)[:32],
            "message": message,
            "thread": threading.current_thread().name,
            "stack": _short_stack(),
            "detected_at": time.time(),
        }
        self._violations.append(v)
        return v

    def _emit(self, violation: Optional[Dict[str, Any]]) -> None:
        """Side effects OUTSIDE the mutex."""
        if violation is None:
            return
        logger.warning("pagecheck: %s violation in %s: %s",
                       violation["kind"], violation["pool"],
                       violation["message"])
        # swarmlint: disable=SWL303 -- benign racy snapshot of an append-only list: flight rings take their own locks, so iterating under _mu would re-enter
        for fl in list(self._flights):
            try:
                fl.record_event({
                    "kind": "pagecheck.violation",
                    "ts": time.time(),
                    "violation_kind": violation["kind"],
                    "pool": violation["pool"],
                    "pages": violation["pages"],
                })
            except Exception:
                pass
        directory = os.environ.get("SWARMDB_FLIGHT_DIR")
        if directory:
            try:
                self.dump_to(directory)
            except Exception:
                logger.exception("pagecheck dump failed")

    def on_take(self, pool_id: int, pages: Sequence[int],
                slot: int) -> None:
        """Pages handed out by the allocator free-list."""
        with self._mu:
            pool = self._pools[pool_id]
            bad = []
            self._epoch += 1
            for p in pages:
                pg = pool.pages[p]
                if pg.state != "free":
                    bad.append(p)
                pg.state = "owned"
                pg.epoch = self._epoch
                pg.owner_slot = slot
                pg.owner_rid = pool.owner_rids.get(slot)
                pg.stack = _short_stack()
            pool.churn_allocated += len(pages)
            v = None
            if bad:
                v = self._violation(
                    pool, "alloc-live-page",
                    f"allocator handed out page(s) {bad} that were not "
                    f"free — the free list and the shadow state "
                    f"disagree (double-registration or table "
                    f"corruption)", bad)
        self._emit(v)

    def on_give(self, pool_id: int, pages: Sequence[int]) -> None:
        """Pages returned to the free list."""
        with self._mu:
            pool = self._pools[pool_id]
            dbl, pinned = [], []
            for p in pages:
                pg = pool.pages.get(p)
                if pg is None:
                    continue
                if pg.state == "free":
                    dbl.append(p)
                    continue
                if pg.pins > 0:
                    pinned.append(p)
                pg.state = "free"
                pg.owner_slot = None
                pg.owner_rid = None
                pg.pins = 0
                pg.poisoned = False
            pool.churn_freed += len(pages)
            v1 = v2 = None
            if dbl:
                v1 = self._violation(
                    pool, "double-free",
                    f"page(s) {dbl} freed twice — two future "
                    f"allocations will alias the same pages", dbl)
            if pinned:
                v2 = self._violation(
                    pool, "free-pinned",
                    f"page(s) {pinned} freed while pinned — an active "
                    f"slot's attention still reads them", pinned)
        self._emit(v1)
        self._emit(v2)

    def on_reserve(self, pool_id: int, pages: Sequence[int]) -> None:
        with self._mu:
            pool = self._pools[pool_id]
            for p in pages:
                pg = pool.pages[p]
                pg.state = "reserved"
                pg.owner_slot = None

    def on_reference(self, pool_id: int, slot: int,
                     pages: Sequence[int]) -> None:
        """A row is about to REFERENCE (not own) these pages: prefix
        hits and rolling resume pages. They must be live in THIS pool
        — a freed page, a reserved page, or a page id from another
        lane's pool (cross-lane aliasing after a migration replay) all
        fail here."""
        with self._mu:
            pool = self._pools[pool_id]
            bad: List[Tuple[int, str]] = []
            demoted: List[int] = []
            for p in pages:
                pg = pool.pages.get(p)
                if pg is None:
                    bad.append((p, "not a page of this pool"))
                elif pg.state == "host_resident":
                    demoted.append(p)
                elif pg.state not in ("owned", "cached"):
                    bad.append((p, f"state={pg.state}"))
            v = v2 = None
            if bad:
                detail = ", ".join(f"{p} ({why})" for p, why in bad)
                v = self._violation(
                    pool, "stale-reference",
                    f"slot {slot} (rid="
                    f"{pool.owner_rids.get(slot)}) references dead or "
                    f"foreign page(s): {detail} — the row would alias "
                    f"pages this conversation does not own",
                    [p for p, _ in bad])
            if demoted:
                v2 = self._violation(
                    pool, "use-after-demote",
                    f"slot {slot} (rid="
                    f"{pool.owner_rids.get(slot)}) references demoted "
                    f"page(s) {demoted} — their contents left for the "
                    f"warm tier; the device copy is about to be freed "
                    f"and reallocated (promote first, or re-prefill "
                    f"cold)", demoted)
        self._emit(v)
        self._emit(v2)

    def stamp_row(self, pool_id: int, slot: int,
                  pages: Sequence[int]) -> None:
        with self._mu:
            pool = self._pools[pool_id]
            pool.row_stamps[slot] = [
                (p, pool.pages[p].epoch) for p in pages
                if p in pool.pages and pool.pages[p].state != "trash"]

    def validate_row(self, pool_id: int, slot: int) -> None:
        """Dispatch-time check: every page the slot's row was built on
        is still live at the epoch it was stamped with."""
        with self._mu:
            pool = self._pools[pool_id]
            stamps = pool.row_stamps.get(slot)
            if not stamps:
                return
            bad: List[Tuple[int, str]] = []
            for p, epoch in stamps:
                pg = pool.pages.get(p)
                if pg is None or pg.state in ("free", "reserved"):
                    bad.append((p, "freed"))
                elif pg.epoch != epoch:
                    bad.append(
                        (p, f"epoch {epoch} -> {pg.epoch} (owner slot "
                            f"{pg.owner_slot}, rid {pg.owner_rid})"))
            v = None
            if bad:
                detail = ", ".join(f"{p}: {why}" for p, why in bad)
                v = self._violation(
                    pool, "epoch-mismatch",
                    f"slot {slot} dispatching a table row whose pages "
                    f"moved under it: {detail} — the stale-table/"
                    f"reused-page race", [p for p, _ in bad])
        self._emit(v)

    def on_evict(self, pool_id: int, pages: Sequence[int]) -> None:
        """Cached entries evicted straight into a new custody (the
        dense acquire path evicts and re-hands in one step): cached ->
        free silently; other states are left for on_take to police."""
        with self._mu:
            pool = self._pools[pool_id]
            for p in pages:
                pg = pool.pages.get(p)
                if pg is not None and pg.state == "cached" \
                        and pg.pins <= 0:
                    pg.state = "free"

    def on_to_cache(self, pool_id: int, pages: Sequence[int]) -> None:
        with self._mu:
            pool = self._pools[pool_id]
            for p in pages:
                pg = pool.pages.get(p)
                if pg is not None and pg.state == "owned":
                    pg.state = "cached"
                    pg.owner_slot = None

    # -- cross-tier custody (ISSUE 19: SWL801-805 learn the spill) ------

    def on_demote(self, pool_id: int, pages: Sequence[int],
                  key: Any = None) -> None:
        """Conversation ``key``'s pages are leaving for the warm tier:
        their contents were gathered to host RAM and the device ids are
        about to return to the free list. Shadow state ``owned``/
        ``cached`` -> ``host_resident`` — a demoted page is NOT freed
        yet, and referencing it is a distinct ``use-after-demote``
        crime. Demoting a page you do not hold (free/reserved/trash) or
        demoting the same key twice without an intervening promote/drop
        are violations."""
        with self._mu:
            pool = self._pools[pool_id]
            dbl = key is not None and key in pool.host_keys
            bad, twice = [], []
            for p in pages:
                pg = pool.pages.get(p)
                if pg is None or pg.state in ("free", "reserved",
                                              "trash"):
                    bad.append(p)
                    continue
                if pg.state == "host_resident":
                    twice.append(p)
                    continue
                pg.state = "host_resident"
                pg.owner_slot = None
            if key is not None:
                pool.host_keys.add(key)
            v1 = v2 = None
            if bad:
                v1 = self._violation(
                    pool, "demote-of-free",
                    f"demotion gathered page(s) {bad} the conversation "
                    f"does not hold — the spilled payload would carry "
                    f"another owner's (or freed) pages to the warm "
                    f"tier", bad)
            if twice or dbl:
                v2 = self._violation(
                    pool, "double-demote",
                    f"key {key!r} demoted twice (pages {twice or pages}"
                    f") — two warm-tier payloads would claim the same "
                    f"conversation and the second gather reads pages "
                    f"already spilled", list(twice or pages))
        self._emit(v1)
        self._emit(v2)

    def on_promote(self, pool_id: int, pages: Sequence[int],
                   key: Any = None) -> None:
        """Warm payload re-admitted: ``pages`` are the freshly RESERVED
        device ids the payload was device_put into; they become rolling
        custody (``cached``). Promoting into pages the allocator did
        not reserve is a violation — the insert would overwrite live
        state."""
        with self._mu:
            pool = self._pools[pool_id]
            self._epoch += 1
            bad = []
            for p in pages:
                pg = pool.pages.get(p)
                if pg is None:
                    continue
                if pg.state != "reserved":
                    bad.append(p)
                pg.state = "cached"
                pg.epoch = self._epoch
                pg.owner_slot = None
            if key is not None:
                pool.host_keys.discard(key)
            v = None
            if bad:
                v = self._violation(
                    pool, "promote-unreserved",
                    f"promotion inserted into page(s) {bad} that were "
                    f"not reserved — the H2D bulk insert would "
                    f"overwrite pages another conversation owns", bad)
        self._emit(v)

    def on_host_drop(self, pool_id: int, key: Any) -> None:
        """A warm entry left the host store WITHOUT promotion (capacity
        eviction or finalize) — the conversation went cold. Clears the
        double-demote guard for the key."""
        with self._mu:
            self._pools[pool_id].host_keys.discard(key)

    def on_pin(self, pool_id: int, pages: Sequence[int]) -> None:
        with self._mu:
            pool = self._pools[pool_id]
            for p in pages:
                pg = pool.pages.get(p)
                if pg is not None:
                    pg.pins += 1

    def on_unpin(self, pool_id: int, pages: Sequence[int]) -> None:
        with self._mu:
            pool = self._pools[pool_id]
            bad = []
            for p in pages:
                pg = pool.pages.get(p)
                if pg is None:
                    continue
                if pg.pins <= 0:
                    bad.append(p)
                else:
                    pg.pins -= 1
            v = None
            if bad:
                v = self._violation(
                    pool, "unpin-unpinned",
                    f"page(s) {bad} unpinned without a matching pin — "
                    f"pin bookkeeping has drifted and evictable_count "
                    f"is wrong", bad)
        self._emit(v)

    def on_reset(self, pool_id: int) -> None:
        with self._mu:
            pool = self._pools[pool_id]
            for pg in pool.pages.values():
                if pg.state != "trash":
                    pg.state = "free"
                    pg.owner_slot = None
                    pg.owner_rid = None
                    pg.pins = 0
                    pg.poisoned = False
            pool.row_stamps.clear()
            pool.owner_rids.clear()

    def set_owner(self, pool_id: int, slot: int, rid: Optional[str],
                  lane: Optional[str] = None) -> None:
        with self._mu:
            pool = self._pools[pool_id]
            if rid is None:
                pool.owner_rids.pop(slot, None)
            else:
                pool.owner_rids[slot] = rid
            for pg in pool.pages.values():
                if pg.owner_slot == slot:
                    pg.owner_rid = rid
            if lane is not None:
                pool.lane = lane

    def set_lane(self, pool_id: int, lane: str) -> None:
        with self._mu:
            self._pools[pool_id].lane = lane

    def mark_poisoned(self, pool_id: int, pages: Sequence[int]) -> None:
        with self._mu:
            pool = self._pools[pool_id]
            for p in pages:
                pg = pool.pages.get(p)
                if pg is not None:
                    pg.poisoned = True

    def poisoned_pages(self, pool_id: int,
                       pages: Sequence[int]) -> List[int]:
        """Which of ``pages`` carry a canary the engine should verify."""
        with self._mu:
            pool = self._pools[pool_id]
            return [p for p in pages
                    if pool.pages.get(p) is not None
                    and pool.pages[p].poisoned]

    def clear_poison(self, pool_id: int, pages: Sequence[int]) -> None:
        """Verification done — the new owner is about to legitimately
        overwrite these pages."""
        with self._mu:
            pool = self._pools[pool_id]
            for p in pages:
                pg = pool.pages.get(p)
                if pg is not None:
                    pg.poisoned = False

    def canary_violation(self, pool_id: int, pages: Sequence[int],
                         detail: str = "") -> None:
        """The engine found a freed page's canary overwritten."""
        with self._mu:
            pool = self._pools[pool_id]
            v = self._violation(
                pool, "canary",
                f"freed page(s) {sorted(pages)} were WRITTEN between "
                f"free and re-allocation{': ' + detail if detail else ''}"
                f" — a write-after-free landed in the pool (stale "
                f"dispatch or table aliasing)", list(pages))
        self._emit(v)

    # ------------------------------------------------------------ reading

    def _node_identity(self) -> str:
        raw = (os.environ.get("SWARMDB_NODE_ID") or f"p{os.getpid()}")
        return re.sub(r"[^A-Za-z0-9_.-]", "_", raw)

    def violations(self) -> List[Dict[str, Any]]:
        with self._mu:
            return [dict(v) for v in self._violations]

    def report(self) -> Dict[str, Any]:
        with self._mu:
            pools = []
            for pool in self._pools.values():
                pools.append({
                    "pool": pool.label,
                    "lane": pool.lane,
                    "num_pages": pool.num_pages,
                    "states": pool.state_counts(),
                    "churn_allocated": pool.churn_allocated,
                    "churn_freed": pool.churn_freed,
                    "live_rows": len(pool.row_stamps),
                })
            violations = [dict(v) for v in self._violations]
        return {
            "enabled": enabled(),
            "node": self._node_identity(),
            "pools": pools,
            "violations": violations,
            "generated_at": time.time(),
        }

    def prometheus_lines(self, prefix: str = "swarmdb_") -> List[str]:
        with self._mu:
            counts: Dict[str, int] = {}
            per_lane: Dict[str, Tuple[int, int]] = {}
            for pool in self._pools.values():
                for k, v in pool.state_counts().items():
                    counts[k] = counts.get(k, 0) + v
                lane = pool.lane or pool.label
                a, f = per_lane.get(lane, (0, 0))
                per_lane[lane] = (a + pool.churn_allocated,
                                  f + pool.churn_freed)
            n_violations = len(self._violations)
        lines = [f"# TYPE {prefix}page_violations_total counter",
                 f"{prefix}page_violations_total {n_violations}",
                 f"# TYPE {prefix}page_state gauge"]
        for k in sorted(counts):
            lines.append(f'{prefix}page_state{{state="{k}"}} '
                         f"{counts[k]}")
        lines.append(f"# TYPE {prefix}page_churn_allocated_total counter")
        lines.append(f"# TYPE {prefix}page_churn_freed_total counter")
        for lane in sorted(per_lane):
            a, f = per_lane[lane]
            lines.append(
                f'{prefix}page_churn_allocated_total{{lane="{lane}"}} '
                f"{a}")
            lines.append(
                f'{prefix}page_churn_freed_total{{lane="{lane}"}} {f}')
        return lines

    def _write_dump(self, directory: str) -> str:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, f"pagecheck_{self._node_identity()}.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.report(), f, indent=1)
        os.replace(tmp, path)
        return path

    def dump_to(self, directory: str) -> str:
        # report() takes the mutex itself; the file write stays outside
        return self._write_dump(directory)

    def _atexit_dump(self) -> None:
        directory = os.environ.get("SWARMDB_FLIGHT_DIR")
        if not directory:
            return
        try:
            self.dump_to(directory)
        except Exception:  # pragma: no cover - shutdown best-effort
            pass

    def reset(self) -> None:
        """Tests only — forget pools, violations, and flights."""
        with self._mu:
            self._pools.clear()
            self._next_pool = 0
            self._epoch = 0
            self._violations.clear()
            self._violation_keys.clear()
            self._flights.clear()


_REGISTRY = PageCheckRegistry()


def registry() -> PageCheckRegistry:
    return _REGISTRY


class PoolHandle:
    """A checked pool's bound view of the registry (engine-facing)."""

    __slots__ = ("_reg", "pool_id")

    def __init__(self, reg: PageCheckRegistry, pool_id: int) -> None:
        self._reg = reg
        self.pool_id = pool_id

    def __getattr__(self, name: str) -> Any:
        fn = getattr(self._reg, name)

        def bound(*args: Any, **kwargs: Any) -> Any:
            return fn(self.pool_id, *args, **kwargs)

        return bound


# ------------------------------------------------------ checked classes

def _make_checked_allocator(base: type) -> type:
    """Checked subclass factory: every custody transition the base
    class performs is mirrored into the registry. ``_take``/``_give``
    are the single choke points for the free list; ``_check_prefix``
    is the base's own reference-validation hook."""

    class _Checked(base):  # type: ignore[misc, valid-type]
        def __init__(self, *args: Any, label: Optional[str] = None,
                     **kwargs: Any) -> None:
            self.pagecheck: Optional[PoolHandle] = None
            super().__init__(*args, **kwargs)
            trash = [k * self.pages_per_shard
                     for k in range(self.n_shards)] \
                if hasattr(self, "pages_per_shard") else [0]
            self.pagecheck = registry().register_pool(
                self.num_pages, trash, label=label)

        # -- free-list choke points -------------------------------------

        def _take(self, slot_id: int, n: int) -> Optional[List[int]]:
            pages = super()._take(slot_id, n)
            if pages is not None and self.pagecheck is not None:
                self.pagecheck.on_take(pages, slot_id)
            return pages

        def _give(self, page_ids: List[int]) -> None:
            if self.pagecheck is not None:
                self.pagecheck.on_give(page_ids)
            super()._give(page_ids)

        def _check_prefix(self, slot_id: int,
                          prefix_pages: List[int]) -> None:
            super()._check_prefix(slot_id, prefix_pages)
            if self.pagecheck is not None:
                self.pagecheck.on_reference(slot_id, prefix_pages)

        # -- row stamping ------------------------------------------------

        def allocate(self, slot_id: int, n: int):
            row = super().allocate(slot_id, n)
            if row is not None:
                self.pagecheck.stamp_row(slot_id,
                                         self.pages_for(slot_id))
            return row

        # swarmlint: borrows[page]: prefix_pages
        def allocate_with_prefix(self, slot_id: int,
                                 prefix_pages: List[int],
                                 n_fresh: int):
            row = super().allocate_with_prefix(slot_id, prefix_pages,
                                               n_fresh)
            if row is not None:
                self.pagecheck.stamp_row(
                    slot_id,
                    list(prefix_pages) + self.pages_for(slot_id))
            return row

        def transfer_to_cache(self, slot_id: int,
                              page_ids: List[int]) -> None:
            super().transfer_to_cache(slot_id, page_ids)
            self.pagecheck.on_to_cache(page_ids)

        def reserve(self, n: int) -> List[int]:
            taken = super().reserve(n)
            if taken:
                self.pagecheck.on_reserve(taken)
            return taken

        def reset(self) -> None:
            super().reset()
            if self.pagecheck is not None:
                self.pagecheck.on_reset()

    _Checked.__name__ = f"Checked{base.__name__}"
    _Checked.__qualname__ = _Checked.__name__
    return _Checked


def _checked_prefix_lru() -> type:
    from ..ops.prefix_cache import PrefixLRU

    class CheckedPrefixLRU(PrefixLRU):
        """Checked prefix cache. In paged mode (manage_free=False) it
        shares the engine allocator's pool shadow (pass ``pool=``); in
        dense mode it registers its own."""

        def __init__(self, num_pages: int, page_size: int,
                     manage_free: bool = True,
                     pool: Optional[Any] = None,
                     label: Optional[str] = None) -> None:
            super().__init__(num_pages, page_size,
                             manage_free=manage_free)
            shared = getattr(pool, "pagecheck", None)
            if shared is not None:
                self.pagecheck: PoolHandle = shared
                self._own_pool = False
            else:
                self.pagecheck = registry().register_pool(
                    num_pages, [0], label=label or "prefix")
                self._own_pool = True

        def pin(self, page_ids: Sequence[int]) -> None:
            super().pin(page_ids)
            self.pagecheck.on_pin(page_ids)

        def unpin(self, page_ids: Sequence[int]) -> None:
            super().unpin(page_ids)
            self.pagecheck.on_unpin(page_ids)

        def register(self, chain: bytes, tokens: Tuple[int, ...],
                     page_id: int) -> bool:
            accepted = super().register(chain, tokens, page_id)
            if accepted and self._own_pool:
                # dense mode: the page moves from caller custody into
                # the table (paged mode mirrors via transfer_to_cache)
                self.pagecheck.on_to_cache([page_id])
            return accepted

        def acquire(self, n: int) -> List[int]:
            pages = super().acquire(n)
            if pages and self._own_pool:
                self.pagecheck.on_evict(pages)  # evicted entries: cached->free
                self.pagecheck.on_take(pages, -1)
            return pages

        def release(self, page_id: int) -> None:
            super().release(page_id)
            if self._manage_free and self._own_pool:
                self.pagecheck.on_give([page_id])

        def reset(self) -> None:
            super().reset()
            if self._own_pool:
                self.pagecheck.on_reset()

    return CheckedPrefixLRU


def __getattr__(name: str) -> Any:  # lazy class construction
    if name == "CheckedPageAllocator":
        from ..ops.paged_kv import PageAllocator

        cls = _make_checked_allocator(PageAllocator)
        globals()[name] = cls
        return cls
    if name == "CheckedShardedPageAllocator":
        from ..ops.paged_kv import ShardedPageAllocator

        cls = _make_checked_allocator(ShardedPageAllocator)
        globals()[name] = cls
        return cls
    if name == "CheckedPrefixLRU":
        cls = _checked_prefix_lru()
        globals()[name] = cls
        return cls
    raise AttributeError(name)
