"""swarmmem — always-on KV/prefix memory accountant (ISSUE 17).

swarmprof (obs/profiler.py) attributes device TIME; this module is its
memory twin: it attributes device PAGES. Three ledgers, all fed by
piggybacked int/dict ops on hooks the owning structures already hold
their locks for:

- **Pool residency** (`MemPool`, one per `ops.paged_kv.PageAllocator`):
  per-page allocation stamps written inside the allocator's own
  allocate/free critical sections, read back as an occupancy
  decomposition (free / active / pinned / cached-evictable) and a page
  residency-age distribution.
- **Conversation temperature** (`ConvLedger`, fed by
  `backend/service.ServingService`): per-conversation resident pages,
  anchor-head tokens, last-touch age and touch count, classified
  hot/warm/cold at READ time by idle-age thresholds
  (``SWARMDB_MEM_HOT_S`` / ``SWARMDB_MEM_WARM_S``).
- **Reuse distances** (`ReuseSampler`, fed by
  `ops.prefix_cache.PrefixLRU.match`): SHARDS-style spatially-hashed
  sampling over prefix-chain accesses — unsampled accesses cost one
  hash + one compare; sampled ones (rate 1/``SWARMDB_MEM_SAMPLE``)
  update a bounded LRU stack whose scaled stack distances yield the
  miss-ratio curve ("hit rate at 0.25x/0.5x/1x/2x/4x capacity").

On top of the curve sit the two what-if models ROADMAP item 3 (the
tiered KV hierarchy) is designed against: a ghost-cache warm tier
(``warm hits(N) = hr(c_dev + N) - hr(c_dev)``, re-admission priced as a
modeled bulk ``device_put``) verified against brute-force LRU replay
(:func:`simulate_lru`, tests pin the sampling error under 2% absolute),
and a cold-resume cost model (re-prefill TTFT from conversation length
over swarmprof's measured prefill tokens/device-second).

``SWARMDB_MEMPROF=0`` hands every hook site a shared Null handle
(swarmprof's NullLane pattern; type identity pinned by
tests/test_memprof.py). Surfaces: ``GET /admin/mem``, ``swarmdb_mem_*``
/metrics gauges, the bench-record ``mem`` block (guarded by
bench_trend), ``obs/analyze.py --memory``, and mem snapshots riding
every flight auto-dump.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import re
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..utils.sync import make_lock

logger = logging.getLogger("swarmdb.memprof")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def memprof_enabled() -> bool:
    """One switch for the whole layer (README env catalog:
    ``SWARMDB_MEMPROF``, default ON — the accountant is an always-on
    flight instrument, like swarmprof)."""
    return os.environ.get("SWARMDB_MEMPROF", "1") != "0"


#: miss-ratio-curve capacity points, as multiples of the device pool
MEM_CURVE_POINTS: Tuple[float, ...] = (0.25, 0.5, 1.0, 2.0, 4.0)


# --------------------------------------------------------------- null handles


class NullPool:
    """Flag-off pool handle: the allocator's hook sites pay one no-op
    method call. A singleton — the SWARMDB_MEMPROF=0 type-identity test
    pins that disabled allocators share exactly this object."""

    __slots__ = ()
    enabled = False
    label = "off"

    def set_label(self, label: str) -> None:  # pragma: no cover - trivial
        pass

    def page_alloc(self, pages) -> None:
        pass

    def page_free(self, pages) -> None:
        pass

    def pool_reset(self) -> None:
        pass


NULL_POOL = NullPool()


class NullProbe:
    """Flag-off prefix-access probe (shared singleton)."""

    __slots__ = ()
    enabled = False

    def access(self, chain: bytes) -> None:
        pass


NULL_PROBE = NullProbe()


class NullConvLedger:
    """Flag-off conversation ledger (shared singleton)."""

    __slots__ = ()
    enabled = False

    def touch(self, key, tokens: int) -> None:
        pass

    def resident(self, key, pages: int) -> None:
        pass

    def anchor(self, key, tokens: int) -> None:
        pass

    def drop(self, key) -> None:
        pass


NULL_CONV = NullConvLedger()


# ------------------------------------------------------------- pool residency


class MemPool:
    """Per-allocator residency ledger. The write path runs INSIDE the
    owning PageAllocator's critical sections (the hooks are called with
    its lock held), so the dict writes need no lock of their own;
    readers snapshot with the profiler's benign-race stance."""

    __slots__ = ("label", "enabled", "ages", "alloc_events", "free_events",
                 "_stats_ref")

    def __init__(self, label: str,
                 stats: Optional[Callable[[], Dict[str, int]]] = None) -> None:
        self.label = label
        self.enabled = True
        # page id -> alloc monotonic ns (residency-age distribution)
        self.ages: Dict[int, int] = {}
        self.alloc_events = 0
        self.free_events = 0
        self._stats_ref = (weakref.WeakMethod(stats)
                           if stats is not None else None)

    def set_label(self, label: str) -> None:
        self.label = label

    # ---------------------------------------------------------- record path

    # swarmlint: hot
    def page_alloc(self, pages) -> None:
        """Stamp newly granted pages (caller: allocator, lock held).
        One clock read + one dict write per page."""
        if not self.enabled:
            return
        t = time.monotonic_ns()
        ages = self.ages
        for p in pages:
            ages[p] = t
        self.alloc_events += 1

    # swarmlint: hot
    def page_free(self, pages) -> None:
        """Clear stamps of returned pages (caller: allocator, lock
        held). One dict pop per page."""
        if not self.enabled:
            return
        ages = self.ages
        for p in pages:
            ages.pop(p, None)
        self.free_events += 1

    def pool_reset(self) -> None:
        """Pool generation bump: every stamp dies with the old ids."""
        self.ages.clear()

    # -------------------------------------------------------------- reading

    def owner_stats(self) -> Optional[Dict[str, int]]:
        """The owning allocator's live stats(), or None once it is
        collected (engines are rebuilt per bench sub-run / test)."""
        if self._stats_ref is None:
            return None
        fn = self._stats_ref()
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # owner mid-teardown — a dead pool, not an error
            return None

    def residency_ages(self, now_ns: Optional[int] = None) -> Dict[str, Any]:
        """Residency-age distribution of currently-stamped pages."""
        now_ns = now_ns or time.monotonic_ns()
        for _ in range(4):
            try:
                vals = list(self.ages.values())  # swarmlint: disable=SWL301 -- lock-free snapshot; a concurrent resize retries
                break
            except RuntimeError:
                continue
        else:
            vals = []
        if not vals:
            return {"pages": 0}
        ages = sorted((now_ns - t) / 1e9 for t in vals)
        n = len(ages)
        return {
            "pages": n,
            "p50_s": round(ages[n // 2], 3),
            "p90_s": round(ages[min(n - 1, (n * 9) // 10)], 3),
            "max_s": round(ages[-1], 3),
        }


# ------------------------------------------------------ conversation ledger


class ConvLedger:
    """Per-conversation temperature ledger. Touched once per served
    message (service thread) and once per retirement (engine thread) —
    per-message frequency, so a small lock is fine here; the per-page /
    per-access hot paths live in MemPool and ReuseSampler instead."""

    __slots__ = ("enabled", "_lock", "_convs", "_cap", "touches_total")

    def __init__(self, cap: int) -> None:
        self.enabled = True
        self._lock = make_lock("obs.memprof.ConvLedger._lock")
        # swarmlint: guarded-by[self._lock]: _convs
        # key -> [last_touch_ns, touches, resident_pages, anchor_tokens,
        #         prompt_tokens]; insertion order == LRU (size-capped)
        self._convs: "OrderedDict[Any, List[Any]]" = OrderedDict()
        self._cap = cap
        self.touches_total = 0

    def touch(self, key, tokens: int) -> None:
        """One served message for ``key`` (prompt length ``tokens``)."""
        if not self.enabled:
            return
        with self._lock:
            st = self._convs.get(key)
            if st is None:
                st = [0, 0, 0, 0, 0]
                while len(self._convs) >= self._cap:
                    self._convs.popitem(last=False)
                self._convs[key] = st
            else:
                self._convs.move_to_end(key)
            st[0] = time.monotonic_ns()
            st[1] += 1
            st[4] = tokens
            self.touches_total += 1

    def resident(self, key, pages: int) -> None:
        """The conversation's kept KV pages (rolling-KV adoption at
        retirement; 0 = state dropped)."""
        if not self.enabled:
            return
        with self._lock:
            st = self._convs.get(key)
            if st is not None:
                st[2] = pages

    def anchor(self, key, tokens: int) -> None:
        """Anchor-head capture (sink-anchored window): ``tokens`` of
        immutable head this conversation re-hits every turn."""
        if not self.enabled:
            return
        with self._lock:
            st = self._convs.get(key)
            if st is not None:
                st[3] = tokens

    def drop(self, key) -> None:
        """Conversation state evicted/finalized-dirty: pages went back
        to the pool."""
        if not self.enabled:
            return
        with self._lock:
            st = self._convs.get(key)
            if st is not None:
                st[2] = 0

    # -------------------------------------------------------------- reading

    def snapshot(self) -> List[Tuple[Any, int, int, int, int, int]]:
        with self._lock:
            return [(k, st[0], st[1], st[2], st[3], st[4])
                    for k, st in self._convs.items()]

    def report(self, hot_s: float, warm_s: float,
               top: int = 8) -> Dict[str, Any]:
        """hot/warm/cold decomposition by idle age, plus the heaviest
        resident conversations (the demote candidates item 3's spill
        logic will walk)."""
        now = time.monotonic_ns()
        rows = self.snapshot()
        counts = {"hot": 0, "warm": 0, "cold": 0}
        pages = {"hot": 0, "warm": 0, "cold": 0}
        detailed = []
        for key, last, touches, res, anchor, toks in rows:
            idle = (now - last) / 1e9
            state = ("hot" if idle < hot_s
                     else "warm" if idle < warm_s else "cold")
            counts[state] += 1
            pages[state] += res
            detailed.append({
                "conversation": "→".join(key)
                if isinstance(key, tuple) else str(key),
                "state": state,
                "idle_s": round(idle, 3),
                "touches": touches,
                "resident_pages": res,
                "anchor_tokens": anchor,
                "prompt_tokens": toks,
            })
        detailed.sort(key=lambda r: (-r["resident_pages"], r["idle_s"]))
        return {
            "tracked": len(rows),
            "touches_total": self.touches_total,
            "by_state": counts,
            "resident_pages_by_state": pages,
            "top_resident": detailed[:top],
        }

    def reset(self) -> None:
        with self._lock:
            self._convs.clear()
            self.touches_total = 0


# ----------------------------------------------------------- reuse sampling


def simulate_lru(trace: Iterable[Any], capacity: int) -> float:
    """Exact LRU hit rate of ``trace`` at ``capacity`` — the brute-force
    ghost-cache verifier the sampled curve is tested against (and the
    ``--memory`` self-check replays)."""
    od: "OrderedDict[Any, None]" = OrderedDict()
    hits = 0
    n = 0
    for key in trace:
        n += 1
        if key in od:
            od.move_to_end(key)
            hits += 1
        else:
            od[key] = None
            if len(od) > capacity:
                od.popitem(last=False)
    return hits / n if n else 0.0


class ReuseSampler:
    """SHARDS-style spatially-hashed reuse-distance sampler.

    Every access hashes its chain digest; only keys under the hash
    threshold (rate ``1/SWARMDB_MEM_SAMPLE``) enter the bounded sampled
    LRU stack. A sampled key's stack distance (distinct sampled keys
    touched since its last access) scaled by the sampling rate is an
    unbiased estimate of its full-stream reuse distance, so
    ``hit_rate(C) = |sampled reuses with scaled distance < C| /
    |sampled accesses|`` — the miss-ratio curve at any capacity from one
    pass. Spatial hashing (vs temporal) keeps the estimate unbiased
    under skew: a key is either always sampled or never."""

    __slots__ = ("enabled", "_lock", "_mod", "_thresh", "rate", "_stack",
                 "_stack_cap", "_hist", "sampled", "accesses", "cold",
                 "overflowed")

    _MOD = 1 << 24

    def __init__(self, sample_inv: int, stack_cap: int) -> None:
        self.enabled = True
        self._lock = make_lock("obs.memprof.ReuseSampler._lock")
        self._mod = self._MOD
        self._thresh = max(1, self._mod // max(1, sample_inv))
        self.rate = self._mod / self._thresh  # distance scale factor
        # swarmlint: guarded-by[self._lock]: _stack, _hist
        self._stack: "OrderedDict[bytes, None]" = OrderedDict()
        self._stack_cap = stack_cap
        self._hist: Dict[int, int] = {}  # scaled distance -> count
        self.sampled = 0
        self.cold = 0
        self.overflowed = 0
        self.accesses = 0

    # ---------------------------------------------------------- record path

    # swarmlint: hot
    def access(self, chain: bytes) -> None:
        """One prefix-chain access (caller: PrefixLRU.match, its lock
        held). Unsampled: one hash, one compare. Sampled (1/rate of
        accesses): the stack update under this sampler's own lock."""
        if not self.enabled:
            return
        self.accesses += 1
        # Fibonacci bit-mix before the threshold test: chain digests are
        # already uniform, but synthetic test traces (and any future
        # integer key source) need not be — spatial sampling is only
        # unbiased if the hash is
        h = ((int.from_bytes(chain[:8], "little")
              * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF) >> 40
        if h >= self._thresh:
            return
        with self._lock:
            self._record(chain)

    # swarmlint: holds[self._lock]
    def _record(self, chain: bytes) -> None:
        self.sampled += 1
        stack = self._stack
        if chain in stack:
            d = 0
            for k in reversed(stack):
                if k == chain:
                    break
                d += 1
            stack.move_to_end(chain)
            sd = int(d * self.rate)
            self._hist[sd] = self._hist.get(sd, 0) + 1
        else:
            self.cold += 1
            stack[chain] = None
            if len(stack) > self._stack_cap:
                stack.popitem(last=False)
                self.overflowed += 1

    # -------------------------------------------------------------- reading

    def hit_rate_at(self, capacity_pages: int) -> float:
        """Estimated LRU hit rate at ``capacity_pages`` (over ALL
        accesses, cold misses included)."""
        with self._lock:
            items = list(self._hist.items())
            sampled = self.sampled
        if not sampled:
            return 0.0
        h = sum(n for d, n in items if d < capacity_pages)
        return h / sampled

    def curve(self, device_capacity: int) -> List[Dict[str, Any]]:
        """The miss-ratio curve at the standard capacity multiples."""
        out = []
        for mult in MEM_CURVE_POINTS:
            cap = max(1, int(device_capacity * mult))
            out.append({
                "capacity_x": mult,
                "capacity_pages": cap,
                "hit_rate": round(self.hit_rate_at(cap), 4),
            })
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "accesses": self.accesses,
                "sampled": self.sampled,
                "cold": self.cold,
                "stack_overflowed": self.overflowed,
                "sample_rate": round(1.0 / self.rate, 6),
                "stack_cap": self._stack_cap,
            }

    def reset(self) -> None:
        with self._lock:
            self._stack.clear()
            self._hist.clear()
            self.sampled = 0
            self.cold = 0
            self.overflowed = 0
            self.accesses = 0


class PrefixProbe:
    """The handle PrefixLRU hook sites hold: forwards sampled accesses
    into the shared ReuseSampler and keeps the cache's stats reachable
    for the occupancy decomposition."""

    __slots__ = ("enabled", "_sampler", "_stats_ref")

    def __init__(self, sampler: ReuseSampler,
                 stats: Optional[Callable[[], Dict[str, int]]] = None
                 ) -> None:
        self.enabled = True
        self._sampler = sampler
        self._stats_ref = (weakref.WeakMethod(stats)
                           if stats is not None else None)

    # swarmlint: hot
    def access(self, chain: bytes) -> None:
        if not self.enabled:
            return
        self._sampler.access(chain)

    def owner_stats(self) -> Optional[Dict[str, int]]:
        if self._stats_ref is None:
            return None
        fn = self._stats_ref()
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            return None


# process-monotonic dump sequence (concurrent dumpers never collide)
_DUMP_SEQ = itertools.count(1)


class MemProfiler:
    """Process-global registry: pool ledgers, the conversation ledger,
    the reuse sampler — and every derived surface (report, Prometheus
    lines, bench block, dumps, the warm-tier / cold-resume models)."""

    def __init__(self, enabled: Optional[bool] = None) -> None:
        self.enabled = memprof_enabled() if enabled is None else enabled
        self._lock = make_lock("obs.memprof.MemProfiler._lock")
        # swarmlint: guarded-by[self._lock]: _pools, _probes
        self._pools: List[MemPool] = []
        self._probes: List[PrefixProbe] = []
        self.hot_s = _env_float("SWARMDB_MEM_HOT_S", 30.0)
        self.warm_s = _env_float("SWARMDB_MEM_WARM_S", 300.0)
        self.sampler = ReuseSampler(
            _env_int("SWARMDB_MEM_SAMPLE", 16),
            _env_int("SWARMDB_MEM_STACK", 65536))
        self.conversations = ConvLedger(
            _env_int("SWARMDB_MEM_CONV_CAP", 100000))
        self.sampler.enabled = self.enabled
        self.conversations.enabled = self.enabled
        # KV bytes per page (engine wiring sets this from its cache
        # buffers) — prices the warm tier's re-admission device_put
        self.page_bytes = 0
        # measured warm tier (ISSUE 19): TierManager binds its status()
        # so tier_validation() can close the loop — the what-if model
        # above vs the tier that actually shipped
        self._tier_status: Optional[Callable[[], Dict[str, Any]]] = None

    def bind_tier(self, status_fn: Callable[[], Dict[str, Any]]) -> None:
        """Register the live TierManager's ``status`` callable. Always
        bound (not flag-gated): the validation compares two cheap
        snapshots at read time, records nothing on the hot path."""
        self._tier_status = status_fn

    # ------------------------------------------------------------ wiring

    def pool(self, stats: Optional[Callable[[], Dict[str, int]]] = None,
             label: str = "pool0"):
        """A residency ledger for one PageAllocator. Flag off -> the
        shared :data:`NULL_POOL` (no registration, no cost)."""
        if not (self.enabled and memprof_enabled()):
            return NULL_POOL
        p = MemPool(label, stats)
        with self._lock:
            self._pools.append(p)
        return p

    def prefix_probe(self,
                     stats: Optional[Callable[[], Dict[str, int]]] = None):
        """An access probe for one PrefixLRU. Flag off -> the shared
        :data:`NULL_PROBE`."""
        if not (self.enabled and memprof_enabled()):
            return NULL_PROBE
        pr = PrefixProbe(self.sampler, stats)
        with self._lock:
            self._probes.append(pr)
        return pr

    def conv_ledger(self):
        """The (single) conversation ledger. Flag off -> the shared
        :data:`NULL_CONV`."""
        if not (self.enabled and memprof_enabled()):
            return NULL_CONV
        return self.conversations

    def set_page_bytes(self, n: int) -> None:
        if n > 0:
            self.page_bytes = int(n)

    def set_enabled(self, on: bool) -> None:
        """Flip recording everywhere (bench A/B overhead toggling).
        Handles handed out while the flag was OFF stay null — like
        swarmprof, a disabled build pays literally nothing."""
        self.enabled = on
        self.sampler.enabled = on
        self.conversations.enabled = on
        with self._lock:
            pools = list(self._pools)
            probes = list(self._probes)
        for p in pools:
            p.enabled = on
        for pr in probes:
            pr.enabled = on

    # ------------------------------------------------------------- reading

    def _live_pools(self) -> List[Tuple[MemPool, Dict[str, int]]]:
        with self._lock:
            pools = list(self._pools)
        out = []
        for p in pools:
            st = p.owner_stats()
            if st is not None:
                out.append((p, st))
        return out

    def _live_prefix_stats(self) -> List[Dict[str, int]]:
        with self._lock:
            probes = list(self._probes)
        out = []
        for pr in probes:
            st = pr.owner_stats()
            if st is not None:
                out.append(st)
        return out

    def occupancy(self) -> Dict[str, Any]:
        """The per-pool decomposition, derived at read time: the
        allocator knows free vs granted; the prefix caches know how
        much of the granted side is cache custody (evictable) vs
        pinned; the remainder is active slot KV."""
        pools = self._live_pools()
        prefix = self._live_prefix_stats()
        now = time.monotonic_ns()
        rows = []
        total = free = 0
        for p, st in pools:
            n = st.get("num_pages", 0)
            f = st.get("free_pages", 0)
            total += max(0, n - 1)  # page 0 (trash) is never handed out
            free += f
            rows.append({
                "pool": p.label,
                "num_pages": n,
                "free_pages": f,
                "live_slots": st.get("live_slots", 0),
                "pages_allocated_total": st.get("pages_allocated_total", 0),
                "pages_freed_total": st.get("pages_freed_total", 0),
                "residency": p.residency_ages(now),
            })
        cached = sum(st.get("cached_pages", 0) for st in prefix)
        pinned = sum(st.get("pinned_pages", 0) for st in prefix)
        evictable = max(0, cached - pinned)
        active = max(0, total - free - cached)
        return {
            "total_pages": total,
            "free": free,
            "active": active,
            "cached_evictable": evictable,
            "pinned": min(pinned, cached),
            "headroom_pages": free + evictable,
            "pools": rows,
        }

    def prefix_totals(self) -> Dict[str, int]:
        """Summed PrefixLRU counters across live caches (the
        flag-independent /metrics gauges read the caches directly;
        this sum feeds the report / sentinel window)."""
        tot = {"lookups": 0, "full_misses": 0, "hit_tokens": 0,
               "miss_tokens": 0, "cached_pages": 0, "pinned_pages": 0,
               "num_pages": 0}
        for st in self._live_prefix_stats():
            for k in tot:
                tot[k] += st.get(k, 0)
        return tot

    def device_capacity(self) -> int:
        """The capacity the curve's "1x" point means: total pool pages
        across live allocators (the HBM-resident tier)."""
        cap = sum(max(0, st.get("num_pages", 1) - 1)
                  for _, st in self._live_pools())
        if cap <= 0:
            cap = _env_int("SWARMDB_MEM_CAPACITY", 1024)
        return cap

    # ------------------------------------------------------ what-if models

    def warm_tier_model(self) -> List[Dict[str, Any]]:
        """Ghost host-RAM warm tier: for each candidate size, the extra
        hit rate over the device-only cache and the modeled re-admission
        cost (bulk ``device_put`` at ``SWARMDB_MEM_H2D_GBPS``)."""
        c_dev = self.device_capacity()
        base = self.sampler.hit_rate_at(c_dev)
        bw = _env_float("SWARMDB_MEM_H2D_GBPS", 10.0) * 1e9
        per_page_ms = (self.page_bytes / bw * 1e3
                       if self.page_bytes and bw else None)
        # warm byte price: a spilled page costs page_bytes of host RAM —
        # divided by the LIVE store's measured compress ratio when
        # SWARMDB_TIER_ZSTD is actually shipping compressed payloads
        ratio = None
        if self._tier_status is not None:
            try:
                ws = self._tier_status().get("warm_store") or {}
                ratio = ws.get("compress_ratio")
            except Exception:
                ratio = None
        page_cost = self.page_bytes
        if page_cost and ratio and ratio > 0:
            page_cost = page_cost / ratio
        out = []
        for mult in (0.5, 1.0, 2.0, 4.0):
            n = max(1, int(c_dev * mult))
            hr = self.sampler.hit_rate_at(c_dev + n)
            row = {
                "warm_pages": n,
                "warm_x": mult,
                "hit_rate": round(hr, 4),
                "extra_hit_rate": round(max(0.0, hr - base), 4),
            }
            if per_page_ms is not None:
                row["readmit_ms_per_page"] = round(per_page_ms, 4)
            if page_cost:
                row["warm_host_bytes"] = int(n * page_cost)
                if ratio:
                    row["compress_ratio"] = ratio
            out.append(row)
        return out

    def cold_resume_model(self) -> Dict[str, Any]:
        """Cold tier = re-prefill from the broker log (bit-identical by
        PR 8's replay proof). TTFT estimate = conversation tokens over
        swarmprof's measured prefill tokens per device-second."""
        rate = None
        try:
            from .profiler import profile_enabled, profiler
            if profile_enabled():
                tokens = 0
                dev_s = 0.0
                for row in profiler().dispatch_profile():
                    tokens += row.get("packed_tokens", 0)
                    dev_s += row.get("variant_device_s", 0.0)
                if tokens and dev_s > 0:
                    rate = tokens / dev_s
        except Exception:
            rate = None
        out: Dict[str, Any] = {"prefill_tokens_per_device_s": (
            round(rate, 1) if rate else None)}
        if rate:
            toks = sorted(t for _, _, _, _, _, t
                          in self.conversations.snapshot() if t)
            if toks:
                n = len(toks)
                out["resume_ttft_est_s"] = {
                    "p50": round(toks[n // 2] / rate, 4),
                    "p95": round(toks[min(n - 1, (n * 19) // 20)] / rate, 4),
                    "max": round(toks[-1] / rate, 4),
                }
        return out

    def verdict(self) -> Optional[str]:
        """The one-line sizing answer for ROADMAP item 3: the smallest
        modeled warm tier whose extra hit rate clears 1%."""
        if not self.sampler.sampled:
            return None
        c_dev = self.device_capacity()
        base = self.sampler.hit_rate_at(c_dev)
        for row in self.warm_tier_model():
            if row["extra_hit_rate"] >= 0.01:
                return (f"warm tier of {row['warm_pages']} pages "
                        f"({row['warm_x']}x device) buys "
                        f"{row['extra_hit_rate'] * 100:.1f}% hit rate "
                        f"(device-only {base * 100:.1f}%)")
        return (f"device pool already captures the working set "
                f"(hit rate {base * 100:.1f}% at 1x; no modeled warm "
                f"tier adds >=1%)")

    def tier_validation(self) -> Optional[Dict[str, Any]]:
        """Predicted vs measured warm tier (ISSUE 19 loop closure).

        The what-if model priced a ghost warm tier from sampled reuse
        distances; now a real one is running. Among arrivals that MISSED
        the device pool, the model predicts the share the warm tier
        recovers as ``extra_hit_rate / (1 - device_hit_rate)``; the
        tier manager measures the same share directly as
        ``promotions / (promotions + cold_resumes)``. Drift beyond
        ``SWARMDB_MEM_TIER_DRIFT`` (default 0.2 absolute) flags the
        model as stale — wrong sampling rate, non-stationary workload,
        or a warm store sized below what the curve assumed.
        """
        if self._tier_status is None:
            return None
        try:
            st = self._tier_status()
        except Exception:
            return None
        counters = st.get("counters", {})
        promotions = int(counters.get("promotions", 0))
        cold = int(counters.get("cold_resumes", 0))
        warm_pages = int(st.get("pages", {}).get("warm", 0))
        resumes = promotions + cold
        out: Dict[str, Any] = {
            "warm_pages": warm_pages,
            "promotions": promotions,
            "cold_resumes": cold,
            "measured_warm_share": (round(promotions / resumes, 4)
                                    if resumes else None),
            "predicted_warm_share": None,
            "drift": None,
            "drifted": False,
        }
        if self.sampler.sampled and warm_pages > 0:
            c_dev = self.device_capacity()
            base = self.sampler.hit_rate_at(c_dev)
            extra = max(0.0, self.sampler.hit_rate_at(c_dev + warm_pages)
                        - base)
            miss = max(1e-9, 1.0 - base)
            out["predicted_warm_share"] = round(min(1.0, extra / miss), 4)
        if (out["measured_warm_share"] is not None
                and out["predicted_warm_share"] is not None
                and resumes >= _env_int("SWARMDB_MEM_TIER_MIN_RESUMES", 20)):
            drift = out["measured_warm_share"] - out["predicted_warm_share"]
            out["drift"] = round(drift, 4)
            out["drifted"] = abs(drift) > _env_float(
                "SWARMDB_MEM_TIER_DRIFT", 0.2)
        return out

    # ------------------------------------------------------------- surfaces

    def counters_snapshot(self) -> Dict[str, Any]:
        """Cumulative totals for window-delta consumers (the SLO
        sentinel)."""
        pt = self.prefix_totals()
        occ = self.occupancy()
        return {
            "hit_tokens": pt["hit_tokens"],
            "miss_tokens": pt["miss_tokens"],
            "lookups": pt["lookups"],
            "full_misses": pt["full_misses"],
            "pool_total_pages": occ["total_pages"],
            "pool_headroom_pages": occ["headroom_pages"],
            "conv_touches": self.conversations.touches_total,
            "mono_ns": time.monotonic_ns(),
        }

    def report(self) -> Dict[str, Any]:
        """The ``GET /admin/mem`` payload / dump body."""
        pt = self.prefix_totals()
        denom = pt["hit_tokens"] + pt["miss_tokens"]
        c_dev = self.device_capacity()
        return {
            "kind": "swarmdb.mem",
            "version": 1,
            "enabled": self.enabled and memprof_enabled(),
            "page_bytes": self.page_bytes,
            "hot_s": self.hot_s,
            "warm_s": self.warm_s,
            "occupancy": self.occupancy(),
            "prefix": dict(pt, hit_rate=round(
                pt["hit_tokens"] / denom, 4) if denom else None),
            "conversations": self.conversations.report(
                self.hot_s, self.warm_s),
            "reuse": dict(self.sampler.stats(),
                          device_capacity_pages=c_dev,
                          curve=self.sampler.curve(c_dev)),
            "warm_tier": self.warm_tier_model(),
            "cold_resume": self.cold_resume_model(),
            "tier_validation": self.tier_validation(),
            "verdict": self.verdict(),
        }

    def mem_profile(self) -> Dict[str, Any]:
        """The bench-record block (per-mode, beside ``kernel_profile``):
        compact scalars bench_trend gates like throughput."""
        pt = self.prefix_totals()
        denom = pt["hit_tokens"] + pt["miss_tokens"]
        occ = self.occupancy()
        conv = self.conversations.report(self.hot_s, self.warm_s, top=0)
        c_dev = self.device_capacity()
        return {
            "prefix_hit_rate": (round(pt["hit_tokens"] / denom, 4)
                                if denom else None),
            "lookups": pt["lookups"],
            "full_misses": pt["full_misses"],
            "occupancy": {k: occ[k] for k in
                          ("total_pages", "free", "active",
                           "cached_evictable", "pinned",
                           "headroom_pages")},
            "conversations": conv["by_state"],
            "curve": {str(r["capacity_x"]): r["hit_rate"]
                      for r in self.sampler.curve(c_dev)},
            "sampled_accesses": self.sampler.sampled,
            "tier_validation": self.tier_validation(),
            "verdict": self.verdict(),
        }

    # -------------------------------------------------------- prometheus

    def prometheus_lines(self) -> List[str]:
        """``swarmdb_mem_*`` + ``swarmdb_conversation_temperature`` for
        /metrics (gated by memprof_enabled(); the flag-independent pool
        and prefix gauges are rendered by the API layer directly)."""
        lines: List[str] = []
        occ = self.occupancy()
        lines.append("# TYPE swarmdb_mem_pool_pages gauge")
        for state in ("free", "active", "cached_evictable", "pinned"):
            lines.append(
                f'swarmdb_mem_pool_pages{{state="{state}"}} {occ[state]}')
        lines.append("# TYPE swarmdb_mem_headroom_pages gauge")
        lines.append(f"swarmdb_mem_headroom_pages {occ['headroom_pages']}")
        conv = self.conversations.report(self.hot_s, self.warm_s, top=0)
        lines.append("# TYPE swarmdb_conversation_temperature gauge")
        for state in ("hot", "warm", "cold"):
            lines.append(
                f'swarmdb_conversation_temperature{{state="{state}"}} '
                f"{conv['by_state'][state]}")
        sst = self.sampler.stats()
        lines.append("# TYPE swarmdb_mem_sampled_accesses_total counter")
        lines.append(
            f"swarmdb_mem_sampled_accesses_total {sst['sampled']}")
        c_dev = self.device_capacity()
        lines.append("# TYPE swarmdb_mem_curve_hit_rate gauge")
        for row in self.sampler.curve(c_dev):
            lines.append(
                f'swarmdb_mem_curve_hit_rate{{capacity="'
                f'{row["capacity_x"]}x"}} {row["hit_rate"]}')
        return lines

    # -------------------------------------------------------------- dumps

    def _dump_identity(self) -> str:
        raw = os.environ.get("SWARMDB_NODE_ID") or f"p{os.getpid()}"
        return re.sub(r"[^A-Za-z0-9_.-]", "_", raw)

    def dump_to(self, directory: str, reason: str = "on_demand") -> str:
        """Write the report under ``directory`` (atomic, collision-free
        filename). ``mem_*.json`` files next to flight dumps are listed
        by ``obs/analyze.py`` and consumed by its ``--memory`` mode."""
        os.makedirs(directory, exist_ok=True)
        payload = self.report()
        payload["dumped_at"] = time.time()
        payload["node"] = self._dump_identity()
        payload["reason"] = reason
        path = os.path.join(
            directory,
            f"mem_{self._dump_identity()}_{next(_DUMP_SEQ)}_"
            f"{reason}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path

    def auto_dump(self, reason: str,
                  directory: Optional[str] = None) -> Optional[str]:
        """Best-effort dump for failure paths (rides every flight
        auto-dump): never raises, returns the path or None."""
        directory = os.environ.get("SWARMDB_FLIGHT_DIR") or directory
        if not directory or not (self.enabled and memprof_enabled()):
            return None
        try:
            return self.dump_to(directory, reason)
        except Exception:
            logger.exception("mem dump failed (%s)", reason)
            return None

    def reset(self) -> None:
        """Drop everything (tests / bench sub-run isolation). Existing
        pool handles keep recording; their stamps re-anchor."""
        self.sampler.reset()
        self.conversations.reset()
        with self._lock:
            pools = list(self._pools)
            # drop handles whose owners are gone (engines are rebuilt
            # per sub-run; dead registrations would pile up forever)
            self._pools = [p for p in pools
                           if p.owner_stats() is not None]
            self._probes = [pr for pr in self._probes
                            if pr.owner_stats() is not None]
        for p in pools:
            p.ages.clear()
            p.alloc_events = 0
            p.free_events = 0


_MEMPROF: Optional[MemProfiler] = None
_MEMPROF_LOCK = make_lock("obs.memprof._MEMPROF_LOCK")


def memprof() -> MemProfiler:
    """The process-global accountant (lazy — brokers/analyzers that
    never serve a token pay nothing)."""
    global _MEMPROF
    if _MEMPROF is None:
        with _MEMPROF_LOCK:
            if _MEMPROF is None:
                _MEMPROF = MemProfiler()
    return _MEMPROF
