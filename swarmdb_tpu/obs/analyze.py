"""Offline trace/flight analyzer (ISSUE 6 tentpole, part 3).

``bench_logs/`` has held the dpserve dp1/dp8 Chrome traces and flight
dumps since PR 2 — deposited precisely to explain the 0.22x dp8
regression (ROADMAP open item 1) — and nothing read them. This module
is the reader: it ingests span/trace exports (Chrome trace-event JSON,
as written by ``SpanTracer.to_chrome_trace`` / ``/admin/trace/export`` /
``/admin/cluster/trace``) and flight-recorder dumps, and produces a
machine-readable diagnosis::

    python -m swarmdb_tpu.obs.analyze bench_logs/dpserve_dp1_trace.json \
        bench_logs/dpserve_dp8_trace.json

With TWO traces the report is a comparison (first = base, second =
test): the per-completion engine cost is decomposed by span category
(queue wait / prefill / decode / host sync), the regression is
attributed across named contributors whose **shares sum to 1**, and the
dominant one is called out with numbers. With one trace it reports that
run's own cost decomposition. Flight dumps passed alongside contribute
the ring-only signals: per-shard occupancy imbalance, padding waste,
and host-syncs per step.

What each contributor means:

- ``admission_serialization`` — queue-wait (``engine.admit``) growth:
  requests sit admitted-nowhere while the engine loop serializes
  admission waves (the flight ring's queued-depth plateau). In an A/B
  at equal offered load and equal capacity, queue-wait GROWTH is by
  definition not capacity — it is the admission machinery.
- ``capacity_wait`` — queue wait that is just demand exceeding the
  achieved service rate (all slots busy while the queue is deep). A
  closed-loop bench always shows large absolute queue waits; only the
  fraction accrued while FREE SLOTS EXISTED is the admission path's
  fault. Split from ``admission_serialization`` using the paired
  flight dump's per-step (active, queued) evidence
  (``admission_stall_frac``) — trusted only when the dump's steps were
  sampled post-admission (``occ_at_admit`` marker, resident-path
  engines): occupancy sampled at session boundaries reads as stall no
  matter how healthy admission is. Unmarked dumps (and the online
  sentinel, which has no flight pairing) keep the old behavior —
  everything on admission_serialization.
- ``prefill_compute`` — ``engine.prefill`` span growth: each admission
  wave's prefill program costs more (sharded program overhead, padding
  waste).
- ``per_shard_imbalance`` — the decode-cost growth attributable to
  uneven ``active_by_shard`` occupancy (idle shards ride along at the
  slowest shard's pace); needs flight dumps, else 0.
- ``host_sync`` — sanctioned host<->device sync time growth.
- ``decode`` — residual decode-chunk cost growth not explained by
  imbalance.

``bench.py --analyze`` runs this after every serving mode and embeds
the diagnosis in the mode's record, so open item 1's root-cause reading
is a repeatable artifact instead of a one-off. ``--self-check`` runs
the pipeline on synthetic traces and verifies its own invariants (the
CI lint job runs it; stdlib-only, no jax).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["analyze_files", "summarize_trace", "summarize_flight",
           "diagnose", "roofline_report", "memory_report", "self_check",
           "main"]

#: span name -> cost category (everything engine-side that serializes
#: the loop; routing spans are microseconds and excluded by design)
SPAN_CATEGORIES = {
    "engine.admit": "queue_wait",
    "engine.prefill": "prefill",
    "engine.decode_chunk": "decode",
    "engine.host_sync": "host_sync",
}

#: diagnosis contributors, reported in this order; shares sum to ~1
CONTRIBUTORS = ("admission_serialization", "capacity_wait",
                "prefill_compute", "per_shard_imbalance", "host_sync",
                "decode")

_WAVE_GAP_US = 2000.0  # prefill starts closer than this = same wave


# ------------------------------------------------------------------ loading


def load_file(path: str) -> Tuple[str, Any]:
    """('trace', events) for Chrome trace JSON, ('flight', dump) for a
    flight-recorder dump, ('profile', dump) for a swarmprof dump,
    ('mem', dump) for a swarmmem dump; raises ValueError for anything
    else."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and "traceEvents" in data:
        return "trace", [e for e in data["traceEvents"]
                         if e.get("ph") == "X"]
    if isinstance(data, dict) and "steps" in data and "requests" in data:
        return "flight", data
    if isinstance(data, dict) and data.get("kind") == "swarmdb.profile":
        return "profile", data
    if isinstance(data, dict) and data.get("kind") == "swarmdb.mem":
        return "mem", data
    raise ValueError(f"{path}: not a Chrome trace export (traceEvents), "
                     "a flight dump (steps/requests), a swarmprof "
                     "profile dump (kind=swarmdb.profile), or a swarmmem "
                     "dump (kind=swarmdb.mem)")


# --------------------------------------------------------------- summaries


def summarize_trace(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-category cost decomposition of one trace export."""
    completed = sum(1 for e in events if e.get("name") == "stage.done")
    if completed == 0:
        completed = len({(e.get("args") or {}).get("rid")
                         for e in events
                         if e.get("name") == "engine.decode_chunk"})
    completed = max(1, completed)
    cost_ms: Dict[str, float] = {c: 0.0 for c in SPAN_CATEGORIES.values()}
    count: Dict[str, int] = {c: 0 for c in SPAN_CATEGORIES.values()}
    prefill_starts: List[float] = []
    t_lo, t_hi = float("inf"), float("-inf")
    for e in events:
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        t_lo = min(t_lo, ts)
        t_hi = max(t_hi, ts + dur)
        cat = SPAN_CATEGORIES.get(e.get("name", ""))
        if cat is None:
            continue
        cost_ms[cat] += dur / 1e3
        count[cat] += 1
        if e["name"] == "engine.prefill":
            prefill_starts.append(ts)
    # admission-wave detection: prefill spans that start within the gap
    # threshold are one wave (every slot in a prefill batch records the
    # same window) — many small waves with long queue waits between
    # them is the serialization signature
    prefill_starts.sort()
    waves: List[int] = []
    prev = float("-inf")
    for ts in prefill_starts:
        if not waves or ts - prev > _WAVE_GAP_US:
            waves.append(1)
        else:
            waves[-1] += 1
        prev = ts
    out: Dict[str, Any] = {
        "completed": completed,
        "wall_s": round(max(0.0, (t_hi - t_lo)) / 1e6, 3)
        if t_hi > t_lo else 0.0,
        "per_completion_ms": {
            c: round(cost_ms[c] / completed, 3) for c in cost_ms},
        "span_counts": count,
        "mean_ms": {c: round(cost_ms[c] / count[c], 3) if count[c] else 0.0
                    for c in cost_ms},
        "admission_waves": len(waves),
        "mean_wave_size": round(sum(waves) / len(waves), 2) if waves
        else 0.0,
    }
    return out


def summarize_flight(dump: Dict[str, Any]) -> Dict[str, Any]:
    """Ring-only signals a trace cannot carry: per-shard occupancy
    imbalance, padding waste, host-syncs per step, and the request-ring
    median timeline decomposition."""
    steps = dump.get("steps") or []
    reqs = dump.get("requests") or []
    imbalances: List[float] = []
    # admission-stall evidence: over steps with a non-empty queue, the
    # queue-weighted fraction of capacity sitting FREE. ~0 = the queue
    # waits because every slot is busy (capacity); ~1 = requests wait
    # while slots idle (the admission machinery is the bottleneck).
    stall_w = 0.0
    stall_total = 0.0
    stall_evidence = False
    for step in steps:
        shards = step.get("active_by_shard") or {}
        vals = [int(v) for v in shards.values()]
        if len(vals) >= 2 and sum(vals) > 0:
            mean = sum(vals) / len(vals)
            imbalances.append((max(vals) - min(vals)) / max(1.0, mean))
        queued = int(step.get("queued", 0))
        cap = int(step.get("max_batch", 0))
        if step.get("occ_at_admit"):
            # occupancy sampled right after admission (resident-path
            # engines mark their steps): the one sampling point where
            # free-while-queued really means the admission path stalled
            stall_evidence = True
        if queued > 0 and cap > 0:
            free = max(0, cap - int(step.get("active", 0)))
            stall_w += queued * (free / cap)
            stall_total += queued
    first, last = (steps[0], steps[-1]) if steps else ({}, {})

    def delta(key: str) -> int:
        return int(last.get(key, 0)) - int(first.get(key, 0))

    prompt = delta("prompt_tokens")
    padding = delta("prefill_padding_tokens")

    def med(values: List[float]) -> float:
        if not values:
            return 0.0
        values = sorted(values)
        return values[len(values) // 2]

    queue = [r["admitted_at"] - r["submitted_at"] for r in reqs
             if r.get("admitted_at") and r.get("submitted_at")]
    ttft = [r["first_token_at"] - r["submitted_at"] for r in reqs
            if r.get("first_token_at") and r.get("submitted_at")]
    # which attention paths served these steps (ISSUE 11): a regression
    # whose base and test dumps disagree here is a PATH change
    # (pallas<->gather, ragged<->bucketed), not a perf drift of one path
    kernels = sorted({s["decode_kernel"] for s in steps
                      if s.get("decode_kernel")})
    wave_kinds = sorted({s["wave_kind"] for s in steps
                         if s.get("wave_kind")})
    # leadership churn (ISSUE 14): ha.repin instants in the event ring
    # tie a TTFT spike to conversations whose lane pin moved with a
    # leadership change (drain handover / failover) — a dump whose
    # p50_ttft regressed WITH repins in-window is churn, not engine drift
    events = dump.get("events") or []
    repins = sum(1 for e in events if e.get("kind") == "ha.repin")
    promotions = sum(1 for e in events
                     if e.get("kind") == "ha.partition_promoted")
    return {
        "steps": len(steps),
        "requests": len(reqs),
        "shard_imbalance": round(med(imbalances), 4) if imbalances else 0.0,
        "shards": len((steps[0].get("active_by_shard") or {})) if steps
        else 0,
        "decode_kernels": kernels,
        "wave_kinds": wave_kinds,
        "padding_ratio": round(padding / prompt, 4) if prompt > 0 else 0.0,
        "admission_stall_frac": round(stall_w / stall_total, 4)
        if stall_total > 0 else 0.0,
        "stall_evidence": stall_evidence,
        "host_syncs_per_step": round(
            delta("host_syncs") / max(1, len(steps) - 1), 3),
        "p50_queue_wait_s": round(med(queue), 4),
        "p50_ttft_s": round(med(ttft), 4),
        "leadership_repins": repins,
        "partition_promotions": promotions,
        "meta": dump.get("meta", {}),
    }


# --------------------------------------------------------------- diagnosis


def _attribute(base: Dict[str, Any], test: Dict[str, Any],
               base_flight: Optional[Dict[str, Any]],
               test_flight: Optional[Dict[str, Any]]) -> Dict[str, float]:
    """Per-completion cost growth (ms), attributed per contributor."""
    b = base["per_completion_ms"]
    t = test["per_completion_ms"]
    decode_delta = max(0.0, t["decode"] - b["decode"])
    # imbalance-attributable decode growth: idle shards pace at the
    # slowest shard, so the imbalance index bounds the decode fraction
    # it can explain
    imb = (test_flight or {}).get("shard_imbalance", 0.0)
    imbalance_ms = min(decode_delta, decode_delta * min(1.0, float(imb)))
    # queue-wait growth: serialization by default (equal offered load,
    # equal slots — growth is the machinery), UNLESS the test dump
    # carries post-admission occupancy evidence saying the slots were
    # in fact busy whenever the queue was non-empty, in which case the
    # wait is demand exceeding the run's achieved service rate
    # (capacity_wait — e.g. lanes sharing a starved host core)
    queue_growth = max(0.0, t["queue_wait"] - b["queue_wait"])
    admit_ms, cap_ms = _queue_split(queue_growth, test_flight)
    return {
        "admission_serialization": admit_ms,
        "capacity_wait": cap_ms,
        "prefill_compute": max(0.0, t["prefill"] - b["prefill"]),
        "per_shard_imbalance": imbalance_ms,
        "host_sync": max(0.0, t["host_sync"] - b["host_sync"]),
        "decode": decode_delta - imbalance_ms,
    }


def _queue_split(queue_ms: float,
                 flight: Optional[Dict[str, Any]]) -> Tuple[float, float]:
    """(admission_ms, capacity_ms) of a queue-wait quantity. The split
    is trusted ONLY when the dump's steps were sampled post-admission
    (``stall_evidence`` — resident-path engines mark their step
    records): occupancy sampled anywhere else reads transient session
    boundaries as stall. Without that evidence every ms stays on
    admission_serialization — the pre-split behavior, which the online
    sentinel (no flight pairing) and all pre-round-7 dumps keep."""
    if (flight is None or not flight.get("stall_evidence")
            or "admission_stall_frac" not in flight):
        return queue_ms, 0.0
    frac = min(1.0, max(0.0, float(flight["admission_stall_frac"])))
    return queue_ms * frac, queue_ms * (1.0 - frac)


def diagnose(base: Dict[str, Any], test: Dict[str, Any],
             base_flight: Optional[Dict[str, Any]] = None,
             test_flight: Optional[Dict[str, Any]] = None
             ) -> Dict[str, Any]:
    """Name the dominant contributor to test-vs-base slowdown, with
    shares that sum to ~1."""
    deltas = _attribute(base, test, base_flight, test_flight)
    total = sum(deltas.values())
    regressed = total > 0.0
    if regressed:
        shares = {c: deltas[c] / total for c in CONTRIBUTORS}
    else:
        # no regression: shares describe the TEST run's own cost mix so
        # the report stays schema-stable (and still sums to 1). The
        # queue wait splits into admission-machinery stall vs plain
        # capacity wait using the flight rings' occupancy evidence.
        t = test["per_completion_ms"]
        admit_ms, cap_ms = _queue_split(t["queue_wait"], test_flight)
        mix = {
            "admission_serialization": admit_ms,
            "capacity_wait": cap_ms,
            "prefill_compute": t["prefill"],
            "per_shard_imbalance": 0.0,
            "host_sync": t["host_sync"],
            "decode": t["decode"],
        }
        mix_total = sum(mix.values()) or 1.0
        shares = {c: mix[c] / mix_total for c in CONTRIBUTORS}
    dominant = max(CONTRIBUTORS, key=lambda c: shares[c])
    b_cost = sum(base["per_completion_ms"].values())
    t_cost = sum(test["per_completion_ms"].values())
    slowdown = round(t_cost / b_cost, 2) if b_cost > 0 else None
    explanation = (
        f"per-completion engine cost {b_cost:.0f}ms -> {t_cost:.0f}ms "
        f"({slowdown}x); dominant contributor: {dominant} "
        f"({shares[dominant]:.0%} of the growth). "
        f"queue_wait {base['per_completion_ms']['queue_wait']:.0f}ms -> "
        f"{test['per_completion_ms']['queue_wait']:.0f}ms, "
        f"prefill mean {base['mean_ms']['prefill']:.1f}ms -> "
        f"{test['mean_ms']['prefill']:.1f}ms over "
        f"{test['admission_waves']} admission waves "
        f"(mean {test['mean_wave_size']:.1f} requests/wave)."
        if regressed else
        f"no per-completion regression ({b_cost:.0f}ms -> {t_cost:.0f}ms); "
        f"shares describe the test run's own cost mix.")
    return {
        "regressed": regressed,
        "dominant": dominant,
        "shares": {c: round(shares[c], 4) for c in CONTRIBUTORS},
        "slowdown_x": slowdown,
        "delta_per_completion_ms": {c: round(deltas[c], 2)
                                    for c in CONTRIBUTORS},
        "explanation": explanation,
    }


def _solo_diagnosis(summary: Dict[str, Any],
                    flight: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """One-run report (bench --analyze embeds this): where did this
    run's per-completion engine time go?"""
    t = summary["per_completion_ms"]
    imb = (flight or {}).get("shard_imbalance", 0.0)
    imbalance_ms = t["decode"] * min(1.0, float(imb))
    admit_ms, cap_ms = _queue_split(t["queue_wait"], flight)
    mix = {
        "admission_serialization": admit_ms,
        "capacity_wait": cap_ms,
        "prefill_compute": t["prefill"],
        "per_shard_imbalance": imbalance_ms,
        "host_sync": t["host_sync"],
        "decode": t["decode"] - imbalance_ms,
    }
    total = sum(mix.values()) or 1.0
    shares = {c: round(mix[c] / total, 4) for c in CONTRIBUTORS}
    dominant = max(CONTRIBUTORS, key=lambda c: shares[c])
    return {
        "regressed": None,
        "dominant": dominant,
        "shares": shares,
        "slowdown_x": None,
        "delta_per_completion_ms": None,
        "explanation": (
            f"per-completion engine cost {total:.0f}ms; largest share: "
            f"{dominant} ({shares[dominant]:.0%})."),
    }


# ------------------------------------------------------------------ driver


def analyze_files(paths: Sequence[str]) -> Dict[str, Any]:
    """Analyze trace/flight files. Two traces -> comparison diagnosis
    (first is the base); one trace -> solo cost decomposition. Flight
    dumps pair with the traces in the order given."""
    traces: List[Tuple[str, Dict[str, Any]]] = []
    flights: List[Tuple[str, Dict[str, Any]]] = []
    profiles: List[Tuple[str, Dict[str, Any]]] = []
    mems: List[Tuple[str, Dict[str, Any]]] = []
    inputs = []
    for path in paths:
        kind, data = load_file(path)
        inputs.append({"path": path, "kind": kind})
        if kind == "trace":
            traces.append((path, summarize_trace(data)))
        elif kind == "profile":
            profiles.append((path, data))
        elif kind == "mem":
            mems.append((path, data))
        else:
            flights.append((path, summarize_flight(data)))
    if not traces:
        raise ValueError("need at least one Chrome trace export "
                         "(use --roofline for profile dumps alone, "
                         "--memory for swarmmem dumps alone)")
    report: Dict[str, Any] = {
        "kind": "swarmdb.obs.analyze",
        "version": 1,
        "inputs": inputs,
    }
    lockchecks = _lockcheck_dumps(paths)
    if lockchecks:
        report["lockcheck_dumps"] = lockchecks
    pagechecks = _pagecheck_dumps(paths)
    if pagechecks:
        report["pagecheck_dumps"] = pagechecks
    kernchecks = _kerncheck_dumps(paths)
    if kernchecks:
        report["kerncheck_dumps"] = kernchecks
    profile_list = ([_profile_summary(p, d) for p, d in profiles]
                    + _profile_dumps(paths))
    if profile_list:
        report["profile_dumps"] = profile_list
    mem_list = ([_mem_summary(p, d) for p, d in mems]
                + _mem_dumps(paths))
    if mem_list:
        report["mem_dumps"] = mem_list
    base_flight = flights[0][1] if flights else None
    test_flight = flights[-1][1] if flights else None
    if len(traces) >= 2:
        base, test = traces[0][1], traces[1][1]
        report["base"] = {"path": traces[0][0], **base,
                          "flight": base_flight}
        report["test"] = {"path": traces[1][0], **test,
                          "flight": test_flight}
        report["diagnosis"] = diagnose(base, test, base_flight,
                                       test_flight)
    else:
        summary = traces[0][1]
        report["summary"] = {"path": traces[0][0], **summary,
                             "flight": test_flight}
        report["diagnosis"] = _solo_diagnosis(summary, test_flight)
    return report


def _lockcheck_dumps(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """Lock-sanitizer dumps (``lockcheck_<node>.json``, ISSUE 12)
    sitting next to the analyzed flight/trace files: the flight dump
    says what the node was doing, the lockcheck dump says which lock
    orders it exercised doing it — an inversion cycle here IS the
    diagnosis. Listed with their cycle counts so a report reader never
    has to know the files exist to notice a detected deadlock order."""
    seen: set = set()
    out: List[Dict[str, Any]] = []
    for p in paths:
        d = os.path.dirname(os.path.abspath(p))
        if d in seen:
            continue
        seen.add(d)
        for cand in sorted(glob.glob(os.path.join(d,
                                                  "lockcheck_*.json"))):
            try:
                with open(cand, "r", encoding="utf-8") as f:
                    dump = json.load(f)
            except (OSError, ValueError):
                continue
            cycles = dump.get("cycles") or []
            out.append({
                "path": cand,
                "node": dump.get("node"),
                "cycles": len(cycles),
                "cycle_sites": [c.get("sites") for c in cycles],
                "sites_tracked": len(dump.get("sites") or {}),
            })
    return out


def _pagecheck_dumps(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """Page-sanitizer dumps (``pagecheck_<node>.json``, ISSUE 13)
    sitting next to the analyzed flight/trace files — the page twin of
    the lockcheck listing above: the flight dump says what the node was
    doing, the pagecheck dump says which page custody it violated doing
    it. Listed with violation counts/kinds so a detected use-after-free
    is never invisible in a report."""
    seen: set = set()
    out: List[Dict[str, Any]] = []
    for p in paths:
        d = os.path.dirname(os.path.abspath(p))
        if d in seen:
            continue
        seen.add(d)
        for cand in sorted(glob.glob(os.path.join(d,
                                                  "pagecheck_*.json"))):
            try:
                with open(cand, "r", encoding="utf-8") as f:
                    dump = json.load(f)
            except (OSError, ValueError):
                continue
            violations = dump.get("violations") or []
            out.append({
                "path": cand,
                "node": dump.get("node"),
                "violations": len(violations),
                "violation_kinds": sorted(
                    {v.get("kind") for v in violations}),
                "pools": len(dump.get("pools") or []),
            })
    return out


def _kerncheck_dumps(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """Kernel-sanitizer dumps (``kerncheck_<node>.json``, ISSUE 16)
    sitting next to the analyzed flight/trace files — the kernel twin
    of the lockcheck/pagecheck listings above: the flight dump says
    what the node was doing, the kerncheck dump says which Pallas
    kernel contract it broke doing it (out-of-bounds block or Ref
    slice, grid write race, short-written output row, parity break).
    Listed with violation counts/kinds so a detected kernel crime is
    never invisible in a report."""
    seen: set = set()
    out: List[Dict[str, Any]] = []
    for p in paths:
        d = os.path.dirname(os.path.abspath(p))
        if d in seen:
            continue
        seen.add(d)
        for cand in sorted(glob.glob(os.path.join(d,
                                                  "kerncheck_*.json"))):
            try:
                with open(cand, "r", encoding="utf-8") as f:
                    dump = json.load(f)
            except (OSError, ValueError):
                continue
            violations = dump.get("violations") or []
            out.append({
                "path": cand,
                "node": dump.get("node"),
                "violations": len(violations),
                "violation_kinds": sorted(
                    {v.get("kind") for v in violations}),
                "kernels": sorted(
                    {v.get("kernel") for v in violations}),
            })
    return out


def _profile_summary(path: str, dump: Dict[str, Any]) -> Dict[str, Any]:
    """One line per swarmprof dump for the main report: enough to spot
    "the decode kernel ate 80% of device time at MFU 0.004" without
    opening the file (the --roofline mode prints the full table)."""
    variants = dump.get("variants") or []
    top = variants[0] if variants else {}
    return {
        "path": path,
        "node": dump.get("node"),
        "platform": dump.get("platform"),
        "mfu": dump.get("mfu"),
        "variants": len(variants),
        "top_variant": top.get("variant"),
        "top_device_s": top.get("device_s"),
        "tiny_flush_waves": dump.get("tiny_flush_waves", 0),
    }


def _profile_dumps(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """swarmprof dumps (``profile_*.json``, ISSUE 15) sitting next to
    the analyzed flight/trace files — the device-time sibling of the
    lockcheck/pagecheck listings above: the flight dump says what the
    node was doing, the profile dump says which compiled programs the
    device spent that time in."""
    given = {os.path.abspath(p) for p in paths}
    seen: set = set()
    out: List[Dict[str, Any]] = []
    for p in paths:
        d = os.path.dirname(os.path.abspath(p))
        if d in seen:
            continue
        seen.add(d)
        for cand in sorted(glob.glob(os.path.join(d, "profile_*.json"))):
            if os.path.abspath(cand) in given:
                continue
            try:
                with open(cand, "r", encoding="utf-8") as f:
                    dump = json.load(f)
            except (OSError, ValueError):
                continue
            if dump.get("kind") != "swarmdb.profile":
                continue
            out.append(_profile_summary(cand, dump))
    return out


def _mem_summary(path: str, dump: Dict[str, Any]) -> Dict[str, Any]:
    """One line per swarmmem dump for the main report: enough to spot
    "the pool sat full of cold pages at a 40% prefix hit rate" without
    opening the file (the --memory mode prints the full picture)."""
    occ = dump.get("occupancy") or {}
    conv = dump.get("conversations") or {}
    prefix = dump.get("prefix") or {}
    return {
        "path": path,
        "node": dump.get("node"),
        "prefix_hit_rate": prefix.get("hit_rate"),
        "total_pages": occ.get("total_pages"),
        "headroom_pages": occ.get("headroom_pages"),
        "conversations": conv.get("by_state"),
        "tier_validation": dump.get("tier_validation"),
        "verdict": dump.get("verdict"),
    }


def _mem_dumps(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """swarmmem dumps (``mem_*.json``, ISSUE 17) sitting next to the
    analyzed flight/trace files — the memory sibling of the profile
    listing above: the flight dump says what the node was doing, the
    mem dump says where its KV pages and prefix-cache hit rate stood
    while it did it."""
    given = {os.path.abspath(p) for p in paths}
    seen: set = set()
    out: List[Dict[str, Any]] = []
    for p in paths:
        d = os.path.dirname(os.path.abspath(p))
        if d in seen:
            continue
        seen.add(d)
        for cand in sorted(glob.glob(os.path.join(d, "mem_*.json"))):
            if os.path.abspath(cand) in given:
                continue
            try:
                with open(cand, "r", encoding="utf-8") as f:
                    dump = json.load(f)
            except (OSError, ValueError):
                continue
            if dump.get("kind") != "swarmdb.mem":
                continue
            out.append(_mem_summary(cand, dump))
    return out


# ------------------------------------------------------------------- memory


def memory_report(paths: Sequence[str]) -> Dict[str, Any]:
    """``--memory``: the full memory-accounting report over swarmmem
    dumps (``mem_*.json``). For each dump: the pool occupancy
    decomposition with residency ages, the hot/warm/cold conversation
    temperature distribution (plus the heaviest resident
    conversations — item 3's demote candidates), the sampled miss-ratio
    curve at the standard capacity multiples, the what-if warm-tier
    model with re-admission cost, the cold-resume TTFT model, and the
    sizing verdict ROADMAP item 3 asks for."""
    dumps: List[Dict[str, Any]] = []
    for path in paths:
        kind, data = load_file(path)
        if kind != "mem":
            raise ValueError(f"{path}: --memory takes swarmmem dumps "
                             "(kind=swarmdb.mem)")
        conv = data.get("conversations") or {}
        reuse = data.get("reuse") or {}
        dumps.append({
            "path": path,
            "node": data.get("node"),
            "enabled": data.get("enabled"),
            "page_bytes": data.get("page_bytes"),
            "occupancy": data.get("occupancy"),
            "prefix": data.get("prefix"),
            "temperature": {
                "hot_s": data.get("hot_s"),
                "warm_s": data.get("warm_s"),
                "tracked": conv.get("tracked"),
                "by_state": conv.get("by_state"),
                "resident_pages_by_state":
                    conv.get("resident_pages_by_state"),
                "top_resident": conv.get("top_resident"),
            },
            "miss_ratio_curve": reuse.get("curve"),
            "sampling": {k: reuse.get(k) for k in
                         ("accesses", "sampled", "cold", "sample_rate",
                          "stack_overflowed",
                          "device_capacity_pages")},
            "warm_tier": data.get("warm_tier"),
            "cold_resume": data.get("cold_resume"),
            # predicted-vs-measured warm tier (ISSUE 19): the what-if
            # model's promised hit-rate gain against the promotion hit
            # rate the live tier actually delivered, with a drift flag
            # when the model has gone stale
            "tier_validation": data.get("tier_validation"),
            "verdict": data.get("verdict"),
        })
    return {
        "kind": "swarmdb.obs.memory",
        "version": 1,
        "dumps": dumps,
        # dumps whose live tier disagreed with the what-if model by
        # more than SWARMDB_MEM_TIER_DRIFT — re-run sizing before
        # trusting the verdict line
        "tier_drift_flagged": [
            d["path"] for d in dumps
            if (d.get("tier_validation") or {}).get("drifted")],
    }


# ----------------------------------------------------------------- roofline


def roofline_report(paths: Sequence[str],
                    top_n: int = 10) -> Dict[str, Any]:
    """``--roofline``: the kernel-level device-time report over swarmprof
    dumps. For each dump: the platform peak table, the top-N variants by
    cumulative device seconds (invocations, device_s, per-call FLOPs and
    bytes, achieved FLOP/s, MFU, arithmetic intensity, compute- vs
    memory-bound), per-lane duty cycles, and the dispatch-shape profile
    with tiny ragged flush waves called out — ROADMAP item 2's "should
    SWARMDB_RAGGED_MIN_WIDTH go up" is answered by ``tiny_flush_waves``
    plus those rows' cumulative device time."""
    dumps: List[Dict[str, Any]] = []
    for path in paths:
        kind, data = load_file(path)
        if kind != "profile":
            raise ValueError(f"{path}: --roofline takes swarmprof "
                             "profile dumps (kind=swarmdb.profile)")
        variants = list(data.get("variants") or [])
        variants.sort(key=lambda v: -(v.get("device_s") or 0.0))
        total_dev = sum(v.get("device_s") or 0.0 for v in variants)
        top = []
        for v in variants[:top_n]:
            row = dict(v)
            if total_dev > 0:
                row["device_share"] = round(
                    (v.get("device_s") or 0.0) / total_dev, 4)
            top.append(row)
        tiny = [w for w in (data.get("dispatch_profile") or [])
                if w.get("tiny_flush")]
        # static VMEM view (SWL903, analysis/kernelcheck.py): variants
        # whose dispatch recorded a static footprint estimate, shown
        # against the dump platform's budget — "how close is this
        # kernel to spilling" belongs next to its roofline class
        try:
            from ..analysis.kernelcheck import vmem_budget
            budget = vmem_budget(data.get("device_kind") or "")
        except Exception:
            budget = None
        vm_rows = []
        for v in variants:
            est = v.get("vmem_est_bytes")
            if est is None:
                continue
            b = v.get("vmem_budget_bytes") or budget
            vm_rows.append({
                "variant": v.get("variant"),
                "vmem_est_bytes": est,
                "vmem_budget_bytes": b,
                "vmem_utilization": (round(est / b, 4) if b else None),
            })
        # per-pool section (swarmfleet): pool idleness is a first-class
        # number. Prefer the dump's own pools rollup; reconstruct it from
        # pool-labelled lane rows for dumps written mid-transition.
        pools = [dict(p) for p in (data.get("pools") or [])]
        if not pools:
            by_pool: Dict[str, List[Dict[str, Any]]] = {}
            for lrow in (data.get("lanes") or []):
                p = lrow.get("pool")
                if p:
                    by_pool.setdefault(p, []).append(lrow)
            for p, rows in sorted(by_pool.items()):
                duties = [r.get("duty_cycle") or 0.0 for r in rows]
                pools.append({
                    "pool": p,
                    "lanes": [r.get("lane") for r in rows],
                    "duty_cycle_min": round(min(duties), 6),
                    "duty_cycle_mean": round(sum(duties) / len(duties), 6),
                })
        fam = {"prefill": ("prefill",), "decode": ("decode", "resident")}
        for prow in pools:
            # each pool's variant family grouped out of the same device-
            # time table: role-typed pools partition the variant names,
            # so the share split is exact in fleet mode
            fams = fam.get(str(prow.get("pool")))
            if not fams:
                continue
            pv = [v for v in variants
                  if str(v.get("variant") or "").startswith(fams)]
            dev = sum(v.get("device_s") or 0.0 for v in pv)
            prow["device_s"] = round(dev, 6)
            if total_dev > 0:
                prow["device_share"] = round(dev / total_dev, 4)
            prow["top_variants"] = [v.get("variant") for v in pv[:3]]
        dumps.append({
            "path": path,
            "node": data.get("node"),
            "platform": data.get("platform"),
            "device_kind": data.get("device_kind"),
            "peaks": data.get("peaks"),
            "mfu": data.get("mfu"),
            "device_s_total": round(total_dev, 6),
            "top_variants": top,
            "lanes": data.get("lanes"),
            "pools": pools,
            "tiny_flush_waves": data.get("tiny_flush_waves", 0),
            "tiny_flush_rows": tiny,
            "vmem_budget_bytes": budget,
            "vmem_variants": vm_rows,
        })
    return {
        "kind": "swarmdb.obs.roofline",
        "version": 1,
        "dumps": dumps,
    }


# --------------------------------------------------------------- self-check


def _synthetic_trace(queue_ms: float, prefill_ms: float, decode_ms: float,
                     n: int = 16) -> List[Dict[str, Any]]:
    events = []
    t = 0.0
    for i in range(n):
        rid = f"r{i}"
        events.append({"name": "engine.admit", "ph": "X", "ts": t,
                       "dur": queue_ms * 1e3, "args": {"rid": rid}})
        t += queue_ms * 1e3
        events.append({"name": "engine.prefill", "ph": "X", "ts": t,
                       "dur": prefill_ms * 1e3, "args": {"rid": rid}})
        t += prefill_ms * 1e3 + 2 * _WAVE_GAP_US
        events.append({"name": "engine.decode_chunk", "ph": "X", "ts": t,
                       "dur": decode_ms * 1e3, "args": {"rid": rid}})
        events.append({"name": "engine.host_sync", "ph": "X", "ts": t,
                       "dur": 100.0, "args": None})
        t += decode_ms * 1e3
        events.append({"name": "stage.done", "ph": "X", "ts": t,
                       "dur": 0.0, "args": {"rid": rid}})
    return events


def self_check() -> Dict[str, Any]:
    """Run the pipeline on synthetic data and verify its invariants;
    raises AssertionError on any violation (the CI lint job runs this)."""
    base = summarize_trace(_synthetic_trace(5.0, 10.0, 20.0))
    test = summarize_trace(_synthetic_trace(400.0, 80.0, 25.0))
    verdict = diagnose(base, test)
    shares_sum = sum(verdict["shares"].values())
    assert abs(shares_sum - 1.0) < 1e-3, shares_sum  # 4dp rounding
    assert verdict["dominant"] == "admission_serialization", verdict
    assert verdict["regressed"] is True
    assert set(verdict["shares"]) == set(CONTRIBUTORS)
    # flat A/B: schema-stable, still sums to 1; without flight evidence
    # the whole queue wait stays on admission_serialization (pre-split
    # behavior — what the online sentinel keeps seeing)
    flat = diagnose(base, base)
    assert flat["regressed"] is False
    assert abs(sum(flat["shares"].values()) - 1.0) < 1e-3
    assert flat["shares"]["capacity_wait"] == 0.0
    # with flight evidence of FULL occupancy while queued, the own-mix
    # queue wait is capacity, not admission serialization
    busy_flight = summarize_flight({
        "steps": [{"active": 8, "max_batch": 8, "queued": 5,
                   "occ_at_admit": True, "prompt_tokens": 0,
                   "prefill_padding_tokens": 0, "host_syncs": 0},
                  {"active": 8, "max_batch": 8, "queued": 7,
                   "occ_at_admit": True, "prompt_tokens": 100,
                   "prefill_padding_tokens": 0, "host_syncs": 1}],
        "requests": [],
    })
    assert busy_flight["admission_stall_frac"] == 0.0
    assert busy_flight["stall_evidence"] is True
    split = diagnose(base, base, test_flight=busy_flight)
    assert split["shares"]["admission_serialization"] == 0.0
    assert split["shares"]["capacity_wait"] > 0.0
    # a REGRESSED pair with busy-occupancy evidence puts the queue
    # growth on capacity, not the admission machinery; without the
    # post-admission marker the growth stays on admission (the r05
    # fixture behavior)
    grow = diagnose(base, test, None, busy_flight)
    assert grow["regressed"] is True
    assert grow["shares"]["admission_serialization"] < 0.05
    assert grow["shares"]["capacity_wait"] > 0.5
    unmarked = dict(busy_flight, stall_evidence=False)
    legacy = diagnose(base, test, None, unmarked)
    assert legacy["dominant"] == "admission_serialization"
    # flight summary invariants on a synthetic imbalanced dump
    fl = summarize_flight({
        "steps": [
            {"active_by_shard": {"0": 8, "1": 0}, "prompt_tokens": 0,
             "prefill_padding_tokens": 0, "host_syncs": 0},
            {"active_by_shard": {"0": 8, "1": 0}, "prompt_tokens": 100,
             "prefill_padding_tokens": 25, "host_syncs": 2},
        ],
        "requests": [{"submitted_at": 0.0, "admitted_at": 0.5,
                      "first_token_at": 0.7, "retired_at": 1.0}],
    })
    assert fl["shard_imbalance"] == 2.0
    assert fl["padding_ratio"] == 0.25
    json.dumps(verdict)  # the whole report must be JSON-serializable
    return {"ok": True, "synthetic_diagnosis": verdict}


# --------------------------------------------------------------------- CLI


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m swarmdb_tpu.obs.analyze",
        description="Offline analyzer for swarmdb trace exports and "
                    "flight dumps: per-completion cost decomposition, "
                    "A/B regression attribution (shares sum to 1), "
                    "shard imbalance / padding / host-sync signals.")
    ap.add_argument("paths", nargs="*",
                    help="trace exports and/or flight dumps; with two "
                         "traces the first is the base of the A/B")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write the full report to PATH")
    ap.add_argument("--self-check", action="store_true",
                    help="run the pipeline on synthetic data and verify "
                         "its invariants (CI)")
    ap.add_argument("--roofline", action="store_true",
                    help="kernel-level roofline report over swarmprof "
                         "profile dumps (profile_*.json): top device-"
                         "time variants, MFU, compute- vs memory-bound, "
                         "lane duty cycles, tiny ragged flush waves")
    ap.add_argument("--memory", action="store_true",
                    help="memory-accounting report over swarmmem dumps "
                         "(mem_*.json): pool occupancy + residency "
                         "ages, conversation temperature, sampled "
                         "miss-ratio curve, warm-tier / cold-resume "
                         "models and the tier-sizing verdict")
    args = ap.parse_args(argv)

    if args.self_check:
        result = self_check()
        print(json.dumps(result["synthetic_diagnosis"], indent=2))
        print("analyze self-check: ok")
        return 0
    if not args.paths:
        ap.error("no input files (or use --self-check)")
    try:
        if args.memory:
            report = memory_report(args.paths)
        elif args.roofline:
            report = roofline_report(args.paths)
        else:
            report = analyze_files(args.paths)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"analyze: {exc}", file=sys.stderr)
        return 2
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
