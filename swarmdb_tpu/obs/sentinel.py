"""Online SLO sentinel (ISSUE 7 tentpole): live, in-process regression
detection with cost attribution.

PR 5's offline analyzer explains a regression *after* someone exports
traces and runs ``obs.analyze``. This module runs the same
per-completion cost decomposition (queue_wait / prefill / decode /
host_sync) **continuously, while the regression is happening**:

- The sentinel piggybacks on counters and histograms the hot paths
  already feed — the engine's ``phase_us_*`` accumulators, the
  admission-wave counters, and the TTFT / queue-wait histograms. Its
  record-path cost is therefore ZERO: nothing new is written per
  request, per chunk, or per token.
- ``maybe_tick()`` — one monotonic read and a compare — is called from
  the engine loop and the runtime send path. When a rolling window
  (``SWARMDB_SLO_WINDOW_S``, default 10 s) elapses, the window closes:
  counter/histogram deltas since the previous close become a window
  summary.
- The first ``SWARMDB_SLO_WARMUP`` non-idle windows are averaged into a
  **baseline**. Every later window is checked against the configured
  SLOs (p95 TTFT, p95 queue wait, per-completion engine-cost growth
  factor vs baseline); on breach, the existing regression attributor
  (:func:`swarmdb_tpu.obs.analyze.diagnose`) runs baseline-vs-window
  and the alert names the dominant contributor with numbers, shares
  summing to 1.
- Alerts land in a bounded ring, each firing an automatic flight dump
  and a trace export **tagged with the alert id** (same directory the
  watchdog dumps use — ``SWARMDB_FLIGHT_DIR`` / the engine's flight
  dir), plus a rewrite of the full alert ring
  (``slo_alerts_<node>.json``) so a CI failure artifact carries it.
- Everything is served at ``GET /admin/slo`` and as ``swarmdb_slo_*``
  gauges on ``/metrics``.

Locking stance: the deadline check is lock-free; the rare window-close
path takes a non-blocking lock purely to elect ONE closer when the
engine loop and a runtime send thread race on the same deadline — a
loser skips, it never waits. ``ingest()`` (the pure detection core) is
deterministic given a window summary, which is what the injected-
regression test drives directly.

``SWARMDB_SENTINEL=0`` disables the sentinel entirely (``maybe_tick``
then costs one attribute read).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .metrics import HISTOGRAMS, HIST_QUEUE_WAIT, HIST_TTFT
from ..utils.sync import make_lock

logger = logging.getLogger("swarmdb_tpu.obs")

__all__ = ["SLOSentinel", "SLOConfig"]

#: engine cost categories, one-to-one with the offline analyzer's
CATEGORIES = ("queue_wait", "prefill", "decode", "host_sync")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class SLOConfig:
    """Env-backed sentinel knobs (README env catalog documents them)."""

    __slots__ = ("window_s", "warmup_windows", "min_completions",
                 "ttft_p95_s", "queue_p95_s", "cost_growth_x",
                 "retry_rate", "mfu_drop_x", "duty_drop_x",
                 "prefix_hit_drop_x", "mem_headroom_min",
                 "handoff_p95_ms", "max_alerts", "enabled")

    def __init__(self,
                 window_s: Optional[float] = None,
                 warmup_windows: Optional[int] = None,
                 min_completions: Optional[int] = None,
                 ttft_p95_s: Optional[float] = None,
                 queue_p95_s: Optional[float] = None,
                 cost_growth_x: Optional[float] = None,
                 retry_rate: Optional[float] = None,
                 mfu_drop_x: Optional[float] = None,
                 duty_drop_x: Optional[float] = None,
                 prefix_hit_drop_x: Optional[float] = None,
                 mem_headroom_min: Optional[float] = None,
                 handoff_p95_ms: Optional[float] = None,
                 max_alerts: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        self.window_s = window_s if window_s is not None else \
            _env_float("SWARMDB_SLO_WINDOW_S", 10.0)
        self.warmup_windows = warmup_windows if warmup_windows is not None \
            else _env_int("SWARMDB_SLO_WARMUP", 3)
        # idle-window guard: fewer completions than this and the window
        # neither trains the baseline nor alerts (a 2-request blip would
        # otherwise dominate a mean)
        self.min_completions = min_completions if min_completions is not \
            None else _env_int("SWARMDB_SLO_MIN_COMPLETIONS", 8)
        self.ttft_p95_s = ttft_p95_s if ttft_p95_s is not None else \
            _env_float("SWARMDB_SLO_TTFT_P95_S", 2.5)
        self.queue_p95_s = queue_p95_s if queue_p95_s is not None else \
            _env_float("SWARMDB_SLO_QUEUE_P95_S", 1.0)
        self.cost_growth_x = cost_growth_x if cost_growth_x is not None \
            else _env_float("SWARMDB_SLO_COST_GROWTH_X", 2.0)
        # retry-rate SLO (ISSUE 9): supervised retries (lane migration
        # requeues, shed-and-retry, engine-loss requeues) per completion
        # in the window. A flapping lane shows up HERE — each flap
        # re-fails its migrated requests — before throughput degrades
        # enough to trip the cost SLO.
        self.retry_rate = retry_rate if retry_rate is not None else \
            _env_float("SWARMDB_SLO_RETRY_RATE", 0.5)
        # swarmprof regression SLOs (ISSUE 15): a busy window whose MFU
        # (or worst lane duty cycle) fell past baseline/<factor> breaches
        # even while throughput holds — silicon efficiency is a
        # first-class SLO, not a bench-time afterthought. <= 1 disables.
        self.mfu_drop_x = mfu_drop_x if mfu_drop_x is not None else \
            _env_float("SWARMDB_SLO_MFU_DROP_X", 3.0)
        self.duty_drop_x = duty_drop_x if duty_drop_x is not None else \
            _env_float("SWARMDB_SLO_DUTY_DROP_X", 3.0)
        # swarmmem SLOs (ISSUE 17): a busy window whose prefix hit rate
        # fell past baseline/<factor> (the anchor-jump / cache-thrash
        # signature), or whose pool headroom (free + evictable pages
        # over total) dropped under an absolute floor — parked KV is
        # about to starve admission. <= 1 / <= 0 disables.
        self.prefix_hit_drop_x = prefix_hit_drop_x \
            if prefix_hit_drop_x is not None else \
            _env_float("SWARMDB_SLO_PREFIX_HIT_DROP_X", 2.0)
        self.mem_headroom_min = mem_headroom_min \
            if mem_headroom_min is not None else \
            _env_float("SWARMDB_SLO_MEM_HEADROOM_MIN", 0.05)
        # swarmfleet SLO (ISSUE 20): p95 prefill→decode handoff latency
        # in a window that actually handed off. The handoff is a host
        # gather + store round-trip — if it degrades toward prefill cost
        # the disaggregation is returning its win. <= 0 disables.
        self.handoff_p95_ms = handoff_p95_ms \
            if handoff_p95_ms is not None else \
            _env_float("SWARMDB_SLO_HANDOFF_P95_MS", 250.0)
        self.max_alerts = max_alerts if max_alerts is not None else \
            _env_int("SWARMDB_SLO_ALERTS", 64)
        self.enabled = enabled if enabled is not None else \
            os.environ.get("SWARMDB_SENTINEL", "1") != "0"

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}


class SLOSentinel:
    """Always-on rolling-window SLO monitor over a shared metrics
    registry (the engine records into the same registry the runtime
    owns, so one sentinel sees the whole serving path)."""

    def __init__(self, metrics: Any = None,
                 config: Optional[SLOConfig] = None,
                 flight: Any = None,
                 tracer: Any = None,
                 flight_dir: Optional[str] = None) -> None:
        self.config = config or SLOConfig()
        self.metrics = metrics
        # bound by the serving layer once an engine exists (bind());
        # a broker-only process still gets windows/baseline/SLO checks
        self.flight = flight
        self.tracer = tracer
        self.flight_dir = flight_dir
        self.enabled = self.config.enabled
        self.baseline: Optional[Dict[str, Any]] = None
        self.last_window: Optional[Dict[str, Any]] = None
        self.breached = False
        self.windows_total = 0
        self.alerts_total = 0
        # swarmlint: guarded-by[self._alerts_lock]: _alerts
        self._alerts: List[Dict[str, Any]] = []
        self._alerts_lock = make_lock("obs.sentinel.SLOSentinel._alerts_lock")
        self._warmup: List[Dict[str, Any]] = []
        self._tick_lock = make_lock("obs.sentinel.SLOSentinel._tick_lock")  # single-closer election only
        self._deadline = time.monotonic() + self.config.window_s
        self._window_opened = time.time()
        self._prev_counters: Optional[Dict[str, int]] = None
        self._prev_ttft: List[int] = list(HIST_TTFT.counts)
        self._prev_queue: List[int] = list(HIST_QUEUE_WAIT.counts)
        # swarmprof cumulative snapshot of the previous close (window
        # MFU / duty cycles are deltas, like every other window number)
        self._prev_prof: Optional[Dict[str, Any]] = None
        # swarmmem cumulative snapshot (window prefix hit rate is a
        # token-count delta, same stance)
        self._prev_mem: Optional[Dict[str, Any]] = None
        # swarmfleet cumulative handoff count (window handoffs = delta)
        self._prev_handoffs: Optional[int] = None

    # ------------------------------------------------------------- wiring

    def bind(self, flight: Any = None, tracer: Any = None,
             flight_dir: Optional[str] = None) -> None:
        """Attach the engine-side dump sources (ServingService calls
        this once the engine exists). Idempotent."""
        if flight is not None:
            self.flight = flight
        if tracer is not None:
            self.tracer = tracer
        if flight_dir is not None:
            self.flight_dir = flight_dir

    def set_enabled(self, enabled: bool) -> None:
        """Flip monitoring (bench echo A/B; mirrors the tracer /
        histogram toggles)."""
        self.enabled = bool(enabled)
        if enabled:
            self._deadline = time.monotonic() + self.config.window_s
            self._window_opened = time.time()
            self._prev_counters = None  # re-anchor, don't bill the gap
            self._prev_prof = None
            self._prev_mem = None
            self._prev_handoffs = None

    # -------------------------------------------------------- record path

    # swarmlint: hot
    def maybe_tick(self, now: float = 0.0) -> None:
        """Deadline probe, called from the engine loop and the runtime
        send path: one compare on the fast path, window close only when
        the deadline passed AND this caller wins the non-blocking
        closer election (SWL504 holds this allocation-free)."""
        if not self.enabled:
            return
        if not now:
            now = time.monotonic()
        if now < self._deadline:
            return
        if not self._tick_lock.acquire(blocking=False):
            return
        try:
            if now >= self._deadline:  # re-check: a closer may have won
                self._close_window()
        finally:
            self._tick_lock.release()

    # ------------------------------------------------------- window close

    def _counter_value(self, name: str) -> int:
        # read .value without materializing a defaultdict miss for
        # engines that never ran (a broker-only process has no
        # phase_us_* counters)
        if self.metrics is None:
            return 0
        c = self.metrics.counters.get(name)
        return int(c.value) if c is not None else 0

    def _snapshot_counters(self) -> Dict[str, int]:
        names = ["engine_completed", "engine_admitted",
                 "engine_admission_waves", "engine_host_syncs",
                 "requests_retried", "requests_migrated", "requests_shed"]
        names += [f"phase_us_{c}" for c in CATEGORIES]
        return {n: self._counter_value(n) for n in names}

    @staticmethod
    def _p95_from_delta(boundaries, cur: List[int],
                        prev: List[int]) -> Optional[float]:
        """Window p95 from the cumulative-count delta of a fixed-bucket
        histogram: the upper bound of the bucket where the window's
        cumulative fraction crosses 0.95 (conservative overestimate —
        exactly what an SLO check wants)."""
        delta = [max(0, c - p) for c, p in zip(cur, prev)]
        total = sum(delta)
        if total <= 0:
            return None
        target = 0.95 * total
        cum = 0
        for i, d in enumerate(delta):
            cum += d
            if cum >= target:
                return float(boundaries[min(i, len(boundaries) - 1)])
        return float(boundaries[-1])

    def _close_window(self) -> None:
        """Diff counters/histograms since the previous close into a
        window summary, then run detection on it."""
        now_mono = time.monotonic()
        self._deadline = now_mono + self.config.window_s
        cur = self._snapshot_counters()
        cur_ttft = list(HIST_TTFT.counts)
        cur_queue = list(HIST_QUEUE_WAIT.counts)
        prev, self._prev_counters = self._prev_counters, cur
        opened, self._window_opened = self._window_opened, time.time()
        if prev is None:
            # first close (or re-enable): anchor only — the deltas would
            # bill everything since process start to one window
            self._prev_ttft, self._prev_queue = cur_ttft, cur_queue
            return
        completed = cur["engine_completed"] - prev["engine_completed"]
        window: Dict[str, Any] = {
            "closed_at": self._window_opened,
            "span_s": round(self._window_opened - opened, 3),
            "completed": completed,
            "admitted": cur["engine_admitted"] - prev["engine_admitted"],
            "admission_waves": (cur["engine_admission_waves"]
                                - prev["engine_admission_waves"]),
            "p95_ttft_s": self._p95_from_delta(
                HIST_TTFT.boundaries, cur_ttft, self._prev_ttft),
            "p95_queue_wait_s": self._p95_from_delta(
                HIST_QUEUE_WAIT.boundaries, cur_queue, self._prev_queue),
            "retried": cur["requests_retried"] - prev["requests_retried"],
            "migrated": (cur["requests_migrated"]
                         - prev["requests_migrated"]),
            "shed": cur["requests_shed"] - prev["requests_shed"],
        }
        window["retry_rate"] = round(
            window["retried"] / max(1, completed), 3)
        self._prev_ttft, self._prev_queue = cur_ttft, cur_queue
        denom = max(1, completed)
        per_completion = {}
        for cat in CATEGORIES:
            delta_us = cur[f"phase_us_{cat}"] - prev[f"phase_us_{cat}"]
            per_completion[cat] = round(delta_us / 1e3 / denom, 3)
        window["per_completion_ms"] = per_completion
        waves = max(1, window["admission_waves"])
        window["mean_wave_size"] = round(window["admitted"] / waves, 2)
        # per-category means for the attributor's explanation text:
        # queue/prefill per admission wave, decode/host_sync per chunk
        chunks = max(1, cur["engine_host_syncs"]
                     - prev["engine_host_syncs"])
        admitted = max(1, window["admitted"])
        window["mean_ms"] = {
            "queue_wait": round(
                (cur["phase_us_queue_wait"] - prev["phase_us_queue_wait"])
                / 1e3 / admitted, 3),
            "prefill": round(
                (cur["phase_us_prefill"] - prev["phase_us_prefill"])
                / 1e3 / waves, 3),
            "decode": round(
                (cur["phase_us_decode"] - prev["phase_us_decode"])
                / 1e3 / chunks, 3),
            "host_sync": round(
                (cur["phase_us_host_sync"] - prev["phase_us_host_sync"])
                / 1e3 / chunks, 3),
        }
        self._profile_window(window)
        self._mem_window(window)
        self._fleet_window(window)
        self.ingest(window)

    def _profile_window(self, window: Dict[str, Any]) -> None:
        """Fold swarmprof deltas into the closing window: executed-FLOPs
        MFU over the window's wall time and the minimum per-lane duty
        cycle — the silicon-efficiency numbers the mfu_drop_x /
        duty_drop_x SLOs watch. No-op with the profiler off."""
        try:
            from .profiler import profile_enabled, profiler
        except Exception:  # pragma: no cover - import is stdlib-only
            return
        if not profile_enabled():
            return
        prof = profiler()
        cur = prof.counters_snapshot()
        prev, self._prev_prof = self._prev_prof, cur
        if prev is None:
            return
        span_s = max(1e-6, (cur["mono_ns"] - prev["mono_ns"]) / 1e9)
        peaks = prof.peaks()
        dflops = cur["flops_total"] - prev["flops_total"]
        if peaks.get("peak_flops") and dflops > 0:
            window["mfu"] = round(
                dflops / span_s / peaks["peak_flops"], 6)
        duties = []
        for lane, busy in cur["lane_busy_ns"].items():
            dbusy = busy - prev["lane_busy_ns"].get(lane, 0)
            duties.append(min(1.0, max(0.0, dbusy / (span_s * 1e9))))
        if duties:
            window["min_lane_duty"] = round(min(duties), 4)

    def _mem_window(self, window: Dict[str, Any]) -> None:
        """Fold swarmmem deltas into the closing window: the window's
        prefix hit rate (hit-token delta over looked-up-token delta)
        and the CURRENT pool headroom fraction (free + cached-evictable
        over total) — the numbers the prefix_hit_drop_x /
        mem_headroom_min SLOs watch. No-op with the accountant off."""
        try:
            from .memprof import memprof, memprof_enabled
        except Exception:  # pragma: no cover - import is stdlib-only
            return
        if not memprof_enabled():
            return
        mp = memprof()
        cur = mp.counters_snapshot()
        prev, self._prev_mem = self._prev_mem, cur
        total = cur.get("pool_total_pages", 0)
        if total > 0:
            window["mem_headroom_frac"] = round(
                cur.get("pool_headroom_pages", 0) / total, 4)
        if prev is None:
            return
        dhit = cur["hit_tokens"] - prev["hit_tokens"]
        dmiss = cur["miss_tokens"] - prev["miss_tokens"]
        if dhit + dmiss > 0:
            window["prefix_hit_rate"] = round(dhit / (dhit + dmiss), 4)

    def _fleet_window(self, window: Dict[str, Any]) -> None:
        """Fold swarmfleet handoff latency into the closing window: only
        windows that actually handed off carry ``handoff_p95_ms`` (the
        handoff_p95_ms SLO watches it). No-op without a fleet."""
        if self.metrics is None:
            return
        c = self.metrics.counters.get("fleet_handoffs")
        cur = int(c.value) if c is not None else 0
        prev, self._prev_handoffs = self._prev_handoffs, cur
        if prev is None or cur <= prev:
            return
        window["handoffs"] = cur - prev
        h = self.metrics.latencies.get("fleet_handoff_s")
        p95 = h.percentile(95) if h is not None else None
        if p95 is not None:
            window["handoff_p95_ms"] = round(p95 * 1e3, 3)

    # ---------------------------------------------------------- detection

    @staticmethod
    def _normalize(window: Dict[str, Any]) -> Dict[str, Any]:
        """Fill the keys the attributor expects (tests hand-build
        windows; the online path always provides everything)."""
        w = dict(window)
        pcm = {c: float(w.get("per_completion_ms", {}).get(c, 0.0))
               for c in CATEGORIES}
        w["per_completion_ms"] = pcm
        w.setdefault("mean_ms", dict(pcm))
        w["mean_ms"] = {c: float(w["mean_ms"].get(c, 0.0))
                        for c in CATEGORIES}
        w.setdefault("completed", 0)
        w.setdefault("admission_waves", 0)
        w.setdefault("mean_wave_size", 0.0)
        w.setdefault("retried", 0)
        w.setdefault("retry_rate",
                     round(w["retried"] / max(1, w["completed"]), 3))
        w.setdefault("mfu", None)
        w.setdefault("min_lane_duty", None)
        w.setdefault("prefix_hit_rate", None)
        w.setdefault("mem_headroom_frac", None)
        return w

    def _baseline_from_warmup(self) -> Dict[str, Any]:
        n = len(self._warmup)
        base: Dict[str, Any] = {
            "windows": n,
            "completed": sum(w["completed"] for w in self._warmup),
            "per_completion_ms": {
                c: round(sum(w["per_completion_ms"][c]
                             for w in self._warmup) / n, 3)
                for c in CATEGORIES},
            "mean_ms": {
                c: round(sum(w["mean_ms"][c] for w in self._warmup) / n, 3)
                for c in CATEGORIES},
            "admission_waves": round(
                sum(w["admission_waves"] for w in self._warmup) / n, 1),
            "mean_wave_size": round(
                sum(w["mean_wave_size"] for w in self._warmup) / n, 2),
        }
        for key in ("p95_ttft_s", "p95_queue_wait_s", "mfu",
                    "min_lane_duty", "prefix_hit_rate"):
            vals = [w[key] for w in self._warmup if w.get(key) is not None]
            base[key] = round(sum(vals) / len(vals), 6) if vals else None
        return base

    def ingest(self, window: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Run detection on one closed window (the deterministic core:
        the injected-regression test replays synthetic windows through
        here). Returns the alert if one fired, else None."""
        window = self._normalize(window)
        self.windows_total += 1
        self.last_window = window
        if window["completed"] < self.config.min_completions:
            window["idle"] = True
            return None
        if self.baseline is None:
            self._warmup.append(window)
            if len(self._warmup) >= self.config.warmup_windows:
                self.baseline = self._baseline_from_warmup()
                self._warmup = []
                logger.info("SLO sentinel baseline learned over %d "
                            "windows: %s per-completion ms",
                            self.baseline["windows"],
                            self.baseline["per_completion_ms"])
            return None
        breaches = self._check_slos(window)
        if not breaches:
            self.breached = False
            return None
        return self._fire_alert(window, breaches)

    def _check_slos(self, window: Dict[str, Any]) -> List[Dict[str, Any]]:
        cfg = self.config
        breaches: List[Dict[str, Any]] = []
        ttft = window.get("p95_ttft_s")
        if ttft is not None and ttft > cfg.ttft_p95_s:
            breaches.append({"slo": "ttft_p95_s", "limit": cfg.ttft_p95_s,
                             "value": ttft})
        queue = window.get("p95_queue_wait_s")
        if queue is not None and queue > cfg.queue_p95_s:
            breaches.append({"slo": "queue_wait_p95_s",
                             "limit": cfg.queue_p95_s, "value": queue})
        rr = window.get("retry_rate")
        if rr is not None and rr > cfg.retry_rate:
            breaches.append({"slo": "retry_rate", "limit": cfg.retry_rate,
                             "value": rr})
        base_cost = sum(self.baseline["per_completion_ms"].values())
        cost = sum(window["per_completion_ms"].values())
        growth = (cost / base_cost) if base_cost > 0 else 1.0
        window["cost_growth_x"] = round(growth, 2)
        if growth > cfg.cost_growth_x:
            breaches.append({"slo": "cost_growth_x",
                             "limit": cfg.cost_growth_x,
                             "value": round(growth, 2)})
        # swarmprof regression SLOs (ISSUE 15): a BUSY window (the idle
        # guard already gated) whose MFU or worst lane duty collapsed
        # past baseline/<drop factor> — silicon efficiency falling while
        # throughput holds is a regression, not a curiosity. Baselines
        # of None (profiler off during warmup) disable each check.
        mfu, base_mfu = window.get("mfu"), self.baseline.get("mfu")
        if (mfu is not None and base_mfu and cfg.mfu_drop_x > 1.0
                and mfu < base_mfu / cfg.mfu_drop_x):
            breaches.append({"slo": "mfu_drop_x",
                             "limit": round(base_mfu / cfg.mfu_drop_x, 6),
                             "value": mfu})
        duty = window.get("min_lane_duty")
        base_duty = self.baseline.get("min_lane_duty")
        if (duty is not None and base_duty and cfg.duty_drop_x > 1.0
                and duty < base_duty / cfg.duty_drop_x):
            breaches.append({"slo": "duty_drop_x",
                             "limit": round(base_duty / cfg.duty_drop_x,
                                            4),
                             "value": duty})
        # swarmmem SLOs (ISSUE 17): hit rate collapsing past
        # baseline/<factor> is the cache-thrash / anchor-jump signature;
        # headroom under the absolute floor means parked KV is about to
        # starve admission (runbook step 14 names the checks).
        hr = window.get("prefix_hit_rate")
        base_hr = self.baseline.get("prefix_hit_rate")
        if (hr is not None and base_hr and cfg.prefix_hit_drop_x > 1.0
                and hr < base_hr / cfg.prefix_hit_drop_x):
            breaches.append({"slo": "prefix_hit_drop_x",
                             "limit": round(
                                 base_hr / cfg.prefix_hit_drop_x, 4),
                             "value": hr})
        headroom = window.get("mem_headroom_frac")
        if (headroom is not None and cfg.mem_headroom_min > 0
                and headroom < cfg.mem_headroom_min):
            breaches.append({"slo": "mem_headroom_min",
                             "limit": cfg.mem_headroom_min,
                             "value": headroom})
        # swarmfleet SLO (ISSUE 20): the prefill→decode handoff is a
        # host gather + transit-store round-trip — p95 creeping toward
        # prefill cost means the disaggregation is returning its win
        # (runbook step 17 names the checks).
        ho = window.get("handoff_p95_ms")
        if (ho is not None and cfg.handoff_p95_ms > 0
                and ho > cfg.handoff_p95_ms):
            breaches.append({"slo": "handoff_p95_ms",
                             "limit": cfg.handoff_p95_ms,
                             "value": ho})
        return breaches

    def _fire_alert(self, window: Dict[str, Any],
                    breaches: List[Dict[str, Any]]) -> Dict[str, Any]:
        # deferred import: obs/__init__ pulls this module in, and a
        # module-level import of .analyze here would make
        # `python -m swarmdb_tpu.obs.analyze` trip runpy's
        # found-in-sys.modules warning
        from . import analyze

        self.breached = True
        self.alerts_total += 1
        alert_id = f"slo-{self.alerts_total}-{int(time.time() * 1000)}"
        # the PR 5 attributor, online: baseline is the base of the A/B
        diagnosis = analyze.diagnose(self.baseline, window)
        alert: Dict[str, Any] = {
            "id": alert_id,
            "at": time.time(),
            "breaches": breaches,
            "dominant": diagnosis["dominant"],
            "diagnosis": diagnosis,
            "window": window,
            "baseline": self.baseline,
            "flight_dump": None,
            "trace_dump": None,
        }
        directory = os.environ.get("SWARMDB_FLIGHT_DIR") or self.flight_dir
        if self.flight is not None:
            # flight dump tagged with the alert id (filename + payload
            # reason); auto_dump never raises
            alert["flight_dump"] = self.flight.auto_dump(
                alert_id, self.flight_dir)
        if self.tracer is not None and directory:
            alert["trace_dump"] = self._dump_trace(alert_id, directory)
        with self._alerts_lock:
            self._alerts.append(alert)
            if len(self._alerts) > self.config.max_alerts:
                self._alerts = self._alerts[-self.config.max_alerts:]
        if directory:
            self._write_alert_ring(directory)
        logger.warning(
            "SLO breach %s: %s — dominant contributor %s (%.0f%%); "
            "flight=%s trace=%s", alert_id,
            ", ".join(f"{b['slo']} {b['value']} > {b['limit']}"
                      for b in breaches),
            diagnosis["dominant"],
            100 * diagnosis["shares"][diagnosis["dominant"]],
            alert["flight_dump"], alert["trace_dump"])
        return alert

    def _dump_trace(self, alert_id: str, directory: str) -> Optional[str]:
        """Best-effort trace export next to the flight dump, tagged with
        the alert id in both the filename and the metadata."""
        try:
            os.makedirs(directory, exist_ok=True)
            trace = self.tracer.to_chrome_trace()
            trace["metadata"]["alert_id"] = alert_id
            path = os.path.join(directory, f"trace_{alert_id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(trace, f)
            os.replace(tmp, path)
            return path
        except Exception:
            logger.exception("SLO trace dump failed (%s)", alert_id)
            return None

    def _write_alert_ring(self, directory: str) -> None:
        """Rewrite the full alert ring (atomic) so the CI failure
        artifact that already uploads SWARMDB_FLIGHT_DIR carries the
        sentinel's verdicts alongside the flight dumps."""
        try:
            os.makedirs(directory, exist_ok=True)
            node = os.environ.get("SWARMDB_NODE_ID") or f"p{os.getpid()}"
            path = os.path.join(directory, f"slo_alerts_{node}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"alerts": self.alerts(),
                           "alerts_total": self.alerts_total}, f, indent=1)
            os.replace(tmp, path)
        except Exception:
            logger.exception("SLO alert-ring write failed")

    # ------------------------------------------------------------ reading

    def alerts(self) -> List[Dict[str, Any]]:
        with self._alerts_lock:
            return list(self._alerts)

    def status(self) -> Dict[str, Any]:
        """The ``GET /admin/slo`` payload: config, baseline, the last
        window, the alert ring, and the exemplar links that turn a tail
        histogram bucket into a concrete trace export."""
        exemplars = {
            name: [dict(e, export=f"/admin/trace/export?trace_id="
                                   f"{e['trace_id']}")
                   for e in entries]
            for name, entries in HISTOGRAMS.exemplars().items()}
        return {
            "enabled": self.enabled,
            "config": self.config.to_dict(),
            "baseline": self.baseline,
            "warmup_windows_seen": len(self._warmup),
            "last_window": self.last_window,
            "breached": self.breached,
            "windows_total": self.windows_total,
            "alerts_total": self.alerts_total,
            "alerts": self.alerts(),
            "exemplars": exemplars,
        }

    def prometheus_lines(self) -> List[str]:
        """``swarmdb_slo_*`` gauges for /metrics (the alerting surface:
        page on ``swarmdb_slo_breached == 1`` and read the dominant
        contributor off /admin/slo)."""
        lines = [
            "# TYPE swarmdb_slo_breached gauge",
            f"swarmdb_slo_breached {1 if self.breached else 0}",
            "# TYPE swarmdb_slo_alerts_total counter",
            f"swarmdb_slo_alerts_total {self.alerts_total}",
            "# TYPE swarmdb_slo_windows_total counter",
            f"swarmdb_slo_windows_total {self.windows_total}",
        ]
        w = self.last_window or {}
        if w.get("p95_ttft_s") is not None:
            lines.append("# TYPE swarmdb_slo_ttft_p95_seconds gauge")
            lines.append(f"swarmdb_slo_ttft_p95_seconds {w['p95_ttft_s']}")
        if w.get("p95_queue_wait_s") is not None:
            lines.append("# TYPE swarmdb_slo_queue_wait_p95_seconds gauge")
            lines.append("swarmdb_slo_queue_wait_p95_seconds "
                         f"{w['p95_queue_wait_s']}")
        if w.get("cost_growth_x") is not None:
            lines.append("# TYPE swarmdb_slo_cost_growth_x gauge")
            lines.append(f"swarmdb_slo_cost_growth_x {w['cost_growth_x']}")
        if w.get("retry_rate") is not None:
            lines.append("# TYPE swarmdb_slo_retry_rate gauge")
            lines.append(f"swarmdb_slo_retry_rate {w['retry_rate']}")
        if w.get("mfu") is not None:
            lines.append("# TYPE swarmdb_slo_window_mfu gauge")
            lines.append(f"swarmdb_slo_window_mfu {w['mfu']}")
        if w.get("min_lane_duty") is not None:
            lines.append("# TYPE swarmdb_slo_min_lane_duty gauge")
            lines.append(
                f"swarmdb_slo_min_lane_duty {w['min_lane_duty']}")
        if w.get("prefix_hit_rate") is not None:
            lines.append("# TYPE swarmdb_slo_prefix_hit_rate gauge")
            lines.append(
                f"swarmdb_slo_prefix_hit_rate {w['prefix_hit_rate']}")
        if w.get("mem_headroom_frac") is not None:
            lines.append("# TYPE swarmdb_slo_mem_headroom_frac gauge")
            lines.append(
                f"swarmdb_slo_mem_headroom_frac {w['mem_headroom_frac']}")
        if w.get("per_completion_ms"):
            lines.append("# TYPE swarmdb_slo_per_completion_ms gauge")
            for cat in CATEGORIES:
                lines.append(
                    f'swarmdb_slo_per_completion_ms{{category="{cat}"}} '
                    f"{w['per_completion_ms'].get(cat, 0.0)}")
        return lines
