"""Low-overhead request-span tracer (the flight recorder's twin).

The serving path previously had ONE tracing hook — ``Message.stage_stamp``
wall-clock stamps in a metadata dict (SURVEY §5.1) — which cannot explain
where a request's latency went: queue wait, prefill, decode chunks, and
host syncs all collapse into "done minus enqueued". This tracer records
closed spans with monotonic clocks into per-thread ring buffers and
exports them as Chrome trace-event JSON (``chrome://tracing`` /
https://ui.perfetto.dev load it directly), so a request is a readable
timeline from the API route through the broker to individual engine
decode chunks.

Design constraints (the record path runs inside the engine decode loop
and the broker send path):

- **Zero locks on record.** Each thread owns one ring buffer; the only
  lock is taken once per thread lifetime, at ring registration. Readers
  (export) take benign racy snapshots — a torn read costs at most one
  event, never a crash.
- **Bounded memory.** Rings are fixed-size (``SWARMDB_TRACE_RING``,
  default 4096 events/thread); old events are overwritten. Rings of dead
  threads are pruned at the next registration.
- **Monotonic time.** Spans are stamped with ``time.monotonic_ns`` so a
  wall-clock step can never produce negative durations; one
  (monotonic, epoch) anchor pair converts to wall time at export.
- **Two record APIs.** ``span(...)`` is a convenience context manager for
  warm paths; hot-path functions (``# swarmlint: hot``) must use the
  allocation-free ``span_begin()`` / ``span_end()`` pair — machine-checked
  by swarmlint SWL501/SWL502 (analysis/spans.py).

``SWARMDB_TRACE=0`` disables recording entirely (the record path then
costs one attribute read and a branch).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple
from ..utils.sync import make_lock

__all__ = ["SpanTracer", "TRACER"]

# event tuple layout: (name, cat, rid, t0_ns, t1_ns, args-or-None)
_Event = Tuple[str, str, Optional[str], int, int, Optional[Dict[str, Any]]]


class _Ring:
    """Single-writer event ring owned by one thread."""

    __slots__ = ("events", "idx", "cap", "tid", "name")

    def __init__(self, cap: int, tid: int, name: str) -> None:
        self.events: List[Optional[_Event]] = [None] * cap
        self.idx = 0
        self.cap = cap
        self.tid = tid
        self.name = name

    def put(self, ev: _Event) -> None:
        self.events[self.idx % self.cap] = ev
        self.idx += 1

    def snapshot(self) -> List[_Event]:
        """Oldest-first copy (benign racy read from other threads)."""
        idx = self.idx
        events = list(self.events)  # one shot; writer may lap one slot
        if idx <= self.cap:
            out = events[:idx]
        else:
            cut = idx % self.cap
            out = events[cut:] + events[:cut]
        return [e for e in out if e is not None]


class _SpanCtx:
    """Tiny context manager for ``SpanTracer.span`` (warm paths only)."""

    __slots__ = ("_tracer", "_name", "_cat", "_rid", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str,
                 rid: Optional[str], args: Optional[Dict[str, Any]]) -> None:
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._rid = rid
        self._args = args

    def __enter__(self) -> "_SpanCtx":
        self._t0 = self._tracer.span_begin()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._tracer.span_end(self._t0, self._name, cat=self._cat,
                              rid=self._rid, args=self._args)


class SpanTracer:
    def __init__(self, capacity_per_thread: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        if capacity_per_thread is None:
            try:
                capacity_per_thread = int(
                    os.environ.get("SWARMDB_TRACE_RING", "4096"))
            except ValueError:
                capacity_per_thread = 4096
        if enabled is None:
            enabled = os.environ.get("SWARMDB_TRACE", "1") != "0"
        self.enabled = bool(enabled)
        self.capacity = max(16, capacity_per_thread)
        # ring registry: (ring, weakref-to-owning-thread); mutated only
        # under _reg_lock (once per thread lifetime + resets)
        self._rings: List[Tuple[_Ring, "weakref.ref"]] = []
        self._reg_lock = make_lock("obs.tracer.SpanTracer._reg_lock")
        self._local = threading.local()
        # clock anchor: monotonic <-> epoch, captured together once
        self._anchor_mono_ns = time.monotonic_ns()
        self._anchor_epoch = time.time()

    # ------------------------------------------------------------ recording

    #: dead-thread rings retained (newest first) — a short-lived thread's
    #: events (an HA promotion thread's "ha.promoted" instant, a one-shot
    #: chaos injector) must survive into the next export, or a failover
    #: trace loses exactly the instants it exists to show. The cap still
    #: bounds the registry under thread churn.
    _MAX_DEAD_RINGS = 32

    def _ring(self) -> _Ring:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            t = threading.current_thread()
            ring = _Ring(self.capacity, t.ident or 0, t.name)
            self._local.ring = ring
            with self._reg_lock:
                # bound the registry under thread churn WITHOUT dropping
                # recently dead threads' events: live rings always stay,
                # dead rings are kept newest-first up to the cap
                alive, dead = [], []
                for r, wr in self._rings:
                    owner = wr()
                    if owner is not None and owner.is_alive():
                        alive.append((r, wr))
                    else:
                        dead.append((r, wr))
                if len(dead) > self._MAX_DEAD_RINGS:
                    dead = dead[-self._MAX_DEAD_RINGS:]
                self._rings = alive + dead
                self._rings.append((ring, weakref.ref(t)))
        return ring

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def span_begin(self) -> int:
        """Monotonic-ns start stamp for ``span_end`` — allocation-free,
        the hot-path half of the API (swarmlint SWL501 checks balance)."""
        return time.monotonic_ns() if self.enabled else 0

    def span_end(self, t0: int, name: str, cat: str = "span",
                 rid: Optional[str] = None,
                 args: Optional[Dict[str, Any]] = None) -> None:
        """Record the closed span started at ``t0`` (one ring write)."""
        if not self.enabled or t0 == 0:
            return
        self._ring().put((name, cat, rid, t0, time.monotonic_ns(), args))

    def span_at(self, name: str, start_epoch: float, end_epoch: float,
                cat: str = "span", rid: Optional[str] = None,
                args: Optional[Dict[str, Any]] = None) -> None:
        """Record a span from WALL-clock endpoints (retro-spans for
        intervals whose start predates the tracer call site, e.g. queue
        wait measured from ``submitted_at``)."""
        if not self.enabled:
            return
        t0 = self.mono_of_epoch(start_epoch)
        t1 = max(t0, self.mono_of_epoch(end_epoch))
        self._ring().put((name, cat, rid, t0, t1, args))

    def instant(self, name: str, cat: str = "mark",
                rid: Optional[str] = None,
                args: Optional[Dict[str, Any]] = None) -> None:
        if not self.enabled:
            return
        now = time.monotonic_ns()
        self._ring().put((name, cat, rid, now, now, args))

    def span(self, name: str, cat: str = "span", rid: Optional[str] = None,
             args: Optional[Dict[str, Any]] = None) -> _SpanCtx:
        """Context-manager convenience (allocates — NOT for hot-path
        functions; swarmlint SWL502 flags it there)."""
        return _SpanCtx(self, name, cat, rid, args)

    # -------------------------------------------------------------- reading

    def mono_of_epoch(self, epoch_s: float) -> int:
        return self._anchor_mono_ns + int(
            (epoch_s - self._anchor_epoch) * 1e9)

    def epoch_of_mono(self, mono_ns: int) -> float:
        return self._anchor_epoch + (mono_ns - self._anchor_mono_ns) / 1e9

    def snapshot(self) -> List[Dict[str, Any]]:
        """All buffered events as dicts (oldest-first per thread)."""
        with self._reg_lock:
            rings = [r for r, _ in self._rings]
        out: List[Dict[str, Any]] = []
        for ring in rings:
            for name, cat, rid, t0, t1, args in ring.snapshot():
                out.append({
                    "name": name, "cat": cat, "rid": rid,
                    "start_s": self.epoch_of_mono(t0),
                    "dur_us": (t1 - t0) / 1e3,
                    "tid": ring.tid, "thread": ring.name,
                    "args": args,
                })
        out.sort(key=lambda e: e["start_s"])
        return out

    def events_for(self, rid: str) -> List[Dict[str, Any]]:
        """One request's timeline (spans recorded with this rid)."""
        return [e for e in self.snapshot() if e["rid"] == rid]

    def to_chrome_trace(self, last_n: Optional[int] = None,
                        rid: Optional[str] = None,
                        max_events: Optional[int] = None) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto / chrome://tracing loadable):
        complete ("ph": "X") events, microsecond timestamps relative to
        the tracer's clock anchor, one named track per source thread.

        The export is BOUNDED (ISSUE 6 satellite): a long-lived node's
        rings can hold ``threads x SWARMDB_TRACE_RING`` events, and an
        unbounded ``/admin/trace/export`` response body took the API
        worker down with it. ``rid`` keeps only one trace's events
        (plus ``cat="ha"`` instants — promotions/fencing belong in
        every failover trace regardless of which request they cut
        across); ``last_n`` keeps the newest N span events; both are
        further capped at ``max_events`` (default
        ``SWARMDB_TRACE_EXPORT_MAX``, 50000). Truncation is by age —
        oldest dropped first — and is declared in the metadata."""
        if max_events is None:
            try:
                max_events = int(os.environ.get(
                    "SWARMDB_TRACE_EXPORT_MAX", "50000"))
            except ValueError:
                max_events = 50000
        pid = os.getpid()
        dead_rings: List[_Ring] = []
        with self._reg_lock:
            rings = [r for r, _ in self._rings]
            for r, wr in self._rings:
                owner = wr()
                if owner is None or not owner.is_alive():
                    dead_rings.append(r)
        spans: List[Dict[str, Any]] = []
        tracks: List[Dict[str, Any]] = []
        # dead-thread ring accounting (ISSUE 7 satellite): consumers of a
        # failover/short-lived-thread trace need to know whether those
        # threads' spans are still retained or already evicted by the
        # _MAX_DEAD_RINGS cap — count them and stamp the newest event's
        # age so "the promotion instant is missing" is distinguishable
        # from "it was never recorded"
        newest_end_ns = 0
        for ring in dead_rings:
            for ev in ring.snapshot():
                if ev[4] > newest_end_ns:
                    newest_end_ns = ev[4]
        dead_meta: Dict[str, Any] = {
            "count": len(dead_rings),
            "retain_cap": self._MAX_DEAD_RINGS,
            "newest_event_age_s": (
                round(max(0.0, (time.monotonic_ns() - newest_end_ns))
                      / 1e9, 3) if newest_end_ns else None),
        }
        for ring in rings:
            tracks.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": ring.tid, "args": {"name": ring.name},
            })
            for name, cat, ev_rid, t0, t1, args in ring.snapshot():
                if rid is not None and ev_rid != rid and cat != "ha":
                    continue
                ev: Dict[str, Any] = {
                    "name": name, "cat": cat, "ph": "X", "pid": pid,
                    "tid": ring.tid,
                    "ts": (t0 - self._anchor_mono_ns) / 1e3,
                    "dur": max(0.0, (t1 - t0) / 1e3),
                }
                if ev_rid is not None or args:
                    a: Dict[str, Any] = dict(args or {})
                    if ev_rid is not None:
                        a["rid"] = ev_rid
                    ev["args"] = a
                spans.append(ev)
        spans.sort(key=lambda e: e["ts"])
        total = len(spans)
        keep = total
        if last_n is not None:
            keep = min(keep, max(0, int(last_n)))
        if max_events and max_events > 0:
            keep = min(keep, max_events)
        if keep < total:
            spans = spans[total - keep:]
        events: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "swarmdb_tpu"},
        }]
        events.extend(tracks)
        events.extend(spans)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "anchor_epoch_s": self._anchor_epoch,
                "clock": "monotonic_ns relative to anchor",
                "span_events": len(spans),
                "total_span_events": total,
                "truncated": keep < total,
                "dead_thread_rings": dead_meta,
            },
        }

    def reset(self) -> None:
        """Drop every buffered event (tests / bench window isolation).
        Live threads lazily re-register their rings on the next record."""
        with self._reg_lock:
            self._rings.clear()
        # threads keep their old (now unregistered) ring until they next
        # record through _ring(); force re-registration for THIS thread
        self._local = threading.local()


# Process-global default tracer: every layer (API, runtime, broker,
# engine) records here so one export holds the whole request path.
TRACER = SpanTracer()
