"""Runtime lock sanitizer (swarmlock dynamic half, ISSUE 12).

The static pass (analysis/lockorder.py) reasons about lock *sites*; it
cannot see instances (lane A's ``_cv`` vs lane B's), dynamic dispatch,
or orderings created by data. This module is the other half: when
``SWARMDB_LOCKCHECK=1``, every lock the package allocates through
``utils/sync.py`` is a thin instrumented wrapper that maintains

- a **per-thread held set** (order-preserving),
- the **runtime acquisition-order graph** over lock *instances*, each
  new edge stamped with the acquiring site pair, thread, and a short
  stack — on every new edge a DFS looks for a return path, and a found
  cycle is an **inversion violation**: recorded once per site-cycle,
  written to the flight recorders attached by the engine/HA node,
  dumped to ``lockcheck_<node>.json`` in ``SWARMDB_FLIGHT_DIR``, and
  surfaced at ``GET /admin/lockcheck``,
- per-site **hold-time / contended-acquire stats** (exported on
  ``/metrics`` as ``swarmdb_lock_contended_acquires_total`` and
  ``swarmdb_lock_hold_seconds`` for the top ``SWARMDB_LOCKCHECK_TOPN``
  sites).

With the flag off (default), ``utils/sync.py`` returns the plain
``threading`` classes and this module is never imported — zero
overhead by construction (the bench echo A/B covers the off path;
tests pin the returned types).

The registry's own mutex is a *leaf* lock: it is only ever taken with
user locks already held, never the reverse, and no user code runs
under it — so the sanitizer cannot introduce the inversions it hunts.
Edge bookkeeping is graph-level work done once per novel (a, b)
instance pair; steady-state acquires pay one dict hit and two float
reads.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import re
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

logger = logging.getLogger("swarmdb_tpu.obs")

__all__ = ["enabled", "checked", "registry", "LockCheckRegistry",
           "CheckedLock", "CheckedRLock", "CheckedCondition"]


def enabled() -> bool:
    return os.environ.get("SWARMDB_LOCKCHECK", "0") not in ("", "0")


def _topn() -> int:
    try:
        return max(1, int(os.environ.get("SWARMDB_LOCKCHECK_TOPN", "10")))
    except ValueError:
        return 10


def _short_stack(skip: int = 3, limit: int = 6) -> List[str]:
    """Compact acquisition stack: innermost frames outside this module."""
    out = []
    for fr in reversed(traceback.extract_stack()[:-skip]):
        if fr.filename.endswith(("lockcheck.py", "sync.py")):
            continue
        out.append(f"{os.path.basename(fr.filename)}:{fr.lineno} "
                   f"{fr.name}")
        if len(out) >= limit:
            break
    return out


class _SiteStats:
    __slots__ = ("acquires", "contended", "wait_s", "hold_s",
                 "max_hold_s", "instances")

    def __init__(self) -> None:
        self.acquires = 0
        self.contended = 0
        self.wait_s = 0.0
        self.hold_s = 0.0
        self.max_hold_s = 0.0
        self.instances = 0

    def to_json(self) -> Dict[str, Any]:
        return {
            "acquires": self.acquires,
            "contended": self.contended,
            "wait_s": round(self.wait_s, 6),
            "hold_s": round(self.hold_s, 6),
            "max_hold_s": round(self.max_hold_s, 6),
            "instances": self.instances,
        }


class LockCheckRegistry:
    """Process-global acquisition-order graph + per-site stats."""

    def __init__(self) -> None:
        # leaf lock (see module docstring): never held while taking a
        # user lock, no user code runs under it
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._sites: Dict[str, _SiteStats] = {}
        # instance-level order graph: node = id(wrapper)
        self._adj: Dict[int, Set[int]] = {}
        self._edges: Dict[Tuple[int, int], Dict[str, Any]] = {}
        self._names: Dict[int, str] = {}
        self._cycles: List[Dict[str, Any]] = []
        self._cycle_keys: Set[Tuple[str, ...]] = set()
        self._flights: List[Any] = []
        self._atexit_armed = False

    # ----------------------------------------------------------- wiring

    def attach_flight(self, recorder: Any) -> None:
        """Violations also land as flight-recorder instants."""
        with self._mu:
            if recorder not in self._flights:
                self._flights.append(recorder)

    def register(self, wrapper: "CheckedLock") -> None:
        with self._mu:
            self._names[id(wrapper)] = wrapper.site
            self._sites.setdefault(wrapper.site, _SiteStats()).instances \
                += 1
            if not self._atexit_armed:
                self._atexit_armed = True
                atexit.register(self._atexit_dump)

    def _held_list(self) -> List[List[Any]]:
        """Per-thread held entries ``[wrapper, t_acquired, depth]``.
        Depth lives HERE, not on the wrapper: an RLock's re-entry count
        is per-owner, and a shared instance counter is corrupted the
        moment a Condition.wait parks one thread's ownership while
        another thread acquires (the stale-held-entry bug the chaos
        drill caught on this module's first run)."""
        lst = getattr(self._tls, "held", None)
        if lst is None:
            lst = []
            self._tls.held = lst
        return lst

    def _find_entry(self, wrapper: "CheckedLock") -> Optional[List[Any]]:
        held = self._held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is wrapper:
                return held[i]
        return None

    # ------------------------------------------------------------ events

    def on_acquired(self, wrapper: "CheckedLock", waited_s: float,
                    contended: bool, depth: int = 1) -> None:
        held = self._held_list()
        fresh_cycles: List[Dict[str, Any]] = []
        with self._mu:
            st = self._sites.setdefault(wrapper.site, _SiteStats())
            st.acquires += 1
            st.wait_s += waited_s
            if contended:
                st.contended += 1
            for entry in held:
                if entry[0] is not wrapper:
                    cycle = self._add_edge(entry[0], wrapper)
                    if cycle is not None:
                        fresh_cycles.append(cycle)
        held.append([wrapper, time.monotonic(), depth])
        # side effects OUTSIDE _mu: the flight recorder's event ring
        # takes its own (checked) lock, and re-entering the registry
        # from under its mutex would be this module's own deadlock
        for cycle in fresh_cycles:
            self._emit_violation(cycle)

    def reenter(self, wrapper: "CheckedLock") -> None:
        """Re-entrant acquire by the owning thread: bump depth only."""
        entry = self._find_entry(wrapper)
        if entry is not None:
            entry[2] += 1

    def on_released(self, wrapper: "CheckedLock") -> None:
        entry = self._find_entry(wrapper)
        if entry is None:
            return
        entry[2] -= 1
        if entry[2] > 0:
            return
        self._drop_entry(entry)

    def force_release(self, wrapper: "CheckedLock") -> int:
        """Condition.wait parking: the wait fully releases the lock no
        matter the re-entry depth; returns that depth so the wake-side
        re-acquire can restore it."""
        entry = self._find_entry(wrapper)
        if entry is None:
            return 1
        depth = entry[2]
        self._drop_entry(entry)
        return depth

    def _drop_entry(self, entry: List[Any]) -> None:
        held = self._held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is entry:
                del held[i]
                break
        dt = time.monotonic() - entry[1]
        with self._mu:
            st = self._sites.setdefault(entry[0].site, _SiteStats())
            st.hold_s += dt
            if dt > st.max_hold_s:
                st.max_hold_s = dt

    def holds(self, wrapper: "CheckedLock") -> bool:
        return self._find_entry(wrapper) is not None

    # ------------------------------------------------------- graph/cycles

    # swarmlint: holds[self._mu]
    def _add_edge(self, a: "CheckedLock",
                  b: "CheckedLock") -> Optional[Dict[str, Any]]:
        """Called under ``self._mu``; returns a newly-detected cycle
        (side effects are the caller's job, outside the mutex)."""
        key = (id(a), id(b))
        edge = self._edges.get(key)
        if edge is not None:
            edge["count"] += 1
            return None
        self._edges[key] = {
            "from_site": a.site,
            "to_site": b.site,
            "count": 1,
            "thread": threading.current_thread().name,
            "stack": _short_stack(),
        }
        self._adj.setdefault(key[0], set()).add(key[1])
        self._adj.setdefault(key[1], set())
        path = self._find_path(key[1], key[0])
        if path is None:
            return None
        # path runs key[1] .. key[0]; the closing edge is the one just
        # added, so drop the terminal node to keep each cycle node
        # exactly once
        return self._record_cycle([key[0]] + path[:-1])

    # swarmlint: holds[self._mu]
    def _find_path(self, frm: int, to: int) -> Optional[List[int]]:
        """DFS instance path frm -> to, as a node list ending at to."""
        stack: List[Tuple[int, List[int]]] = [(frm, [frm])]
        seen = {frm}
        while stack:
            node, path = stack.pop()
            if node == to:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # swarmlint: holds[self._mu]
    def _record_cycle(self, nodes: List[int]
                      ) -> Optional[Dict[str, Any]]:
        """``nodes`` is the instance cycle (closing edge implied);
        called under ``self._mu``. Returns the cycle when it is new
        (dedup by site set irrespective of rotation/instances)."""
        sites = [self._names.get(n, "?") for n in nodes]
        key = tuple(sorted(sites))
        if key in self._cycle_keys:
            return None
        self._cycle_keys.add(key)
        edges = []
        for i, n in enumerate(nodes):
            nxt = nodes[(i + 1) % len(nodes)]
            info = self._edges.get((n, nxt))
            if info is not None:
                edges.append(dict(info))
        cycle = {
            "sites": sites,
            "edges": edges,
            "thread": threading.current_thread().name,
            "detected_at": time.time(),
        }
        self._cycles.append(cycle)
        return cycle

    def _emit_violation(self, cycle: Dict[str, Any]) -> None:
        """Runs OUTSIDE ``self._mu`` (the flight ring takes its own
        checked lock)."""
        sites = cycle["sites"]
        logger.warning("lockcheck: lock-order inversion cycle: %s",
                       " -> ".join(sites + [sites[0]]))
        for fl in list(self._flights):
            try:
                fl.record_event({
                    "kind": "lockcheck.inversion",
                    "ts": time.time(),
                    "sites": sites,
                })
            except Exception:
                pass
        # dump immediately: a SIGKILLed chaos victim never reaches
        # atexit, and the violation is the post-mortem
        directory = os.environ.get("SWARMDB_FLIGHT_DIR")
        if directory:
            try:
                self.dump_to(directory)
            except Exception:
                logger.exception("lockcheck dump failed")

    # ------------------------------------------------------------ reading

    def _node_identity(self) -> str:
        raw = (os.environ.get("SWARMDB_NODE_ID")
               or f"p{os.getpid()}")
        return re.sub(r"[^A-Za-z0-9_.-]", "_", raw)

    def report(self) -> Dict[str, Any]:
        with self._mu:
            edges = [dict(e) for e in self._edges.values()]
            cycles = [dict(c) for c in self._cycles]
            sites = {s: st.to_json() for s, st in self._sites.items()}
        return {
            "enabled": enabled(),
            "node": self._node_identity(),
            "sites": sites,
            "edges": edges,
            "cycles": cycles,
            "generated_at": time.time(),
        }

    def cycles(self) -> List[Dict[str, Any]]:
        with self._mu:
            return [dict(c) for c in self._cycles]

    def prometheus_lines(self, prefix: str = "swarmdb_") -> List[str]:
        """Top-N sites by contended acquires and by cumulative hold
        time (``SWARMDB_LOCKCHECK_TOPN``)."""
        with self._mu:
            items = [(s, st) for s, st in self._sites.items()]
            n_cycles = len(self._cycle_keys)
        n = _topn()
        lines = [f"# TYPE {prefix}lock_contended_acquires_total counter"]
        for s, st in sorted(items, key=lambda kv: -kv[1].contended)[:n]:
            lines.append(
                f'{prefix}lock_contended_acquires_total{{site="{s}"}} '
                f"{st.contended}")
        lines.append(f"# TYPE {prefix}lock_hold_seconds counter")
        for s, st in sorted(items, key=lambda kv: -kv[1].hold_s)[:n]:
            lines.append(f'{prefix}lock_hold_seconds{{site="{s}"}} '
                         f"{st.hold_s:.6f}")
        lines.append(f"# TYPE {prefix}lock_inversion_cycles gauge")
        lines.append(f"{prefix}lock_inversion_cycles {n_cycles}")
        return lines

    # swarmlint: holds[self._mu]
    def _write_dump(self, directory: str) -> str:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, f"lockcheck_{self._node_identity()}.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        payload = {
            "enabled": True,
            "node": self._node_identity(),
            "sites": {s: st.to_json() for s, st in self._sites.items()},
            "edges": [dict(e) for e in self._edges.values()],
            "cycles": [dict(c) for c in self._cycles],
            "generated_at": time.time(),
        }
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path

    def dump_to(self, directory: str) -> str:
        with self._mu:
            return self._write_dump(directory)

    def _atexit_dump(self) -> None:
        directory = os.environ.get("SWARMDB_FLIGHT_DIR")
        if not directory:
            return
        try:
            self.dump_to(directory)
        except Exception:  # pragma: no cover - shutdown best-effort
            pass

    def reset(self) -> None:
        """Tests only — forget the graph, stats, and violations."""
        with self._mu:
            self._sites.clear()
            self._adj.clear()
            self._edges.clear()
            self._names.clear()
            self._cycles.clear()
            self._cycle_keys.clear()


_REGISTRY = LockCheckRegistry()


def registry() -> LockCheckRegistry:
    return _REGISTRY


class CheckedLock:
    """Instrumented ``threading.Lock`` with held-set/order tracking."""

    _factory = staticmethod(threading.Lock)
    reentrant = False

    def __init__(self, site: str,
                 reg: Optional[LockCheckRegistry] = None) -> None:
        self.site = site
        self._reg = reg or _REGISTRY
        self._inner = self._factory()
        self._reg.register(self)

    # the threading.Lock surface ---------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self.reentrant and self._reg.holds(self):
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._reg.reenter(self)
            return got
        got = self._inner.acquire(False)
        contended = not got
        waited = 0.0
        if not got and blocking:
            t0 = time.monotonic()
            got = self._inner.acquire(True, timeout)
            waited = time.monotonic() - t0
        elif not got and not blocking:
            return False
        if got:
            self._reg.on_acquired(self, waited, contended)
        return got

    def release(self) -> None:
        self._reg.on_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} site={self.site!r}>"


class CheckedRLock(CheckedLock):
    _factory = staticmethod(threading.RLock)
    reentrant = True

    def locked(self) -> bool:  # RLock has no locked() pre-3.12
        got = self._inner.acquire(blocking=False)
        if got:
            self._inner.release()
        return not got


class CheckedCondition:
    """Instrumented ``threading.Condition``: the underlying lock is a
    tracked node, and ``wait()`` models the release/re-acquire pair —
    re-acquiring after a wake records order edges against whatever
    else the thread still holds, which is precisely the shape that
    inverts in practice."""

    def __init__(self, site: str, lock: Optional[Any] = None,
                 reg: Optional[LockCheckRegistry] = None) -> None:
        # Condition's default lock is an RLock; the tracked node wraps
        # the SAME instance the condition synchronizes on
        self._lock = CheckedRLock(site, reg=reg) if lock is None else lock
        self.site = self._lock.site
        inner = getattr(self._lock, "_inner", self._lock)
        self._cond = threading.Condition(inner)
        self._reg = reg or _REGISTRY

    # lock surface -----------------------------------------------------

    def acquire(self, *args: Any, **kwargs: Any) -> bool:
        return self._lock.acquire(*args, **kwargs)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "CheckedCondition":
        self._lock.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()

    # condition surface ------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> bool:
        # wait() fully releases the lock while parked (whatever the
        # re-entry depth) and re-acquires before returning: mirror that
        # in the held-set so (a) hold time stops accruing across the
        # park, (b) another thread's acquire during the park cannot
        # corrupt ownership bookkeeping, and (c) the re-acquire records
        # order edges against locks this thread still holds
        depth = self._reg.force_release(self._lock)
        try:
            # swarmlint: disable=SWL304 -- this wrapper IS the wait primitive; predicate loops live at its call sites
            return self._cond.wait(timeout)
        finally:
            self._reg.on_acquired(self._lock, 0.0, False, depth=depth)

    def wait_for(self, predicate: Any,
                 timeout: Optional[float] = None) -> Any:
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<CheckedCondition site={self.site!r}>"


def checked(kind: str, site: str) -> Any:
    if kind == "lock":
        return CheckedLock(site)
    if kind == "rlock":
        return CheckedRLock(site)
    if kind == "condition":
        return CheckedCondition(site)
    raise ValueError(f"unknown lock kind: {kind!r}")
