"""Cluster-wide trace-context propagation (ISSUE 6 tentpole, part 1).

The PR 2 tracer is strictly process-local: a request that enters through
``ha/client.py``, crosses the TCP data plane, and is served by a leader
node produces disconnected span fragments in N process-local rings that
nothing can stitch back together. This module defines the compact trace
context that rides the wire so ONE agent message yields ONE trace:

- ``TraceContext(trace_id, span_id, origin)`` — the trace id is the
  join key (for messages it is the message id, so the propagated
  context lines up with every rid-tagged span the layers already
  record); ``origin`` names the node/process that started the trace.
- a **thread-local current context** (``use()`` / ``current()``): the
  runtime activates it around a send, and every wire client below it
  (data-plane calls, ClusterBroker retries, replication appends) injects
  it without threading an argument through the Broker ABC.
- ``inject()`` / ``extract()`` — the wire form is a 3-key dict
  ``{"t": trace_id, "s": span_id, "o": origin}`` small enough to ride
  every data-plane envelope and an occasional replication ``G`` frame.
- ``merge_chrome_traces()`` — stitches per-node Chrome-trace exports
  into one Perfetto-loadable document by re-anchoring each export's
  monotonic timestamps onto a shared wall-clock origin (every export
  carries its ``anchor_epoch_s``). ``GET /admin/cluster/trace`` fans
  out to the cluster map's nodes and returns this merge.

Stdlib-only, like the rest of ``swarmdb_tpu/obs``.
"""

from __future__ import annotations

import itertools
import os
import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["TraceContext", "current", "use", "inject", "extract",
           "node_id", "merge_chrome_traces"]

_local = threading.local()
_span_seq = itertools.count(1)  # C-level next(): thread-safe enough


def node_id() -> str:
    """This process's identity in exported traces: the HA node id when
    the process runs one (``SWARMDB_NODE_ID``, set by HANode/CLI), else
    a pid-derived fallback that is still stable for the process life."""
    return os.environ.get("SWARMDB_NODE_ID") or f"pid-{os.getpid()}"


class TraceContext:
    """One hop's view of a distributed trace (immutable once built)."""

    __slots__ = ("trace_id", "span_id", "origin")

    def __init__(self, trace_id: str, span_id: Optional[str] = None,
                 origin: Optional[str] = None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id or f"{os.getpid():x}.{next(_span_seq):x}"
        self.origin = origin or node_id()

    def child(self) -> "TraceContext":
        """Same trace, fresh span id, THIS process as the hop origin —
        what a server activates for work done on behalf of a caller."""
        return TraceContext(self.trace_id, origin=node_id())

    def __repr__(self) -> str:  # debugging / log lines only
        return (f"TraceContext({self.trace_id!r}, span={self.span_id!r}, "
                f"origin={self.origin!r})")


def current() -> Optional[TraceContext]:
    return getattr(_local, "ctx", None)


@contextmanager
def use(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Activate ``ctx`` for the calling thread (None = no-op passthrough,
    so call sites need no branching)."""
    if ctx is None:
        yield None
        return
    prev = getattr(_local, "ctx", None)
    _local.ctx = ctx
    try:
        yield ctx
    finally:
        _local.ctx = prev


def inject(ctx: Optional[TraceContext] = None) -> Optional[Dict[str, str]]:
    """Wire form of ``ctx`` (or the current context); None when there is
    nothing to propagate — callers simply omit the envelope key."""
    ctx = ctx if ctx is not None else current()
    if ctx is None:
        return None
    return {"t": ctx.trace_id, "s": ctx.span_id, "o": ctx.origin}


def extract(wire: Any) -> Optional[TraceContext]:
    """Parse a wire dict back into a context; tolerant of anything (a
    malformed envelope must never kill a data-plane connection)."""
    if not isinstance(wire, dict):
        return None
    trace_id = wire.get("t")
    if not isinstance(trace_id, str) or not trace_id:
        return None
    span_id = wire.get("s")
    origin = wire.get("o")
    return TraceContext(trace_id,
                        span_id=span_id if isinstance(span_id, str) else None,
                        origin=origin if isinstance(origin, str) else None)


# ---------------------------------------------------------------- merging


def _anchor_of(trace: Dict[str, Any]) -> float:
    try:
        return float(trace.get("metadata", {}).get("anchor_epoch_s", 0.0))
    except (TypeError, ValueError):
        return 0.0


def merge_chrome_traces(
        sources: List[Tuple[str, Dict[str, Any]]]) -> Dict[str, Any]:
    """Merge per-node Chrome-trace exports into one document.

    ``sources`` is ``[(node_label, chrome_trace_dict), ...]``. Each
    export's ``ts`` values are microseconds relative to that process's
    own monotonic anchor; its ``metadata.anchor_epoch_s`` maps them to
    wall time. The merge re-bases every event onto the EARLIEST anchor
    so one Perfetto timeline shows true cross-node ordering (modulo
    host clock skew — wall clocks are the only shared reference).

    In-process clusters share one tracer, so the same event can arrive
    from several "nodes": events are deduplicated on their full
    identity (pid, tid, ts, name, dur).
    """
    anchors = [a for a in (_anchor_of(t) for _, t in sources) if a > 0]
    base = min(anchors) if anchors else 0.0
    events: List[Dict[str, Any]] = []
    seen = set()
    nodes: List[str] = []
    for label, trace in sources:
        nodes.append(label)
        shift_us = (_anchor_of(trace) - base) * 1e6 if base else 0.0
        for ev in trace.get("traceEvents", []):
            if ev.get("ph") == "M":
                # metadata rows (process/thread names) need no shift and
                # must keep one copy per (pid, tid)
                key = ("M", ev.get("name"), ev.get("pid"), ev.get("tid"),
                       str(ev.get("args")))
                if key in seen:
                    continue
                seen.add(key)
                out = dict(ev)
                if ev.get("name") == "process_name":
                    out = dict(ev)
                    out["args"] = {"name": f"swarmdb_tpu:{label}"}
                events.append(out)
                continue
            key = (ev.get("pid"), ev.get("tid"), ev.get("ts"),
                   ev.get("name"), ev.get("dur"))
            if key in seen:
                continue
            seen.add(key)
            out = dict(ev)
            out["ts"] = float(ev.get("ts", 0.0)) + shift_us
            events.append(out)
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "anchor_epoch_s": base,
            "clock": "monotonic_ns re-anchored to the earliest node anchor",
            "nodes": nodes,
        },
    }
