"""Core data model: message types, priorities, statuses, the Message record,
and broker configuration.

Capability parity: reference `swarmdb/ main.py:23-127` (MessageType :23-32,
MessagePriority :35-41, MessageStatus :44-51, Message :54-111, KafkaConfig
:114-127). Behavioral fixes relative to the reference:

- `Message.to_dict` uses pydantic serialization, not ``dataclasses.asdict``
  (reference defect D2, ` main.py:91-98`, which raises TypeError on every
  send).
- Timestamps are coerced to float on construction exactly like the
  reference's validator (` main.py:84-89`).
"""

from __future__ import annotations

import enum
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, Field, field_validator

from ..obs import TRACER


class MessageType(str, enum.Enum):
    """Kinds of inter-agent traffic (reference ` main.py:23-32`)."""

    CHAT = "chat"
    COMMAND = "command"
    FUNCTION_CALL = "function_call"
    FUNCTION_RESULT = "function_result"
    SYSTEM = "system"
    ERROR = "error"
    STATUS = "status"


class MessagePriority(int, enum.Enum):
    """Delivery priority (reference ` main.py:35-41`).

    Unlike the reference — which stores the priority but never orders by it —
    the TPU build's admission queue services higher priorities first (see
    ``backend/engine.py``).
    """

    LOW = 0
    NORMAL = 1
    HIGH = 2
    CRITICAL = 3


class MessageStatus(str, enum.Enum):
    """Lifecycle: pending → delivered → read → processed; failed
    (reference ` main.py:44-51`)."""

    PENDING = "pending"
    DELIVERED = "delivered"
    READ = "read"
    PROCESSED = "processed"
    FAILED = "failed"


MessageContent = Union[str, Dict[str, Any], List[Any]]


class Message(BaseModel):
    """A single inter-agent message (reference ` main.py:54-111`).

    Field-for-field compatible with the reference's pydantic model so that
    persisted JSON snapshots and wire payloads interoperate.
    """

    id: str = Field(default_factory=lambda: str(uuid.uuid4()))
    sender_id: str
    receiver_id: Optional[str] = None  # None = broadcast
    content: MessageContent
    type: MessageType = MessageType.CHAT
    priority: MessagePriority = MessagePriority.NORMAL
    timestamp: float = Field(default_factory=time.time)
    status: MessageStatus = MessageStatus.PENDING
    metadata: Dict[str, Any] = Field(default_factory=dict)
    token_count: Optional[int] = None
    visible_to: List[str] = Field(default_factory=list)

    @field_validator("timestamp", mode="before")
    @classmethod
    def _coerce_timestamp(cls, v: Any) -> float:
        # Reference ` main.py:84-89`: accepts int/float/str, coerces to float.
        if v is None:
            return time.time()
        return float(v)

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe dict (enums → values). Fixes reference defect D2."""
        return self.model_dump(mode="json")

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Message":
        """Inverse of :meth:`to_dict` (reference ` main.py:100-111`)."""
        return cls.model_validate(data)

    def stage_stamp(self, stage: str) -> None:
        """Record a per-stage timestamp in metadata (tracing hook, SURVEY §5.1).

        Stages used by the serving path: ``enqueued``, ``admitted``,
        ``prefill_done``, ``first_token``, ``done``.

        Subsumed by the span tracer (swarmdb_tpu/obs): each stamp also
        lands as an instant event keyed by the message id, so the stage
        marks appear on the same exported timeline as the engine's
        prefill/decode spans. The metadata dict is kept for wire/API
        compatibility (clients read ``metadata["stages"]``).
        """
        self.metadata.setdefault("stages", {})[stage] = time.time()
        TRACER.instant(f"stage.{stage}", cat="stage", rid=self.id)


@dataclass
class BrokerConfig:
    """Transport configuration (reference ``KafkaConfig``, ` main.py:114-127`).

    The field names and defaults mirror the reference so env-var based
    deployments translate directly; Kafka-specific knobs (heartbeats,
    session timeouts) are honored by the in-tree broker's liveness tracker
    rather than by an external cluster.
    """

    bootstrap_servers: str = "localhost:9092"  # ignored by in-proc broker
    group_id: str = "swarm_agents"
    auto_offset_reset: str = "earliest"
    num_partitions: int = 3
    replication_factor: int = 1
    retention_ms: int = 7 * 24 * 60 * 60 * 1000  # 7 days
    max_poll_interval_ms: int = 300_000
    session_timeout_ms: int = 30_000
    heartbeat_interval_ms: int = 10_000
    consumer_timeout_ms: int = 1_000
    # TPU-build extensions (no reference counterpart):
    # directory for the C++ broker's mmap segment logs; None = in-memory only.
    log_dir: Optional[str] = None
    # preferred broker implementation: "auto" | "python" | "native"
    implementation: str = "auto"


# Backwards-compatible alias: deployments written against the reference
# import `KafkaConfig`.
KafkaConfig = BrokerConfig


@dataclass
class BackendSpec:
    """Descriptor of one LLM serving backend (the TPU build's replacement for
    the reference's bare backend-id strings, ` main.py:1293-1325`)."""

    backend_id: str
    model_name: str = "llama3-8b"
    mesh_shape: Dict[str, int] = field(default_factory=dict)  # e.g. {"data": 4, "model": 2}
    max_batch_size: int = 8
    max_seq_len: int = 2048
    partitions: List[int] = field(default_factory=list)  # broker partitions this backend drains
