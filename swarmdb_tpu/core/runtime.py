"""SwarmDB — the core multi-agent messaging runtime.

Capability parity with the reference's ``SwarmsDB`` class
(`swarmdb/ main.py:130-1394`): agent lifecycle, unicast/broadcast/group
send, polled receive, query/search/conversation, status management,
JSON/YAML persistence + archive GC, stats/load introspection, LLM-backend
assignment, partition autoscaling, and context-manager shutdown.

Architectural differences (all deliberate, per SURVEY.md):

- Transport is the in-tree broker (``broker/``), not an external Kafka
  cluster; the L1 interface is the same shape (produce/poll/flush,
  subscribe/poll/close, create_topics/create_partitions).
- Partition routing uses stable FNV-1a (fixes defect D6) and consumers have
  REAL partition affinity: unicast is produced to the receiver's partition,
  broadcast is a fan-out write to every partition, and each agent's consumer
  reads only its own partition (fixes defect D8 — receive is O(own
  messages), not O(all messages)).
- All shared state is guarded by one RLock; the reference shares unlocked
  dicts across 4 gunicorn threads (SURVEY §5.2).
- ``resend_failed_messages`` marks the failed original with
  ``metadata.resent_to`` and skips already-resent messages, so repeated
  calls don't duplicate (fixes defect D10).
- Stats counters are maintained incrementally (O(1) ``get_stats``) instead
  of full scans (` main.py:973-1024`).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..broker.base import Broker, Consumer, Producer, Record
from ..obs import TRACER, propagate
from ..obs.metrics import HIST_PUBLISH
from ..obs.sentinel import SLOSentinel
from ..utils.hashing import stable_partition
from ..utils.metrics import MetricsRegistry
from ..utils.sync import make_rlock
from .messages import (
    BrokerConfig,
    Message,
    MessageContent,
    MessagePriority,
    MessageStatus,
    MessageType,
)

logger = logging.getLogger("swarmdb_tpu")


def _default_broker(config: BrokerConfig) -> Broker:
    """Pick the broker implementation: native C++ engine when built and
    requested, else the pure-Python LocalBroker."""
    impl = config.implementation
    if impl in ("auto", "native"):
        try:
            from ..broker.native import NativeBroker, native_available

            if native_available():
                return NativeBroker(log_dir=config.log_dir)
            if impl == "native":
                raise RuntimeError("native broker requested but library not built")
        except ImportError:
            if impl == "native":
                raise
    from ..broker.local import LocalBroker

    return LocalBroker()


class SwarmDB:
    """TPU-native re-implementation of the reference's ``SwarmsDB``
    (` main.py:130-1394`)."""

    def __init__(
        self,
        config: Optional[BrokerConfig] = None,
        topic_name: str = "swarm_messages",
        save_dir: str = "message_history",
        autosave_interval: float = 300.0,
        max_messages_per_file: int = 10_000,
        token_counter: Optional[Callable[[str], int]] = None,
        broker: Optional[Broker] = None,
        metrics: Optional[MetricsRegistry] = None,
    ) -> None:
        # Reference `__init__` ` main.py:156-237`.
        self.config = config or BrokerConfig()
        self.topic_name = topic_name
        self.error_topic = f"{topic_name}_errors"
        self.save_dir = save_dir
        self.autosave_interval = autosave_interval
        self.max_messages_per_file = max_messages_per_file
        self.token_counter = token_counter
        self.metrics = metrics or MetricsRegistry()
        # online SLO sentinel (obs/sentinel.py, GET /admin/slo): one per
        # runtime, watching the SHARED metrics registry — the serving
        # engine records its phase counters into the same registry, so
        # the sentinel sees the whole path. The send path and the engine
        # loop both drive window closes; SWARMDB_SENTINEL=0 disables.
        self.sentinel = SLOSentinel(metrics=self.metrics)

        # replication_factor > 1 = the reference's Kafka acks=all durability
        # class (` main.py:118,196-197`): a DELIVERED report survives the
        # loss of a broker node. The in-tree equivalent is segment-log
        # replication to follower hosts (broker/replica.py): factor N needs
        # N-1 follower endpoints in SWARMDB_REPLICA_TARGETS ("host:port,
        # host:port", each running `python -m swarmdb_tpu.broker.replica`).
        # Accepting the factor WITHOUT the followers and silently running
        # single-node would misrepresent what DELIVERED implies — reject.
        replica_targets: List[str] = []
        if self.config.replication_factor > 1:
            replica_targets = [
                t.strip()
                for t in os.environ.get("SWARMDB_REPLICA_TARGETS", "").split(",")
                if t.strip()
            ]
            if len(replica_targets) < self.config.replication_factor - 1:
                raise ValueError(
                    f"replication_factor={self.config.replication_factor} "
                    f"needs {self.config.replication_factor - 1} follower "
                    "endpoints in SWARMDB_REPLICA_TARGETS (found "
                    f"{len(replica_targets)}); run followers with `python "
                    "-m swarmdb_tpu.broker.replica` or use "
                    "replication_factor=1 (single-node group-commit fsync)."
                )

        # HA client mode (ISSUE 4): with SWARMDB_HA_CLUSTER pointing at a
        # shared cluster-map file, this runtime is a CLIENT of an
        # HA-supervised broker cluster (`python -m swarmdb_tpu.ha.node`
        # services). The ClusterBroker binds to whichever node the map
        # says is leader and re-points on failover: reads ride through,
        # an in-flight send either lands acked-durable or raises the
        # retryable LeaderChangedError — never silently lost.
        ha_cluster = os.environ.get("SWARMDB_HA_CLUSTER") or None
        if (broker is None and ha_cluster
                and not os.environ.get("SWARMDB_HA_NODE_ID")):
            # (a process with SWARMDB_HA_NODE_ID set IS a cluster node —
            # server.py wires its broker through the HA node's facade
            # instead of making it a client of itself)
            from ..ha.client import ClusterBroker, data_plane_opener
            from ..ha.cluster import FileClusterMap

            broker = ClusterBroker(FileClusterMap(ha_cluster),
                                   data_plane_opener())
        self.broker: Broker = broker if broker is not None else _default_broker(self.config)
        if replica_targets:
            from ..broker.replica import ReplicatedBroker

            self.broker = ReplicatedBroker(self.broker, replica_targets)
        self.producer = Producer(self.broker)
        self._ensure_topics_exist()

        self._lock = make_rlock("core.runtime.SwarmDB._lock")
        # swarmlint: guarded-by[self._lock]: registered_agents, messages, agent_inbox, _conversations, message_count, _stats_by_type, _stats_by_status, _stats_by_agent
        self.registered_agents: Set[str] = set()
        self.consumers: Dict[str, Consumer] = {}
        self.messages: Dict[str, Message] = {}
        self.agent_inbox: Dict[str, List[Message]] = {}
        # unicast (a,b)-pair index so get_conversation — the prompt-builder
        # hot path, called once per served LLM message — is O(limit), not an
        # O(N log N) scan over every message (reference ` main.py:770-808`)
        self._conversations: Dict[tuple, List[Message]] = {}
        self.agent_metadata: Dict[str, Dict[str, Any]] = {}
        self.metadata: Dict[str, Any] = {
            "agent_groups": {},  # reference stores groups here (` main.py:1208-1227`)
            "llm_backends": {},  # agent_id -> backend_id (` main.py:1293-1325`)
        }
        self.llm_load_balancing_enabled = False
        self.message_count = 0
        self._last_save_time = time.time()
        self._sends_since_save = 0
        self._prescale_ends: Dict[int, int] = {}
        # (count, monotonic expiry) — see num_partitions(); benign-racy
        # tuple store, invalidated on partition growth
        self._nparts_cache: Tuple[int, float] = (0, 0.0)
        self._closed = False

        # incremental stats (replaces full scans at ` main.py:973-1024`);
        # per-agent receive rate lives in self.metrics.rates (self-evicting
        # trailing window — bounded, unlike a raw timestamp list).
        self._stats_by_type: Dict[str, int] = {}
        self._stats_by_status: Dict[str, int] = {}
        self._stats_by_agent: Dict[str, Dict[str, int]] = {}

        os.makedirs(self.save_dir, exist_ok=True)

        # Delivery-report poller: with acks=all semantics the broker's
        # group-commit fsync completes AFTER produce returns, so callbacks
        # queued at send time need a later poll to fire (rdkafka solves this
        # with its background poll thread — same shape here). Event-driven
        # (ADVICE r2: the old version woke every 5 ms forever): sends set
        # ``_poller_wake``; the loop spins at 5 ms only WHILE reports are
        # outstanding, then parks on the event. Exits on close().
        self._poller_stop = threading.Event()
        self._poller_wake = threading.Event()
        self._poller = threading.Thread(
            target=self._delivery_poll_loop, name="swarmdb-delivery-poll",
            daemon=True,
        )
        self._poller.start()

    def _delivery_poll_loop(self) -> None:
        while not self._poller_stop.is_set():
            if not self.producer.pending_count:
                # park until the next send (1 s backstop for races between
                # the pending_count read and the event clear)
                self._poller_wake.wait(timeout=1.0)
                self._poller_wake.clear()
                continue
            try:
                # positive timeout: blocks on the broker's durability
                # watermark (native: group-commit condvar; snapshot-mode
                # local: forces the snapshot) so reports actually fire
                self.producer.poll(0.02)
            except Exception:
                logger.exception("delivery poll failed")
            self._poller_stop.wait(0.005)

    # ------------------------------------------------------------------ setup

    def _ensure_topics_exist(self) -> None:
        """Create base + error topics (reference ` main.py:239-293`:
        base topic with N partitions & 7-day retention, `{base}_errors` with
        1 partition & 2x retention).

        Cluster bring-up (ISSUE 14): with a partition-routed broker the
        create is an admin op against the CONTROLLER, and a runtime
        booting alongside its cluster can race the first promotion —
        retryable failures (LeaderChangedError) are retried with backoff
        for a bounded window (``SWARMDB_TOPIC_WAIT_S``) instead of
        failing the whole runtime on a leaderless instant."""
        deadline = time.monotonic() + float(
            os.environ.get("SWARMDB_TOPIC_WAIT_S", "10"))
        while True:
            try:
                self.broker.create_topic(
                    self.topic_name, self.config.num_partitions,
                    self.config.retention_ms)
                self.broker.create_topic(self.error_topic, 1,
                                         self.config.retention_ms * 2)
                return
            except Exception as exc:
                if (not getattr(exc, "retryable", False)
                        or time.monotonic() >= deadline):
                    raise
                time.sleep(0.1)

    def _count_tokens(self, content: MessageContent) -> Optional[int]:
        """Pluggable token counting (reference ` main.py:295-307`):
        structured content is JSON-serialized first."""
        if self.token_counter is None:
            return None
        text = content if isinstance(content, str) else json.dumps(content)
        try:
            return int(self.token_counter(text))
        except Exception as exc:
            logger.warning("token counter failed: %s", exc)
            return None

    @staticmethod
    def _pair(a: str, b: str) -> tuple:
        """Canonical key for the unicast conversation index."""
        return (a, b) if a <= b else (b, a)

    def num_partitions(self) -> int:
        """Partition count of the base topic, TTL-cached (~1 s).

        With a cluster-routed broker (ISSUE 14) ``list_topics`` is a
        control-plane round trip — paying it on EVERY send (partition
        routing + broadcast fan-out both need the count) would put the
        controller on the produce hot path. Partition count only ever
        grows, and growth through this runtime invalidates the cache
        immediately (``auto_scale_partitions``); cross-process growth is
        picked up within the TTL — the same bounded-staleness window
        concurrent processes already have between create and re-pin."""
        num, expires = self._nparts_cache
        now = time.monotonic()
        if num and now < expires:
            return num
        num = self.broker.list_topics()[self.topic_name].num_partitions
        self._nparts_cache = (num, now + 1.0)
        return num

    def _get_partition(self, agent_id: str) -> int:
        """Stable agent → partition mapping (fixes defect D6;
        reference ` main.py:309-312`)."""
        return stable_partition(agent_id, self.num_partitions())

    # --------------------------------------------------------------- registry

    def register_agent(self, agent_id: str,
                       metadata: Optional[Dict[str, Any]] = None,
                       adopt_backlog: bool = False) -> bool:
        """Register an agent and attach a partition-affine consumer
        (reference ` main.py:314-349` — but assigned to the agent's own
        partition instead of the whole topic, fixing D8).

        CROSS-PROCESS ADOPTION (``adopt_backlog``): within one process,
        send_message registers unknown receivers before producing, so no
        record addressed to this agent can predate its consumer and the
        default "start at partition end" loses nothing. But a SECOND
        process registering an agent whose records were produced elsewhere
        (shared durable broker, no committed offsets for this agent's
        group yet) would skip that pre-registration backlog. Pass
        ``adopt_backlog=True`` there: the consumer starts at the partition
        BEGINNING and the partition-affine filter drains the agent's
        history (O(partition) once, the price of adoption). Committed
        offsets, when present, win over either policy. (ADVICE r2 weak #5:
        previously neither fixed nor documented.)
        """
        with self._lock:
            if agent_id in self.registered_agents:
                if metadata:
                    self.agent_metadata.setdefault(agent_id, {}).update(metadata)
                return False
            self.registered_agents.add(agent_id)
            self.agent_inbox.setdefault(agent_id, [])
            if metadata:
                self.agent_metadata[agent_id] = dict(metadata)
            # Fresh per-agent consumers start at the partition END (not
            # `auto_offset_reset`): send_message registers the receiver
            # BEFORE producing, so no record addressed to this agent can
            # predate this consumer — replaying history would only churn
            # through other agents' records client-side (the O(all) receive
            # cost of reference defect D8). Committed offsets still resume.
            consumer = Consumer(
                self.broker,
                group_id=f"{self.config.group_id}_{agent_id}",
                auto_offset_reset="earliest" if adopt_backlog else "latest",
            )
            consumer.assign([(self.topic_name, self._get_partition(agent_id))])
            self.consumers[agent_id] = consumer
            self.metrics.counters["agents_registered"].inc()
            logger.info("registered agent %s", agent_id)
            return True

    def deregister_agent(self, agent_id: str) -> bool:
        """Remove an agent and close its consumer (reference ` main.py:351-372`)."""
        with self._lock:
            if agent_id not in self.registered_agents:
                return False
            self.registered_agents.discard(agent_id)
            consumer = self.consumers.pop(agent_id, None)
            if consumer is not None:
                consumer.close()
            self.agent_metadata.pop(agent_id, None)
            # evict the agent's rate gauge (ADVICE r2: the one per-agent
            # metric map unbounded under agent churn). _stats_by_agent is
            # retained deliberately: the reference's get_stats derives
            # per-agent counts from retained messages, which survive
            # deregistration.
            self.metrics.rates.pop(f"agent_recv:{agent_id}", None)
            # inbox retained, as in the reference (messages remain queryable)
            logger.info("deregistered agent %s", agent_id)
            return True

    def _reassign_consumers(self) -> None:
        """After partition growth, ADD each agent's newly-mapped partition to
        its consumer while keeping the old one, so the old partition's
        undelivered backlog still drains and the new partition starts at its
        current end (no broadcast replay). No reference counterpart — the
        reference's whole-topic subscribe makes this moot at the cost of
        O(all) receives (defect D8)."""
        with self._lock:
            for agent_id, consumer in self.consumers.items():
                part = self._get_partition(agent_id)
                consumer.add_assignment(
                    self.topic_name, part, start_offset=self._prescale_ends.get(part)
                )

    # ------------------------------------------------------------------- send

    def _delivery_callback(self, err: Optional[str], record: Record) -> None:
        """Broker delivery report → message status (reference ` main.py:374-391`)."""
        msg_id = record.key.decode() if record.key else None
        with self._lock:
            msg = self.messages.get(msg_id) if msg_id else None
            if msg is None:
                return
            if err is None:
                # upgrade only: the consumer may have READ the record before
                # its durability-gated report fired — never walk that back
                if msg.status == MessageStatus.PENDING:
                    self._set_status(msg, MessageStatus.DELIVERED)
                # first report wins: on broadcast fan-out the (partition,
                # offset) of copy #1 is as good an anchor as any
                msg.metadata.setdefault("partition", record.partition)
                msg.metadata.setdefault("offset", record.offset)
            else:
                self._set_status(msg, MessageStatus.FAILED)
                msg.metadata["error"] = err

    def send_message(
        self,
        sender_id: str,
        receiver_id: Optional[str],
        content: MessageContent,
        message_type: MessageType = MessageType.CHAT,
        priority: MessagePriority = MessagePriority.NORMAL,
        metadata: Optional[Dict[str, Any]] = None,
        visible_to: Optional[List[str]] = None,
    ) -> str:
        """Send one message; returns its id (reference ` main.py:374-519`).

        Broadcast (``receiver_id=None``) fills ``visible_to`` with every
        registered agent except the sender and is produced to EVERY
        partition (fan-out write) so partition-affine consumers still see it.
        """
        t_send = TRACER.span_begin()
        message_type = MessageType(message_type)
        priority = MessagePriority(priority)
        # auto-register both ends (reference :419-427)
        self.register_agent(sender_id)
        if receiver_id is not None:
            self.register_agent(receiver_id)

        msg = Message(
            sender_id=sender_id,
            receiver_id=receiver_id,
            content=content,
            type=message_type,
            priority=priority,
            metadata=dict(metadata or {}),
            token_count=self._count_tokens(content),
        )
        if receiver_id is None:
            with self._lock:
                everyone = self.registered_agents - {sender_id}
            # None = everyone; an explicit list (even empty) is honored —
            # excluding all agents must NOT fall back to "all" (empty-list
            # vs None ambiguity).
            if visible_to is None:
                msg.visible_to = sorted(everyone)
            else:
                msg.visible_to = sorted(set(visible_to) & everyone)
        elif visible_to:
            msg.visible_to = list(visible_to)
        msg.stage_stamp("enqueued")

        with self._lock:
            self.messages[msg.id] = msg
            self._stats_record_new(msg)
            if receiver_id is not None:
                self.agent_inbox.setdefault(receiver_id, []).append(msg)
                pair = self._pair(sender_id, receiver_id)
                self._conversations.setdefault(pair, []).append(msg)
            else:
                for agent in msg.visible_to:
                    self.agent_inbox.setdefault(agent, []).append(msg)
            self.message_count += 1
            self._sends_since_save += 1

        if receiver_id is None and not msg.visible_to:
            # Broadcast with no eligible recipients: nothing to put on the
            # wire (an empty visible_to on the wire would mean "all" to
            # reference-compatible consumers). Trivially delivered.
            with self._lock:
                self._set_status(msg, MessageStatus.DELIVERED)
            self.metrics.counters["messages_sent"].inc()
            TRACER.span_end(t_send, "runtime.send", cat="runtime",
                            rid=msg.id)
            return msg.id

        payload = json.dumps(msg.to_dict()).encode("utf-8")
        key = msg.id.encode("utf-8")
        t_pub = TRACER.span_begin()
        t_pub_mono = time.monotonic()
        try:
            # trace context for the publish hop (ISSUE 6): trace id =
            # message id, the same join key every local span already
            # carries as rid — a ClusterBroker/data-plane/replication
            # broker below this call propagates it across processes
            with propagate.use(propagate.TraceContext(msg.id)):
                if receiver_id is not None:
                    self.producer.produce(
                        self.topic_name,
                        payload,
                        key=key,
                        partition=self._get_partition(receiver_id),
                        on_delivery=self._delivery_callback,
                    )
                else:
                    num = self.num_partitions()
                    for p in range(num):
                        self.producer.produce(
                            self.topic_name, payload, key=key, partition=p,
                            on_delivery=self._delivery_callback,
                        )
                self.producer.poll(0)
            self._poller_wake.set()  # un-park the delivery-report poller
        except Exception as exc:
            # failure path (reference :507-517): FAILED + copy to error topic
            with self._lock:
                self._set_status(msg, MessageStatus.FAILED)
                msg.metadata["error"] = str(exc)
                if getattr(exc, "retryable", False):
                    # mid-failover (LeaderChangedError): the message is
                    # FAILED-resendable, and the caller's retry (or
                    # resend_failed_messages) lands it on the new leader.
                    # Skip the error-topic copy — it would go through the
                    # same dead leader and double the failure.
                    msg.metadata["retryable"] = True
            if not getattr(exc, "retryable", False):
                try:
                    self.producer.produce(self.error_topic, payload, key=key, partition=0)
                except Exception:
                    logger.exception("error-topic produce failed for %s", msg.id)
            raise

        TRACER.span_end(t_pub, "broker.publish", cat="broker", rid=msg.id)
        HIST_PUBLISH.observe(time.monotonic() - t_pub_mono, msg.id)
        self.metrics.counters["messages_sent"].inc()
        self.metrics.rates["messages_sent"].mark()
        self._maybe_autosave()
        TRACER.span_end(t_send, "runtime.send", cat="runtime", rid=msg.id)
        # SLO window probe (one compare; closes are rare): broker-only
        # deployments get sentinel windows without an engine loop
        self.sentinel.maybe_tick()
        return msg.id

    def broadcast_message(
        self,
        sender_id: str,
        content: MessageContent,
        message_type: MessageType = MessageType.CHAT,
        priority: MessagePriority = MessagePriority.NORMAL,
        metadata: Optional[Dict[str, Any]] = None,
        exclude_agents: Optional[Sequence[str]] = None,
    ) -> str:
        """Broadcast to all registered agents minus sender minus exclusions
        (reference ` main.py:810-850`)."""
        with self._lock:
            visible = sorted(
                self.registered_agents - {sender_id} - set(exclude_agents or ())
            )
        return self.send_message(
            sender_id,
            None,
            content,
            message_type=message_type,
            priority=priority,
            metadata=metadata,
            visible_to=visible,
        )

    # ---------------------------------------------------------------- receive

    def receive_messages(
        self,
        agent_id: str,
        max_messages: int = 10,
        timeout: float = 5.0,
    ) -> List[Message]:
        """Poll the agent's partition for its messages
        (reference ` main.py:521-601`). Bounded by ``max_messages`` and
        wall-clock ``timeout``; marks received messages READ."""
        self.register_agent(agent_id)
        # consumers is maintained under _lock everywhere else; an
        # unguarded read races a concurrent deregister (swarmlint SWL303)
        with self._lock:
            consumer = self.consumers[agent_id]
        t_recv = TRACER.span_begin()
        out: List[Message] = []
        deadline = time.time() + timeout
        while len(out) < max_messages:
            remaining = deadline - time.time()
            # timeout>0 honors the wall clock strictly — even a partition
            # backlog of records filtered out below (other recipients,
            # already-read broadcasts) cannot extend the call past the
            # deadline. timeout<=0 is "drain what's already there" and exits
            # on the first empty non-blocking poll.
            if timeout > 0 and remaining <= 0:
                break
            rec = consumer.poll(
                min(max(remaining, 0.0), self.config.consumer_timeout_ms / 1000.0)
            )
            if rec is None:
                break  # no data within poll window (reference breaks on EOF :566-568)
            try:
                msg = Message.from_dict(json.loads(rec.value.decode("utf-8")))
            except Exception as exc:
                logger.warning("undecodable record at %s[%d]@%d: %s",
                               rec.topic, rec.partition, rec.offset, exc)
                continue
            # visibility filter (reference :579-585)
            if msg.receiver_id not in (agent_id, None):
                continue
            if msg.receiver_id is None:
                if msg.sender_id == agent_id:
                    continue
                if msg.visible_to and agent_id not in msg.visible_to:
                    continue
            with self._lock:
                stored = self.messages.get(msg.id)
                target = stored if stored is not None else msg
                if msg.receiver_id is None:
                    # Broadcast fan-out writes one copy per partition; a
                    # consumer holding several partitions (post-scale) sees
                    # several copies — dedup per agent via read_by.
                    read_by = target.metadata.setdefault("read_by", [])
                    if agent_id in read_by:
                        continue
                    read_by.append(agent_id)
                self._set_status(target, MessageStatus.READ)
                if stored is None:
                    # record arrived from another process/worker — adopt it
                    self.messages[msg.id] = msg
                    self.agent_inbox.setdefault(agent_id, []).append(msg)
                    self._stats_record_new(msg)
                    if msg.receiver_id is not None:
                        # keep the conversation index complete across
                        # workers, or build_prompt drops adopted turns
                        self._conversations.setdefault(
                            self._pair(msg.sender_id, msg.receiver_id), []
                        ).append(msg)
            out.append(target)
            self.metrics.counters["messages_received"].inc()
            self.metrics.rates[f"agent_recv:{agent_id}"].mark()
        if out:
            # productive polls only, and the FIRST received message's id
            # as the span rid: empty polls dominate a quiet consumer loop
            # and per-poll/per-message records were the bulk of the
            # tracer's echo-mode overhead (measured ~2x the 5% budget)
            TRACER.span_end(t_recv, "runtime.receive", cat="runtime",
                            rid=out[0].id)
        return out

    # ------------------------------------------------------------ read/query

    def get_message(self, message_id: str) -> Optional[Message]:
        """Reference ` main.py:603-612`."""
        with self._lock:
            return self.messages.get(message_id)

    def get_agent_messages(
        self,
        agent_id: str,
        status: Optional[MessageStatus] = None,
        limit: int = 100,
        skip: int = 0,
    ) -> List[Message]:
        """Inbox pagination, newest-first (reference ` main.py:614-652`)."""
        with self._lock:
            inbox = list(reversed(self.agent_inbox.get(agent_id, [])))
        if status is not None:
            status = MessageStatus(status)
            inbox = [m for m in inbox if m.status == status]
        return inbox[skip : skip + limit]

    def query_messages(
        self,
        sender_id: Optional[str] = None,
        receiver_id: Optional[str] = None,
        message_type: Optional[MessageType] = None,
        status: Optional[MessageStatus] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
        limit: int = 100,
        involving: Optional[str] = None,
    ) -> List[Message]:
        """Multi-filter scan, newest-first (reference ` main.py:671-726`).

        ``involving`` (TPU-build addition) keeps only messages the named
        agent participates in (sender, receiver, or in ``visible_to``) —
        applied BEFORE the limit so non-admin API queries can't have their
        own traffic crowded out by others' newer messages."""
        message_type = MessageType(message_type) if message_type is not None else None
        status = MessageStatus(status) if status is not None else None
        if limit <= 0:
            return []
        with self._lock:
            msgs = list(self.messages.values())
        out = []
        for m in sorted(msgs, key=lambda m: m.timestamp, reverse=True):
            if sender_id is not None and m.sender_id != sender_id:
                continue
            if receiver_id is not None and m.receiver_id != receiver_id:
                continue
            if message_type is not None and m.type != message_type:
                continue
            if status is not None and m.status != status:
                continue
            if start_time is not None and m.timestamp < start_time:
                continue
            if end_time is not None and m.timestamp > end_time:
                continue
            if involving is not None and involving not in (
                m.sender_id, m.receiver_id
            ) and involving not in m.visible_to:
                continue
            out.append(m)
            if len(out) >= limit:
                break
        return out

    def search_messages(
        self, keyword: str, case_sensitive: bool = False, limit: int = 100
    ) -> List[Message]:
        """Keyword search over content (structured content JSON-serialized
        first), reference ` main.py:728-768`."""
        if limit <= 0:
            return []
        needle = keyword if case_sensitive else keyword.lower()
        with self._lock:
            msgs = list(self.messages.values())
        out = []
        for m in sorted(msgs, key=lambda m: m.timestamp, reverse=True):
            hay = m.content if isinstance(m.content, str) else json.dumps(m.content)
            if not case_sensitive:
                hay = hay.lower()
            if needle in hay:
                out.append(m)
                if len(out) >= limit:
                    break
        return out

    def get_conversation(
        self, agent_a: str, agent_b: str, limit: int = 100
    ) -> List[Message]:
        """Two-way conversation, chronological, up to ``limit`` newest
        messages (reference ` main.py:770-808` queries limit/2 per direction,
        which starves one side and returns nothing for limit=1; we query
        ``limit`` per direction and trim the merge)."""
        if limit <= 0:
            return []
        pair = self._pair(agent_a, agent_b)
        with self._lock:
            # the index is appended in send order (and rebuilt sorted on
            # load), so the tail slice IS the newest window — O(limit), not
            # O(history); sort only the slice to guard clock skew
            tail = self._conversations.get(pair, ())[-limit:]
            tail = list(tail)
        return sorted(tail, key=lambda m: m.timestamp)

    def conversation_length(self, agent_a: str, agent_b: str) -> int:
        """Total messages ever exchanged between the pair — O(1).

        Consumers that window a conversation (e.g. the serving layer's
        prompt builder) need the STREAM position to anchor their window:
        a window computed only from the newest-N fetch slides by one
        message per turn once N binds, which defeats any prefix reuse of
        the rendered prompt."""
        with self._lock:
            return len(self._conversations.get(
                self._pair(agent_a, agent_b), ()))

    def get_conversation_delta(
        self, agent_a: str, agent_b: str, since: int
    ) -> Tuple[int, List[Message]]:
        """(total stream length, messages with stream index >= since) in
        SEND order, under ONE lock acquisition — a split length+fetch
        pair lets a concurrent send shift a newest-N window and silently
        drop the oldest unseen message (rolling-KV suffix builder)."""
        pair = self._pair(agent_a, agent_b)
        with self._lock:
            stream = self._conversations.get(pair, ())
            total = len(stream)
            tail = list(stream[max(0, since):])
        return total, tail

    def get_conversation_window(
        self, agent_a: str, agent_b: str, limit: int,
        step: Optional[int] = None,
    ) -> List[Message]:
        """Hysteresis-anchored conversation window, atomically.

        Drops old messages in ``step``-sized jumps (default half of
        ``limit``) computed from the TOTAL stream length, so the window
        start moves once per ~``step`` turns instead of every turn (a
        plain newest-``limit`` fetch slides per message once it binds,
        and a prompt rendered from a sliding window shares no prefix
        with its predecessor). ``step`` is the epoch-length knob a
        token-budgeted consumer tunes: a SHALLOW window (short-S serving
        trims to a few turns) wants small steps — each jump invalidates
        the whole rendered tail, so a half-of-64 default jump would
        discard far more context than the token budget ever shows the
        model. Length and slice are taken under ONE lock acquisition:
        splitting them lets a concurrent send shift the window by one
        message for that turn — exactly the one-off prefix miss the
        anchoring prevents."""
        if limit <= 0:
            return []
        pair = self._pair(agent_a, agent_b)
        with self._lock:
            stream = self._conversations.get(pair, ())
            total = len(stream)
            keep = limit
            if total > limit:
                step = max(1, limit // 2 if step is None
                           else min(step, limit))
                start = -(-(total - limit) // step) * step  # round UP
                keep = max(1, total - start)
            tail = list(stream[-keep:])
        # STREAM order, not timestamp order (ADVICE r4 low #4): the
        # rolling-KV suffix builder renders get_conversation_delta in
        # send order, and the two renderings must agree or a resumed
        # conversation's history ordering diverges from what a fresh
        # restart would render whenever timestamps disagree with stream
        # order (clock skew, imported history)
        return tail

    # ------------------------------------------------------------- status mgmt

    # swarmlint: holds[self._lock]
    def _set_status(self, msg: Message, status: MessageStatus) -> None:
        """Single choke-point for status transitions; keeps incremental
        by-status counters consistent."""
        old = msg.status
        if old == status:
            return
        msg.status = status
        self._stats_by_status[old.value] = max(0, self._stats_by_status.get(old.value, 0) - 1)
        self._stats_by_status[status.value] = self._stats_by_status.get(status.value, 0) + 1

    def update_message_status(self, message_id: str, status: MessageStatus) -> bool:
        """Direct status transition (API PUT /messages/{id}/status path,
        reference `api.py:691-733`)."""
        status = MessageStatus(status)
        with self._lock:
            msg = self.messages.get(message_id)
            if msg is None:
                return False
            self._set_status(msg, status)
            return True

    def mark_message_as_processed(self, message_id: str) -> bool:
        """Reference ` main.py:654-669`."""
        return self.update_message_status(message_id, MessageStatus.PROCESSED)

    def resend_failed_messages(self) -> List[str]:
        """Re-emit every FAILED message as a new message with
        ``metadata.resent_from`` lineage (reference ` main.py:1096-1130`).
        Fixes defect D10: the failed original is stamped with ``resent_to``
        and skipped on subsequent calls, so repeat invocations are idempotent.
        """
        with self._lock:
            failed = [
                m for m in self.messages.values()
                if m.status == MessageStatus.FAILED and "resent_to" not in m.metadata
            ]
        new_ids: List[str] = []
        for m in failed:
            new_id = self.send_message(
                m.sender_id,
                m.receiver_id,
                m.content,
                message_type=m.type,
                priority=m.priority,
                metadata={**m.metadata, "resent_from": m.id},
            )
            with self._lock:
                m.metadata["resent_to"] = new_id
            new_ids.append(new_id)
        if new_ids:
            logger.info("resent %d failed messages", len(new_ids))
        return new_ids

    # ----------------------------------------------------------------- groups

    def add_agent_group(self, group_name: str, agent_ids: Sequence[str]) -> bool:
        """Create/replace a named group (reference ` main.py:1208-1227`)."""
        for a in agent_ids:
            self.register_agent(a)
        with self._lock:
            self.metadata["agent_groups"][group_name] = list(agent_ids)
        logger.info("group %s = %s", group_name, list(agent_ids))
        return True

    def get_agent_group(self, group_name: str) -> Optional[List[str]]:
        with self._lock:
            members = self.metadata["agent_groups"].get(group_name)
            return list(members) if members is not None else None

    def send_to_group(
        self,
        sender_id: str,
        group_name: str,
        content: MessageContent,
        message_type: MessageType = MessageType.CHAT,
        priority: MessagePriority = MessagePriority.NORMAL,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> List[str]:
        """Group fan-out: one unicast per member, skipping the sender, each
        stamped with ``metadata.group`` (reference ` main.py:1229-1279`).

        The sends target distinct partitions, so downstream the TPU backend
        services the fan-out as one data-parallel batch over the mesh
        (SURVEY §3.4).
        """
        members = self.get_agent_group(group_name)
        if members is None:
            raise KeyError(f"unknown group: {group_name}")
        ids = []
        for member in members:
            if member == sender_id:
                continue
            ids.append(
                self.send_message(
                    sender_id,
                    member,
                    content,
                    message_type=message_type,
                    priority=priority,
                    metadata={**(metadata or {}), "group": group_name},
                )
            )
        return ids

    # ------------------------------------------------------------ persistence

    def _snapshot_state(self) -> Dict[str, Any]:
        """Snapshot schema identical to the reference (` main.py:878-884`):
        {messages, agent_inbox, registered_agents, timestamp, message_count}."""
        with self._lock:
            return {
                "messages": {mid: m.to_dict() for mid, m in self.messages.items()},
                "agent_inbox": {
                    a: [m.id for m in inbox] for a, inbox in self.agent_inbox.items()
                },
                "registered_agents": sorted(self.registered_agents),
                "timestamp": time.time(),
                "message_count": self.message_count,
            }

    def save_message_history(self, filepath: Optional[str] = None) -> str:
        """JSON snapshot to a timestamped file (reference ` main.py:852-892`)."""
        if filepath is None:
            filepath = os.path.join(
                self.save_dir, f"message_history_{int(time.time())}.json"
            )
        state = self._snapshot_state()
        os.makedirs(os.path.dirname(filepath) or ".", exist_ok=True)
        tmp = filepath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=2)
        os.replace(tmp, filepath)
        with self._lock:
            self._last_save_time = time.time()
            self._sends_since_save = 0
        logger.info("saved message history to %s", filepath)
        return filepath

    def load_message_history(self, filepath: str) -> int:
        """Restore a snapshot: messages, inboxes, re-registered agents
        (reference ` main.py:894-934`). Returns number of messages loaded."""
        with open(filepath) as f:
            state = json.load(f)
        msgs = {mid: Message.from_dict(d) for mid, d in state["messages"].items()}
        with self._lock:
            self.messages.update(msgs)
            self._rebuild_stats()
            for agent, ids in state.get("agent_inbox", {}).items():
                inbox = self.agent_inbox.setdefault(agent, [])
                known = {m.id for m in inbox}
                for mid in ids:
                    if mid in msgs and mid not in known:
                        inbox.append(msgs[mid])
            self.message_count = state.get("message_count", len(self.messages))
        for agent in state.get("registered_agents", []):
            self.register_agent(agent)
        logger.info("loaded %d messages from %s", len(msgs), filepath)
        return len(msgs)

    def export_as_yaml(self, filepath: Optional[str] = None) -> str:
        """YAML export of the same snapshot shape (reference ` main.py:936-971`)."""
        import yaml

        if filepath is None:
            filepath = os.path.join(
                self.save_dir, f"message_history_{int(time.time())}.yaml"
            )
        with open(filepath, "w") as f:
            yaml.safe_dump(self._snapshot_state(), f, sort_keys=False)
        return filepath

    def _maybe_autosave(self) -> None:
        """Autosave on interval or message-count threshold
        (reference ` main.py:492-497`: 300 s / 10 k sends)."""
        with self._lock:
            due = (
                time.time() - self._last_save_time >= self.autosave_interval
                or self._sends_since_save >= self.max_messages_per_file
            )
        if due:
            try:
                self.save_message_history()
            except Exception:
                logger.exception("autosave failed")

    # --------------------------------------------------------------------- GC

    def delete_message(self, message_id: str) -> bool:
        """Remove from the store and every inbox (reference ` main.py:1132-1157`)."""
        with self._lock:
            msg = self.messages.pop(message_id, None)
            if msg is None:
                return False
            self._stats_record_removed(msg)
            for inbox in self.agent_inbox.values():
                inbox[:] = [m for m in inbox if m.id != message_id]
            if msg.receiver_id is not None:
                convo = self._conversations.get(
                    self._pair(msg.sender_id, msg.receiver_id)
                )
                if convo is not None:
                    convo[:] = [m for m in convo if m.id != message_id]
            return True

    def flush_old_messages(self, max_age_seconds: float = 7 * 24 * 3600) -> int:
        """Archive-then-delete messages older than the cutoff
        (reference ` main.py:1159-1206`): archive JSON lands under
        ``save_dir/archives/``; broker log is trimmed to match."""
        cutoff = time.time() - max_age_seconds
        with self._lock:
            old = [m for m in self.messages.values() if m.timestamp < cutoff]
        if not old:
            return 0
        archive_dir = os.path.join(self.save_dir, "archives")
        os.makedirs(archive_dir, exist_ok=True)
        path = os.path.join(archive_dir, f"archive_{int(time.time())}.json")
        with open(path, "w") as f:
            json.dump({"messages": [m.to_dict() for m in old],
                       "archived_at": time.time()}, f, indent=2)
        for m in old:
            self.delete_message(m.id)
        self.broker.trim_older_than(self.topic_name, cutoff)
        logger.info("archived %d messages to %s", len(old), path)
        return len(old)

    # ------------------------------------------------------------------ stats

    # swarmlint: holds[self._lock]
    def _stats_record_new(self, msg: Message) -> None:
        self._stats_by_type[msg.type.value] = self._stats_by_type.get(msg.type.value, 0) + 1
        self._stats_by_status[msg.status.value] = (
            self._stats_by_status.get(msg.status.value, 0) + 1
        )
        sender = self._stats_by_agent.setdefault(msg.sender_id, {"sent": 0, "received": 0})
        sender["sent"] += 1
        if msg.receiver_id is not None:
            recv = self._stats_by_agent.setdefault(
                msg.receiver_id, {"sent": 0, "received": 0}
            )
            recv["received"] += 1

    # swarmlint: holds[self._lock]
    def _stats_record_removed(self, msg: Message) -> None:
        self._stats_by_type[msg.type.value] = max(
            0, self._stats_by_type.get(msg.type.value, 0) - 1
        )
        self._stats_by_status[msg.status.value] = max(
            0, self._stats_by_status.get(msg.status.value, 0) - 1
        )
        sender = self._stats_by_agent.get(msg.sender_id)
        if sender is not None:
            sender["sent"] = max(0, sender["sent"] - 1)
        if msg.receiver_id is not None:
            recv = self._stats_by_agent.get(msg.receiver_id)
            if recv is not None:
                recv["received"] = max(0, recv["received"] - 1)

    # swarmlint: holds[self._lock]
    def _rebuild_stats(self) -> None:
        self._stats_by_type = {}
        self._stats_by_status = {}
        self._stats_by_agent = {}
        self._conversations = {}
        for m in sorted(self.messages.values(), key=lambda m: m.timestamp):
            self._stats_record_new(m)
            if m.receiver_id is not None:
                self._conversations.setdefault(
                    self._pair(m.sender_id, m.receiver_id), []
                ).append(m)

    def get_stats(self) -> Dict[str, Any]:
        """Totals by type/status/agent (reference ` main.py:973-1024`) — O(1)
        from incrementally maintained counters."""
        with self._lock:
            return {
                "total_messages": len(self.messages),
                "message_count": self.message_count,
                "registered_agents": len(self.registered_agents),
                "messages_by_type": dict(self._stats_by_type),
                "messages_by_status": dict(self._stats_by_status),
                "messages_by_agent": {a: dict(c) for a, c in self._stats_by_agent.items()},
                "metrics": self.metrics.snapshot(),
            }

    def get_unread_message_count(self, agent_id: str) -> int:
        """Unread = DELIVERED-status inbox entries (reference ` main.py:1026-1047`)."""
        with self._lock:
            return sum(
                1
                for m in self.agent_inbox.get(agent_id, [])
                if m.status == MessageStatus.DELIVERED
            )

    def get_agent_load(self, agent_id: str) -> Dict[str, Any]:
        """Inbox size, unread count, msgs/sec over trailing 60 s
        (reference ` main.py:1049-1094`)."""
        with self._lock:
            return {
                "agent_id": agent_id,
                "inbox_size": len(self.agent_inbox.get(agent_id, [])),
                "unread_count": self.get_unread_message_count(agent_id),
                "messages_per_second": self.metrics.rates[f"agent_recv:{agent_id}"].rate(),
            }

    # ------------------------------------------------------- LLM load balancer

    def set_llm_load_balancing(self, enabled: bool) -> None:
        """Toggle (reference ` main.py:1281-1291`)."""
        with self._lock:
            self.llm_load_balancing_enabled = bool(enabled)

    def assign_llm_backend(self, agent_id: str, backend_id: str) -> None:
        """Agent → backend assignment (reference ` main.py:1293-1311`).
        In the TPU build this is the routing table the ``TPUBackend``
        consumers act on (the reference only stores it)."""
        with self._lock:
            self.metadata["llm_backends"][agent_id] = backend_id

    def get_llm_backend(self, agent_id: str) -> Optional[str]:
        """Reference ` main.py:1313-1325`."""
        with self._lock:
            return self.metadata["llm_backends"].get(agent_id)

    def agents_for_backend(self, backend_id: str) -> List[str]:
        """Inverse lookup used by TPUBackend consumers (no ref counterpart)."""
        with self._lock:
            return [
                a for a, b in self.metadata["llm_backends"].items() if b == backend_id
            ]

    # -------------------------------------------------------------- autoscale

    def auto_scale_partitions(self) -> int:
        """Grow partitions to ``max(3, ceil(agents/10)*3)`` — never shrink
        (reference ` main.py:1327-1365`). Returns the (possibly new) count.

        In the TPU build, partition count is the data-parallel width, so
        growth here is also a signal to widen the serving mesh's data axis.
        """
        import math

        with self._lock:
            n_agents = len(self.registered_agents)
        recommended = max(3, math.ceil(n_agents / 10) * 3)
        current = self.broker.list_topics()[self.topic_name].num_partitions
        if recommended > current:
            # Snapshot pre-growth end offsets BEFORE widening: a send racing
            # between create_partitions and consumer re-pinning must not be
            # skipped, and pre-growth history must not be replayed.
            self._prescale_ends = {
                p: self.broker.end_offset(self.topic_name, p) for p in range(current)
            }
            self._prescale_ends.update({p: 0 for p in range(current, recommended)})
            self.broker.create_partitions(self.topic_name, recommended)
            self._nparts_cache = (0, 0.0)  # growth visible to next send
            self._reassign_consumers()
            logger.info("scaled partitions %d -> %d", current, recommended)
            return recommended
        return current

    # --------------------------------------------------------------- shutdown

    def close(self) -> None:
        """Autosave, close consumers, flush producer (reference ` main.py:1367-1394`)."""
        if self._closed:
            return
        self._closed = True
        self._poller_stop.set()
        self._poller_wake.set()  # release a parked poller immediately
        self._poller.join(timeout=1.0)
        # flush BEFORE the final snapshot: pending durability-gated delivery
        # reports must land so the saved history doesn't freeze messages at
        # a stale PENDING status
        try:
            self.producer.flush()
        except Exception:
            logger.exception("final producer flush failed")
        try:
            self.save_message_history()
        except Exception:
            logger.exception("final autosave failed")
        with self._lock:
            consumers = list(self.consumers.values())
        for c in consumers:
            c.close()
        self.producer.flush()
        self.broker.close()

    def __enter__(self) -> "SwarmDB":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# Reference-compatible alias (` main.py:130`): existing SwarmDB users import
# `SwarmsDB`.
SwarmsDB = SwarmDB
