"""Automatic prefix caching over a KV page pool (vLLM-style, TPU-shaped).

The serve workload re-sends each conversation's whole history every turn
(`backend/service.build_prompt`), so prefill work grows quadratically with
conversation length and dominates decode ~15:1 on the round-4 profile. This
module caches the KV of PAGE-ALIGNED prompt prefixes across requests:

- Every full ``page_size``-token page of a prompt is identified by a CHAIN
  hash — a running blake2b over all tokens from position 0 through the end
  of that page — so equal chains imply equal token prefixes (the raw token
  window is stored and compared too, making collisions impossible rather
  than merely improbable).
- At admission the engine looks up the longest cached chain run, reuses
  those pages (attention reads them via ``ops.layers.gqa_attention_prefix``)
  and prefills ONLY the suffix. After prefill it registers the prompt's
  freshly-written full pages for future turns.
- Pages live in a dedicated pool (dense engine) or the main paged pool;
  eviction is LRU over pages no active slot depends on.

Host-side safety argument (single engine thread + device program order):
admission N's page reads are dispatched before admission N+1 is even
matched, so an entry evicted and re-registered by N+1 can only be
REWRITTEN by a dispatch that the device executes after N's reads. The
table never points a chain at a page whose (eventual) content differs from
that chain's tokens.

No reference counterpart (the reference has no model/serving layer —
SURVEY §5.7); the automatic-prefix-caching pattern is noted in PAPERS.md.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
from ..utils.sync import make_lock


def make_prefix_lru(num_pages: int, page_size: int,
                    manage_free: bool = True, pool: Any = None,
                    label: Optional[str] = None) -> "PrefixLRU":
    """Prefix-cache factory (the PrefixLRU half of the page sanitizer,
    ISSUE 13). Flag off: the plain :class:`PrefixLRU`, exactly as
    before (type identity pinned by tests/test_pagecheck.py).
    ``SWARMDB_PAGECHECK=1``: the checked subclass whose pin/unpin/
    register/evict events feed the shadow page registry — ``pool``
    (the engine's checked PageAllocator) shares its pool shadow in
    paged mode; dense mode registers its own."""
    if os.environ.get("SWARMDB_PAGECHECK", "0") not in ("", "0"):
        from ..obs import pagecheck

        return pagecheck.CheckedPrefixLRU(
            num_pages, page_size, manage_free=manage_free, pool=pool,
            label=label)
    return PrefixLRU(num_pages, page_size, manage_free=manage_free)


def page_chains(tokens: Sequence[int], page_size: int,
                max_pages: Optional[int] = None) -> List[bytes]:
    """Chain hashes for every FULL page of ``tokens``.

    chain[i] digests tokens[0 : (i+1)*page_size] — a prefix identity, not a
    page identity, so page i can only hit behind a hit of page i-1.
    """
    n_full = len(tokens) // page_size
    if max_pages is not None:
        n_full = min(n_full, max_pages)
    h = hashlib.blake2b(digest_size=16)
    out: List[bytes] = []
    # one vectorized serialization — this runs per admission on the single
    # engine thread; a per-int to_bytes loop was ~100x slower on long
    # prompts (review finding)
    raw = np.asarray(tokens[: n_full * page_size], dtype="<i4").tobytes()
    stride = 4 * page_size
    for i in range(n_full):
        h.update(raw[i * stride: (i + 1) * stride])
        out.append(h.digest())
    return out


class PrefixLRU:
    """Chain-hash → page-id table with LRU eviction over an id pool.

    Page ids are ``1..num_pages-1`` (0 is the trash page, never cached).
    ``pin``/``unpin`` guard pages that an ACTIVE slot's attention still
    reads every decode step (dense mode never needs this — the gathered
    prefix is copied into the slot's lane — but the paged engine reads
    shared pages in place until retirement).
    """

    def __init__(self, num_pages: int, page_size: int,
                 manage_free: bool = True) -> None:
        """``manage_free=False`` (paged-engine mode): this table does NOT
        own a free list — pages are borrowed from the engine's
        PageAllocator, ``acquire``/``evict_lru`` only evict entries, and
        the caller returns evicted ids to the allocator."""
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the trash page)")
        self.page_size = page_size
        self.num_pages = num_pages
        self._manage_free = manage_free
        self._free: List[int] = (
            list(range(num_pages - 1, 0, -1)) if manage_free else []
        )
        # chain -> (page_id, token window); insertion order == LRU order
        self._entries: "OrderedDict[bytes, Tuple[int, Tuple[int, ...]]]" = (
            OrderedDict()
        )
        self._pins: dict = {}            # page_id -> pin count
        self._lock = make_lock("ops.prefix_cache.PrefixLRU._lock")
        self.hits = 0
        self.misses = 0
        # per-LOOKUP counters (vs the per-page hits/misses above):
        # a full-miss lookup on a prompt with cached-eligible pages is
        # the anchor-jump signature — the window re-anchored and every
        # previously cached page of the conversation went dark. The
        # ratio full_misses/lookups is the number the sink-anchored
        # window drives toward zero (PROFILE r6).
        self.lookups = 0
        self.full_misses = 0
        # pool generation (managed-free mode): bumped by reset(). Pages
        # held OUTSIDE the table (the serving layer's dense rolling-KV
        # registry acquires custody via acquire()) are only valid within
        # the generation they were taken in — reset() rebuilds the free
        # list, so a stale holder releasing or resuming them would alias
        # a later occupant's pages (same contract as
        # ops.paged_kv.PageAllocator.generation).
        self.generation = 0
        # swarmmem reuse-distance probe (ISSUE 17): every match() feeds
        # its chain accesses to the SHARDS sampler (flag off -> the
        # shared NullProbe; unsampled accesses cost one hash+compare).
        from ..obs.memprof import memprof

        self.mem = memprof().prefix_probe(self.stats)

    # ---------------------------------------------------------------- lookup

    def match(self, chains: Sequence[bytes],
              tokens: Sequence[int]) -> List[int]:
        """Longest cached run of ``chains`` (from page 0); returns its page
        ids and touches them MRU. ``tokens`` re-verifies content so a hash
        collision cannot alias two different prefixes."""
        pages: List[int] = []
        ps = self.page_size
        with self._lock:
            for i, chain in enumerate(chains):
                entry = self._entries.get(chain)
                if entry is None:
                    break
                page_id, window = entry
                if tuple(tokens[i * ps: (i + 1) * ps]) != window:
                    break  # collision — treat as miss
                self._entries.move_to_end(chain)
                pages.append(page_id)
            self.hits += len(pages)
            self.misses += max(0, len(chains) - len(pages))
            if chains:
                self.lookups += 1
                if not pages:
                    self.full_misses += 1
            m = self.mem
            if m.enabled:
                for chain in chains:
                    m.access(chain)
        return pages

    # ------------------------------------------------------------ allocation

    def acquire(self, n: int) -> List[int]:
        """Take UP TO ``n`` page ids for registration, evicting LRU
        unpinned entries as needed; returns what the pool can cover
        (possibly empty — the caller registers that much less)."""
        with self._lock:
            take: List[int] = []
            while len(take) < n and self._free:
                take.append(self._free.pop())
            if len(take) < n:
                evictable = [c for c, (p, _) in self._entries.items()
                             if not self._pins.get(p)]
                for chain in evictable:
                    if len(take) >= n:
                        break
                    page_id, _ = self._entries.pop(chain)
                    take.append(page_id)
            return take

    def evict_lru(self, n: int, want=None) -> List[int]:
        """Evict up to ``n`` LRU unpinned entries, returning their page
        ids for the caller's free list (paged-engine mode — the returned
        pages are NOT retained here). ``want(page_id)`` filters the
        candidates: on a DP-sharded pool only same-shard pages can cover
        a slot's shortfall, and evicting foreign-shard entries would
        drain the whole cache without unblocking anything."""
        with self._lock:
            out: List[int] = []
            for chain in [c for c, (p, _) in self._entries.items()
                          if not self._pins.get(p)
                          and (want is None or want(p))]:
                if len(out) >= n:
                    break
                page_id, _ = self._entries.pop(chain)
                out.append(page_id)
            return out

    def match_and_pin(self, chains: Sequence[bytes],
                      tokens: Sequence[int]) -> List[int]:
        """``match`` + pin the hit pages atomically (paged mode: a later
        admission in the same round must not evict pages this one is
        about to attach to a slot)."""
        pages = self.match(chains, tokens)
        self.pin(pages)
        return pages

    def reset(self) -> None:
        """Forget everything (engine restart rebuilds the pool buffers, so
        every cached entry would point at zeroed pages)."""
        with self._lock:
            # bump BEFORE rebuilding the free list: a racing epoch check
            # must never observe (old generation, rebuilt pool)
            self.generation += 1
            self._free = (list(range(self.num_pages - 1, 0, -1))
                          if self._manage_free else [])
            self._entries.clear()
            self._pins.clear()

    def evictable_count(self) -> int:
        """How many cached pages could be evicted right now (cached and
        not pinned) — the page-pool backpressure gate counts these as
        headroom, since admission can always reclaim them via
        evict_lru."""
        with self._lock:
            return sum(1 for _, (p, _t) in self._entries.items()
                       if not self._pins.get(p))

    def free_count(self) -> int:
        """Managed-free mode: pages immediately takeable without eviction
        (the dense rolling registry's headroom probe)."""
        with self._lock:
            return len(self._free)

    def register(self, chain: bytes, tokens: Tuple[int, ...],
                 page_id: int) -> bool:
        """Bind ``chain`` to ``page_id`` (whose device content a dispatched
        write is filling with exactly ``tokens``'s KV). Returns True if
        custody of ``page_id`` was accepted; False on a DUPLICATE chain
        (two slots prefilled the same new prefix in one round) — the old
        page is kept and the caller retains custody of the new one (in
        managed-free mode it is recycled here)."""
        with self._lock:
            old = self._entries.pop(chain, None)
            if old is not None:
                self._entries[chain] = old
                self._entries.move_to_end(chain)
                if self._manage_free:
                    self._free.append(page_id)
                return False
            self._entries[chain] = (page_id, tuple(tokens))
            return True

    def release(self, page_id: int) -> None:
        """Return a page acquired but never registered (group failed).
        In paged mode (manage_free=False) the caller returns the page to
        the PageAllocator instead — appending here would fork custody."""
        with self._lock:
            if self._manage_free:
                self._free.append(page_id)

    # ---------------------------------------------------------------- pinning

    def pin(self, page_ids: Sequence[int]) -> None:
        with self._lock:
            for p in page_ids:
                self._pins[p] = self._pins.get(p, 0) + 1

    def unpin(self, page_ids: Sequence[int]) -> None:
        with self._lock:
            for p in page_ids:
                c = self._pins.get(p, 0) - 1
                if c <= 0:
                    self._pins.pop(p, None)
                else:
                    self._pins[p] = c

    # ----------------------------------------------------------- introspection

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_pages": self.num_pages,
                "free_pages": len(self._free),
                "cached_pages": len(self._entries),
                "pinned_pages": len(self._pins),
                "page_size": self.page_size,
                "hit_tokens": self.hits * self.page_size,
                "miss_tokens": self.misses * self.page_size,
                "lookups": self.lookups,
                "full_misses": self.full_misses,
            }
