"""Pallas TPU kernel: decode-step GQA attention over the slot KV cache.

The serving hot path (engine decode chunks) issues attention with ONE query
per slot against that slot's cache lane. The XLA einsum path materializes
fp32 scores [B, Hq, S] in HBM between ops; this kernel keeps each
(batch, kv-head) tile's scores in VMEM: one MXU dot for q·K, masked softmax
in registers, one dot against V — per grid cell the only HBM traffic is the
cache lane itself, which is the unavoidable read.

Layout (grid = (B, Hkv)):
- q block   [1, G, D]   — the G = Hq/Hkv query heads sharing this kv head
- k/v block [1, S, 1, D] — the full cache lane for this (slot, kv head)
- length    [1] in SMEM  — valid prefix length (= q position + 1)

Single-chip path only: under tensor parallelism the cache's head axis is
sharded and this call would force a gather; the engine enables the kernel
when the model is unsharded (see ops/layers.gqa_attention dispatch).

No reference counterpart (the reference has no model code, SURVEY §5.7);
design per /opt/skills/guides/pallas_guide.md and the ragged-paged-attention
pattern noted in PAPERS.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds of jax as well
    from jax.experimental.pallas import tpu as pltpu

    _SMEM = pltpu.SMEM
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _SMEM = _VMEM = None


def _decode_attn_kernel(len_ref, q_ref, k_ref, v_ref, o_ref):
    # q_ref [1, G, D]; k_ref/v_ref [1, S, 1, D]; len_ref [1] (SMEM)
    q = q_ref[0].astype(jnp.float32)                   # [G, D]
    k = k_ref[0, :, 0, :].astype(jnp.float32)          # [S, D]
    v = v_ref[0, :, 0, :].astype(jnp.float32)          # [S, D]
    S = k.shape[0]
    scale = 1.0 / (q.shape[-1] ** 0.5)

    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                          # [G, S]

    valid = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1) < len_ref[0]
    scores = jnp.where(valid, scores, -1e30)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    out = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / denom                                          # [G, D]
    o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_gqa_attention(
    q: jnp.ndarray,        # [B, Hq, D] (single decode query per slot)
    cache_k: jnp.ndarray,  # [B, S, Hkv, D]
    cache_v: jnp.ndarray,  # [B, S, Hkv, D]
    lengths: jnp.ndarray,  # [B] int32 — valid prefix per slot (pos + 1)
    interpret: bool = False,
) -> jnp.ndarray:
    """Returns [B, Hq, D] in q.dtype. ``interpret=True`` runs the kernel on
    CPU for tests (pallas interpreter)."""
    B, Hq, D = q.shape
    S, Hkv = cache_k.shape[1], cache_k.shape[2]
    G = Hq // Hkv

    grid = (B, Hkv)
    return pl.pallas_call(
        _decode_attn_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda b, h: (b,), memory_space=_SMEM),
            pl.BlockSpec((1, G, D), lambda b, h: (b, h, 0)),
            pl.BlockSpec((1, S, 1, D), lambda b, h: (b, 0, h, 0)),
            pl.BlockSpec((1, S, 1, D), lambda b, h: (b, 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, G, D), lambda b, h: (b, h, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(lengths, q, cache_k, cache_v)
