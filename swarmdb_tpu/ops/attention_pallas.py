"""Pallas TPU kernels: decode-step GQA attention (dense slot cache and
ragged block-paged cache).

The serving hot path (engine decode chunks) issues attention with ONE query
per slot against that slot's cache lane. The XLA einsum path materializes
fp32 scores [B, Hq, S] in HBM between ops; this kernel keeps each
(batch, kv-head) tile's scores in VMEM: one MXU dot for q·K, masked softmax
in registers, one dot against V — per grid cell the only HBM traffic is the
cache lane itself, which is the unavoidable read.

Layout (grid = (B, Hkv)):
- q block   [1, G, D]   — the G = Hq/Hkv query heads sharing this kv head
- k/v block [1, S, 1, D] — the full cache lane for this (slot, kv head)
- length    [1] in SMEM  — valid prefix length (= q position + 1)

Single-chip path only: under tensor parallelism the cache's head axis is
sharded and this call would force a gather; the engine enables the kernel
when the model is unsharded (see ops/layers.gqa_attention dispatch).

No reference counterpart (the reference has no model code, SURVEY §5.7);
design per /opt/skills/guides/pallas_guide.md and the ragged-paged-attention
pattern noted in PAPERS.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds of jax as well
    from jax.experimental.pallas import tpu as pltpu

    _SMEM = pltpu.SMEM
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _SMEM = _VMEM = None


def _decode_attn_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *,
                        n_kv_heads: int):
    # q_ref [1, Hq, D]; k_ref/v_ref [1, S, Hkv, D]; len_ref [B] (SMEM,
    # whole array — TPU requires rank-1 blocks be full or 128-multiples,
    # so the kernel indexes its row by grid position instead of slicing).
    # One grid cell = one slot, ALL heads: per-kv-head blocks would need a
    # [1, G, D] tile with G < 8, below the TPU sublane minimum.
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = n_kv_heads
    G = Hq // Hkv
    q = q_ref[0].reshape(Hkv, G, D).astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)                   # [S, Hkv, D]
    v = v_ref[0].astype(jnp.float32)
    S = k.shape[0]
    scale = 1.0 / (D**0.5)

    length = len_ref[pl.program_id(0)]
    valid = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1) < length

    # static unroll over kv heads: Mosaic's dot_general needs batch dims in
    # matching positions, so a batched [Hkv, ...] einsum won't lower; Hkv
    # is small (8 for the Llama-3 family) and the unrolled dots pipeline
    outs = []
    for h in range(Hkv):
        scores = jax.lax.dot_general(
            q[h], k[:, h, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                      # [G, S]
        scores = jnp.where(valid, scores, -1e30)
        m = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores - m)
        denom = jnp.sum(p, axis=-1, keepdims=True)
        out = jax.lax.dot_general(
            p, v[:, h, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / denom                                      # [G, D]
        outs.append(out)
    o_ref[0] = jnp.concatenate(outs, axis=0).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_gqa_attention(
    q: jnp.ndarray,        # [B, Hq, D] (single decode query per slot)
    cache_k: jnp.ndarray,  # [B, S, Hkv, D]
    cache_v: jnp.ndarray,  # [B, S, Hkv, D]
    lengths: jnp.ndarray,  # [B] int32 — valid prefix per slot (pos + 1)
    interpret: bool = False,
) -> jnp.ndarray:
    """Returns [B, Hq, D] in q.dtype. ``interpret=True`` runs the kernel on
    CPU for tests (pallas interpreter)."""
    B, Hq, D = q.shape
    S, Hkv = cache_k.shape[1], cache_k.shape[2]

    grid = (B,)
    return pl.pallas_call(
        functools.partial(_decode_attn_kernel, n_kv_heads=Hkv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((B,), lambda b: (0,), memory_space=_SMEM),
            pl.BlockSpec((1, Hq, D), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, S, Hkv, D), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec((1, S, Hkv, D), lambda b: (b, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(lengths, q, cache_k, cache_v)


# ---------------------------------------------------------------------------
# Ragged PAGED decode attention (ops/paged_kv.py pool layout).
#
# Grid (B, maxp) with the page axis innermost; the page TABLE and the
# per-slot lengths ride as scalar-prefetch operands so each grid step's
# BlockSpec index_map can pick the right physical page — the standard TPU
# paged-attention pattern (PrefetchScalarGridSpec). Each iteration loads ONE
# page across ALL kv heads ([1, ps, Hkv, D] — the Hkv axis may not be
# sliced: Mosaic requires the last two block dims be (8, 128)-divisible or
# whole, and a (…, 1, D) per-head block violates the sublane rule) and a
# static unroll over the Hkv heads runs the online softmax per head, exactly
# like the dense kernel above. Two properties give the bandwidth win over
# the XLA gather path:
#   1. dead iterations (j beyond the slot's live pages) remap to the SAME
#      page as the last live step, and Pallas skips the DMA for a block
#      whose indices didn't change — so HBM traffic is ~live pages, not
#      maxp pages;
#   2. scores/softmax state stay in VMEM scratch across the page loop
#      (online softmax), so nothing but the output tile is written back.


def _online_update(h, s, v, acc_ref, m_ref, l_ref):
    """Fold one masked score tile ``s`` [G, Tk] + value tile ``v`` [Tk, D]
    into head ``h``'s running online-softmax state (flash-attention
    rescaling)."""
    m_prev = m_ref[h][:, :1]                           # [G, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)                    # rescale old state
    p = jnp.exp(s - m_new)                             # [G, Tk]
    l_new = l_ref[h][:, :1] * alpha + jnp.sum(p, -1, keepdims=True)
    acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[h] = jnp.broadcast_to(m_new, m_ref[h].shape)
    l_ref[h] = jnp.broadcast_to(l_new, l_ref[h].shape)


def _attend_tile(q_ref, k_tile_ref, v_tile_ref, valid, n_kv_heads,
                 acc_ref, m_ref, l_ref, k_scale=None, v_scale=None):
    """One [Tk]-token KV tile against every head's query: per-kv-head MXU
    dots (a batched einsum won't lower in Mosaic) folded into the online
    softmax scratch. ``valid`` is the [1, Tk] position mask.

    ``k_scale``/``v_scale`` ([Hkv] f32, or None) are the quantized-pool
    page scales: int8 tiles are dequantized HERE, in VMEM, after the
    page's one HBM read — the roofline sees half the bytes and the MXU
    still runs the f32 math (SWARMDB_KV_DTYPE=int8, ISSUE 18)."""
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    G = Hq // n_kv_heads
    q = q_ref[0].reshape(n_kv_heads, G, D).astype(jnp.float32)
    k = k_tile_ref[0].astype(jnp.float32)              # [Tk, Hkv, D]
    v = v_tile_ref[0].astype(jnp.float32)
    if k_scale is not None:
        k = k * k_scale.reshape(1, n_kv_heads, 1)
    if v_scale is not None:
        v = v * v_scale.reshape(1, n_kv_heads, 1)
    scale = 1.0 / (D ** 0.5)
    for h in range(n_kv_heads):
        s = jax.lax.dot_general(
            q[h], k[:, h, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                      # [G, Tk]
        _online_update(h, jnp.where(valid, s, -1e30), v[:, h, :],
                       acc_ref, m_ref, l_ref)


def _paged_attn_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                       acc_ref, m_ref, l_ref, *, page_size: int,
                       n_kv_heads: int, window):
    b = pl.program_id(0)
    j = pl.program_id(1)
    maxp = pl.num_programs(1)
    length = len_ref[b]
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = n_kv_heads

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j * page_size < length)
    def _compute():
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)              # [1, ps] global pos
        valid = pos < length
        if window is not None:
            valid &= pos > (length - 1 - window)
        _attend_tile(q_ref, k_ref, v_ref, valid, Hkv, acc_ref, m_ref, l_ref)

    @pl.when(j == maxp - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :, :1], 1e-30)    # inactive slot: 0/eps
        o_ref[0] = (acc_ref[...] / denom).reshape(Hq, D).astype(o_ref.dtype)


def _paged_chunk_attn_kernel(table_ref, start_ref, step_ref, q_ref, k_ref,
                             v_ref, ck_ref, cv_ref, o_ref, acc_ref, m_ref,
                             l_ref, *, page_size: int, n_kv_heads: int,
                             window):
    """Ragged paged attention + in-chunk segment under ONE online softmax.

    Grid (B, maxp+1): iterations j < maxp stream the slot's live pages
    (the FROZEN prefix, valid strictly below the chunk start); iteration
    j == maxp processes the [Kc] chunk buffer (entries 0..step) and
    finalizes. The page loop's DMA skipping (dead iterations re-point at
    the last live page) is unchanged from `_paged_attn_kernel`.
    """
    b = pl.program_id(0)
    j = pl.program_id(1)
    maxp = pl.num_programs(1) - 1
    start = start_ref[b]              # frozen prefix length = chunk start
    step = step_ref[0]
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = n_kv_heads

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when((j < maxp) & (j * page_size < start))
    def _pages():
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = pos < start
        if window is not None:
            valid &= pos > (start + step - window)
        _attend_tile(q_ref, k_ref, v_ref, valid, Hkv, acc_ref, m_ref, l_ref)

    @pl.when(j == maxp)
    def _chunk():
        Kc = ck_ref.shape[1]
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, Kc), 1)
        valid = idx <= step
        if window is not None:
            valid &= (start + idx) > (start + step - window)
        _attend_tile(q_ref, ck_ref, cv_ref, valid, Hkv, acc_ref, m_ref, l_ref)

        denom = jnp.maximum(l_ref[:, :, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).reshape(Hq, D).astype(o_ref.dtype)


def _last_live_page(n, ps):
    # (n - 1) // ps for n >= 1, clamped to 0 — via truncating lax.div on a
    # guaranteed-nonnegative numerator: jnp's floor ``//`` expands into a
    # sign/rem jaxpr that bloats the scalar-core index_map program
    return jax.lax.div(jax.lax.max(n - 1, 0), jnp.int32(ps))


@functools.partial(jax.jit, static_argnames=("window", "interpret"))
def paged_decode_gqa_attention_chunked(
    q: jnp.ndarray,           # [B, Hq, D] one decode query per slot
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D] FROZEN single-layer pool
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, maxp] int32
    chunk_k: jnp.ndarray,     # [B, Kc, Hkv, D] chunk buffer
    chunk_v: jnp.ndarray,
    starts: jnp.ndarray,      # [B] int32 frozen prefix length (chunk start)
    step: jnp.ndarray,        # scalar int32 current step within the chunk
    window=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Two-segment ragged paged decode attention; returns [B, Hq, D]."""
    B, Hq, D = q.shape
    _, ps, Hkv, _ = k_pages.shape
    maxp = page_table.shape[1]
    G = Hq // Hkv
    table = page_table.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    step_arr = jnp.reshape(step, (1,)).astype(jnp.int32)

    def q_map(b, j, table_ref, start_ref, step_ref):
        return (b, 0, 0)

    def kv_map(b, j, table_ref, start_ref, step_ref):
        # dead/trailing iterations re-point at the last live page so their
        # DMA is skipped; empty prefix -> table[b, 0]
        last_live = _last_live_page(start_ref[b], ps)
        return (table_ref[b, jnp.minimum(j, last_live)], 0, 0, 0)

    def chunk_map(b, j, table_ref, start_ref, step_ref):
        return (b, 0, 0, 0)

    def o_map(b, j, table_ref, start_ref, step_ref):
        return (b, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, maxp + 1),
        in_specs=[
            pl.BlockSpec((1, Hq, D), q_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, chunk_k.shape[1], Hkv, D), chunk_map),
            pl.BlockSpec((1, chunk_k.shape[1], Hkv, D), chunk_map),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), o_map),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, D), jnp.float32),    # acc
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running max (bcast)
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running denom (bcast)
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_chunk_attn_kernel, page_size=ps,
                          n_kv_heads=Hkv, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(table, starts, step_arr, q, k_pages, v_pages, chunk_k, chunk_v)
    return out


# ---------------------------------------------------------------------------
# Ragged paged PREFILL attention (ISSUE 11 tentpole).
#
# One packed token STREAM per admission wave: the engine concatenates the
# wave's rows back to back (no per-row bucket padding) and describes them
# with per-row ``(start, len, prefix_len)`` descriptors that ride as
# scalar-prefetch operands (SMEM). Grid (R, maxp + n_suffix_tiles): grid
# row ``r`` streams row r's PREFIX pages straight out of the page pool via
# the page table (no ``paged_gather_kv`` densification — the dead-iteration
# DMA-skip trick from the decode kernels bounds HBM traffic at live pages),
# then the packed suffix K/V in [tile]-token slices, all folded into one
# online softmax (`_online_update`, the same machinery the decode kernels
# use). Causality inside the stream is POSITIONAL: rows are contiguous, so
# "key index <= query index within the same row" is exactly causal order
# and no per-token position array is needed in the kernel.
#
# v1 keeps the whole packed stream (q, suffix K/V, fp32 accumulators)
# VMEM-resident — right-sized for serving waves up to a few hundred tokens
# at repro-scale models; production-scale head counts want a query-axis
# block loop on top (noted in ROADMAP). Per grid row the kernel computes
# scores for every stream query against that row's KV and discards the
# foreign rows' results at the masked finalize write — wasted MACs scale
# with R, but the HBM story (pages read once, in place) is what the gather
# fallback cannot do.


def _ragged_prefill_kernel(table_ref, starts_ref, lens_ref, plens_ref,
                           q_ref, sk_ref, sv_ref, kp_ref, vp_ref, o_ref,
                           acc_ref, m_ref, l_ref, *, page_size: int,
                           n_kv_heads: int, n_pages: int, tile: int,
                           window):
    r = pl.program_id(0)
    j = pl.program_id(1)
    n_steps = pl.num_programs(1)
    W, Hq, D = q_ref.shape
    Hkv = n_kv_heads
    G = Hq // Hkv
    ps = page_size
    start = starts_ref[r]
    ln = lens_ref[r]
    plen = plens_ref[r]
    scale = 1.0 / (D ** 0.5)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when((r == 0) & (j == 0))
    def _zero_out():
        # the output block is revisited by every grid row (constant index
        # map) and finalized with a masked write per row — positions no
        # row owns (none when the stream is packed dense) stay zero
        o_ref[...] = jnp.zeros_like(o_ref)

    # stream index of each score row (score rows are (w, g) pairs,
    # w-major — matching q.reshape(W, Hkv, G, D))
    wq = jax.lax.div(
        jax.lax.broadcasted_iota(jnp.int32, (W * G, 1), 0), jnp.int32(G))
    q_abs = plen + wq - start    # absolute position of query w IN ROW r

    def fold(k_tile, v_tile, valid):
        # k_tile/v_tile [Tk, Hkv, D]; valid [W*G, Tk]
        q = q_ref[...].reshape(W, Hkv, G, D).astype(jnp.float32)
        k = k_tile.astype(jnp.float32)
        v = v_tile.astype(jnp.float32)
        for h in range(Hkv):
            qh = q[:, h].reshape(W * G, D)
            s = jax.lax.dot_general(
                qh, k[:, h, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                  # [W*G, Tk]
            _online_update(h, jnp.where(valid, s, -1e30), v[:, h, :],
                           acc_ref, m_ref, l_ref)

    @pl.when((j < n_pages) & (j * ps < plen))
    def _prefix():
        kpos = j * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps), 1)
        valid = kpos < plen
        if window is not None:
            valid &= kpos > (q_abs - window)
        fold(kp_ref[0], vp_ref[0], jnp.broadcast_to(valid, (W * G, ps)))

    @pl.when((j >= n_pages) & (ln > 0))
    def _suffix():
        t = j - n_pages
        first = jax.lax.div(start, jnp.int32(tile))
        last = jax.lax.div(start + ln - 1, jnp.int32(tile))
        tt = first + t

        @pl.when(tt <= last)
        def _live():
            # dynamic [tile]-slice of the resident packed K/V; the slice
            # start clamps to W - tile, so the anti-overlap term
            # (x >= tt*tile) keeps a clamped tail tile from re-folding
            # keys the previous tile already saw
            s0 = jnp.minimum(tt * tile, jnp.int32(W - tile))
            x = s0 + jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
            valid = ((x >= tt * tile) & (x >= start) & (x < start + ln)
                     & (x <= wq))
            if window is not None:
                valid &= x > (wq - window)
            fold(sk_ref[pl.ds(s0, tile)], sv_ref[pl.ds(s0, tile)], valid)

    @pl.when(j == n_steps - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :, :1], 1e-30)    # [Hkv, W*G, 1]
        out = (acc_ref[...] / denom).reshape(Hkv, W, G, D)
        out = out.transpose(1, 0, 2, 3).reshape(W, Hq, D)
        w_iota = jax.lax.broadcasted_iota(jnp.int32, (W, 1, 1), 0)
        mine = (w_iota >= start) & (w_iota < start + ln)
        o_ref[...] = jnp.where(mine, out.astype(o_ref.dtype), o_ref[...])


@functools.partial(jax.jit, static_argnames=("window", "tile", "interpret"))
def ragged_paged_prefill_attention(
    q: jnp.ndarray,           # [W, Hq, D] packed query stream
    sfx_k: jnp.ndarray,       # [W, Hkv, D] packed suffix K (this wave's)
    sfx_v: jnp.ndarray,
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D] single-layer page pool
    v_pages: jnp.ndarray,
    row_tables: jnp.ndarray,  # [R, maxp] int32 page ids per wave row
    starts: jnp.ndarray,      # [R] int32 — row r's offset in the stream
    lens: jnp.ndarray,        # [R] int32 — row r's token count (0 = dead)
    prefix_lens: jnp.ndarray,  # [R] int32 — tokens already in r's pages
    window=None,
    tile: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged paged prefill attention over a packed wave; returns
    [W, Hq, D] in q.dtype (positions outside every row are zero)."""
    W, Hq, D = q.shape
    _, ps, Hkv, _ = k_pages.shape
    R, maxp = row_tables.shape
    G = Hq // Hkv
    Tk = min(tile, W)
    n_st = -(-W // Tk)
    table = row_tables.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    lens = lens.astype(jnp.int32)
    plens = prefix_lens.astype(jnp.int32)

    def stream_map(r, j, table_ref, starts_ref, lens_ref, plens_ref):
        return (0, 0, 0)

    def kv_map(r, j, table_ref, starts_ref, lens_ref, plens_ref):
        # dead page iterations AND every suffix-tile iteration re-point at
        # the last live prefix page, so their DMA is skipped; empty prefix
        # -> table[r, 0] (trash page 0 for fresh rows)
        last_live = _last_live_page(plens_ref[r], ps)
        return (table_ref[r, jnp.minimum(j, last_live)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(R, maxp + n_st),
        in_specs=[
            pl.BlockSpec((W, Hq, D), stream_map),
            pl.BlockSpec((W, Hkv, D), stream_map),
            pl.BlockSpec((W, Hkv, D), stream_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
        ],
        # swarmlint: revisit[r] -- every (r, j) step accumulates into the
        # one stream-resident output block; the masked finalize under
        # pl.when(j == n_steps - 1) writes each row's lanes exactly once
        out_specs=pl.BlockSpec((W, Hq, D), stream_map),
        scratch_shapes=[
            pltpu.VMEM((Hkv, W * G, D), jnp.float32),    # acc
            pltpu.VMEM((Hkv, W * G, 128), jnp.float32),  # running max
            pltpu.VMEM((Hkv, W * G, 128), jnp.float32),  # running denom
        ],
    )
    return pl.pallas_call(
        functools.partial(_ragged_prefill_kernel, page_size=ps,
                          n_kv_heads=Hkv, n_pages=maxp, tile=Tk,
                          window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((W, Hq, D), q.dtype),
        interpret=interpret,
    )(table, starts, lens, plens, q, sfx_k, sfx_v, k_pages, v_pages)


def _dense_chunk_attn_kernel(start_ref, step_ref, q_ref, k_ref, v_ref,
                             ck_ref, cv_ref, o_ref, acc_ref, m_ref, l_ref,
                             *, tile: int, n_kv_heads: int, window):
    """Dense two-segment decode attention (the serve-bench hot path):
    stream the FROZEN slot cache in [tile]-token blocks, then fold the
    in-chunk buffer, all under one online softmax. Mirrors
    `_paged_chunk_attn_kernel` with the page table replaced by the slot's
    own contiguous lane; dead tiles (>= the slot's chunk start) re-point
    at the last live tile so their DMA is skipped — HBM traffic scales
    with each slot's LIVE prefix, which the XLA einsum path (always a
    full [S] read + materialized fp32 scores) cannot do.
    """
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_tiles = pl.num_programs(1) - 1
    start = start_ref[b]              # frozen prefix length = chunk start
    step = step_ref[0]
    Hkv = n_kv_heads

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when((j < n_tiles) & (j * tile < start))
    def _cache():
        pos = j * tile + jax.lax.broadcasted_iota(
            jnp.int32, (1, tile), 1)
        valid = pos < start
        if window is not None:
            valid &= pos > (start + step - window)
        _attend_tile(q_ref, k_ref, v_ref, valid, Hkv, acc_ref, m_ref, l_ref)

    @pl.when(j == n_tiles)
    def _chunk():
        Kc = ck_ref.shape[1]
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, Kc), 1)
        valid = idx <= step
        if window is not None:
            valid &= (start + idx) > (start + step - window)
        _attend_tile(q_ref, ck_ref, cv_ref, valid, Hkv, acc_ref, m_ref,
                     l_ref)

        denom = jnp.maximum(l_ref[:, :, :1], 1e-30)
        Hq, D = q_ref.shape[1], q_ref.shape[2]
        o_ref[0] = (acc_ref[...] / denom).reshape(Hq, D).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("window", "tile", "interpret"))
def decode_gqa_attention_chunked(
    q: jnp.ndarray,          # [B, Hq, D] one decode query per slot
    cache_k: jnp.ndarray,    # [B, S, Hkv, D] FROZEN slot cache
    cache_v: jnp.ndarray,
    chunk_k: jnp.ndarray,    # [B, Kc, Hkv, D] this chunk's K so far
    chunk_v: jnp.ndarray,
    starts: jnp.ndarray,     # [B] int32 frozen prefix length (chunk start)
    step: jnp.ndarray,       # scalar int32 current step within the chunk
    window=None,
    tile: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """Dense two-segment decode attention; returns [B, Hq, D] in q.dtype.
    Requires S % tile == 0 (the dispatch in ops/layers.py checks)."""
    B, Hq, D = q.shape
    S, Hkv = cache_k.shape[1], cache_k.shape[2]
    G = Hq // Hkv
    n_tiles = S // tile
    starts = starts.astype(jnp.int32)
    step_arr = jnp.reshape(step, (1,)).astype(jnp.int32)

    def q_map(b, j, start_ref, step_ref):
        return (b, 0, 0)

    def kv_map(b, j, start_ref, step_ref):
        last_live = _last_live_page(start_ref[b], tile)
        return (b, jnp.minimum(j, last_live), 0, 0)

    def chunk_map(b, j, start_ref, step_ref):
        return (b, 0, 0, 0)

    def o_map(b, j, start_ref, step_ref):
        return (b, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_tiles + 1),
        in_specs=[
            pl.BlockSpec((1, Hq, D), q_map),
            pl.BlockSpec((1, tile, Hkv, D), kv_map),
            pl.BlockSpec((1, tile, Hkv, D), kv_map),
            pl.BlockSpec((1, chunk_k.shape[1], Hkv, D), chunk_map),
            pl.BlockSpec((1, chunk_k.shape[1], Hkv, D), chunk_map),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), o_map),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, D), jnp.float32),    # acc
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running max (bcast)
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running denom (bcast)
        ],
    )
    out = pl.pallas_call(
        functools.partial(_dense_chunk_attn_kernel, tile=tile,
                          n_kv_heads=Hkv, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(starts, step_arr, q, cache_k, cache_v, chunk_k, chunk_v)
    return out


@functools.partial(
    jax.jit, static_argnames=("window", "interpret")
)
def paged_decode_gqa_attention(
    q: jnp.ndarray,           # [B, Hq, D] one decode query per slot
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D] single-layer page pool
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, maxp] int32
    lengths: jnp.ndarray,     # [B] int32 valid prefix (q position + 1)
    window=None,              # sliding-window size (None = full causal)
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged paged decode attention; returns [B, Hq, D] in q.dtype."""
    B, Hq, D = q.shape
    _, ps, Hkv, _ = k_pages.shape
    maxp = page_table.shape[1]
    G = Hq // Hkv
    table = page_table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    def q_map(b, j, table_ref, len_ref):
        return (b, 0, 0)

    def kv_map(b, j, table_ref, len_ref):
        # dead iterations re-point at the last live page so their DMA is
        # skipped (same indices as the previous step); length 0 -> trash 0
        last_live = _last_live_page(len_ref[b], ps)
        return (table_ref[b, jnp.minimum(j, last_live)], 0, 0, 0)

    def o_map(b, j, table_ref, len_ref):
        return (b, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, maxp),
        in_specs=[
            pl.BlockSpec((1, Hq, D), q_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), o_map),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, D), jnp.float32),    # acc
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running max (bcast)
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running denom (bcast)
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, page_size=ps, n_kv_heads=Hkv,
                          window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(table, lengths, q, k_pages, v_pages)
    return out


# ---------------------------------------------------------------------------
# Quantized-pool kernel variants (SWARMDB_KV_DTYPE=int8, ISSUE 18).
#
# Same grids, same online softmax, same DMA-skip index maps as the three
# kernels above — the ONLY difference is the KV operands: int8 page
# payloads plus a per-page-per-head f32 scale operand shaped [P, 1, Hkv]
# (block (1, 1, Hkv), whole in its last two dims — Mosaic-legal — and
# indexed by the SAME page map as the payload, so a page's scale row
# rides the page's DMA step). Dequantization happens inside
# `_attend_tile` in VMEM: HBM sees half the bytes, the MXU still runs
# f32. Suffix streams and in-chunk buffers stay full precision — only
# what lives in the POOL is quantized.


def _paged_attn_kernel_quant(table_ref, len_ref, q_ref, k_ref, ks_ref,
                             v_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref,
                             *, page_size: int, n_kv_heads: int, window):
    b = pl.program_id(0)
    j = pl.program_id(1)
    maxp = pl.num_programs(1)
    length = len_ref[b]
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = n_kv_heads

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j * page_size < length)
    def _compute():
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = pos < length
        if window is not None:
            valid &= pos > (length - 1 - window)
        _attend_tile(q_ref, k_ref, v_ref, valid, Hkv, acc_ref, m_ref,
                     l_ref, k_scale=ks_ref[...], v_scale=vs_ref[...])

    @pl.when(j == maxp - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).reshape(Hq, D).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("window", "interpret"))
def paged_decode_gqa_attention_quant(
    q: jnp.ndarray,           # [B, Hq, D] one decode query per slot
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D] int8 single-layer pool
    k_scale: jnp.ndarray,     # [P, Hkv] f32 per-page-per-head scales
    v_pages: jnp.ndarray,
    v_scale: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, maxp] int32
    lengths: jnp.ndarray,     # [B] int32 valid prefix (q position + 1)
    window=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Quantized ragged paged decode attention; returns [B, Hq, D]."""
    B, Hq, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    maxp = page_table.shape[1]
    G = Hq // Hkv
    table = page_table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)
    ks3 = k_scale.reshape(P, 1, Hkv)
    vs3 = v_scale.reshape(P, 1, Hkv)

    def q_map(b, j, table_ref, len_ref):
        return (b, 0, 0)

    def kv_map(b, j, table_ref, len_ref):
        last_live = _last_live_page(len_ref[b], ps)
        return (table_ref[b, jnp.minimum(j, last_live)], 0, 0, 0)

    def sc_map(b, j, table_ref, len_ref):
        last_live = _last_live_page(len_ref[b], ps)
        return (table_ref[b, jnp.minimum(j, last_live)], 0, 0)

    def o_map(b, j, table_ref, len_ref):
        return (b, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, maxp),
        in_specs=[
            pl.BlockSpec((1, Hq, D), q_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, 1, Hkv), sc_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, 1, Hkv), sc_map),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), o_map),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, D), jnp.float32),    # acc
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running max (bcast)
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running denom (bcast)
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel_quant, page_size=ps,
                          n_kv_heads=Hkv, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(table, lengths, q, k_pages, ks3, v_pages, vs3)
    return out


def _paged_chunk_attn_kernel_quant(table_ref, start_ref, step_ref, q_ref,
                                   k_ref, ks_ref, v_ref, vs_ref, ck_ref,
                                   cv_ref, o_ref, acc_ref, m_ref, l_ref,
                                   *, page_size: int, n_kv_heads: int,
                                   window):
    """Quantized two-segment decode: int8 pages dequantize per tile, the
    in-chunk buffer (never pool-resident) stays full precision."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    maxp = pl.num_programs(1) - 1
    start = start_ref[b]
    step = step_ref[0]
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = n_kv_heads

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when((j < maxp) & (j * page_size < start))
    def _pages():
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = pos < start
        if window is not None:
            valid &= pos > (start + step - window)
        _attend_tile(q_ref, k_ref, v_ref, valid, Hkv, acc_ref, m_ref,
                     l_ref, k_scale=ks_ref[...], v_scale=vs_ref[...])

    @pl.when(j == maxp)
    def _chunk():
        Kc = ck_ref.shape[1]
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, Kc), 1)
        valid = idx <= step
        if window is not None:
            valid &= (start + idx) > (start + step - window)
        _attend_tile(q_ref, ck_ref, cv_ref, valid, Hkv, acc_ref, m_ref,
                     l_ref)

        denom = jnp.maximum(l_ref[:, :, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).reshape(Hq, D).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("window", "interpret"))
def paged_decode_gqa_attention_chunked_quant(
    q: jnp.ndarray,           # [B, Hq, D]
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D] int8 FROZEN pool
    k_scale: jnp.ndarray,     # [P, Hkv] f32
    v_pages: jnp.ndarray,
    v_scale: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, maxp] int32
    chunk_k: jnp.ndarray,     # [B, Kc, Hkv, D] full-precision chunk buffer
    chunk_v: jnp.ndarray,
    starts: jnp.ndarray,      # [B] int32 frozen prefix length
    step: jnp.ndarray,        # scalar int32 step within the chunk
    window=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Quantized two-segment ragged paged decode; returns [B, Hq, D]."""
    B, Hq, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    maxp = page_table.shape[1]
    G = Hq // Hkv
    table = page_table.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    step_arr = jnp.reshape(step, (1,)).astype(jnp.int32)
    ks3 = k_scale.reshape(P, 1, Hkv)
    vs3 = v_scale.reshape(P, 1, Hkv)

    def q_map(b, j, table_ref, start_ref, step_ref):
        return (b, 0, 0)

    def kv_map(b, j, table_ref, start_ref, step_ref):
        last_live = _last_live_page(start_ref[b], ps)
        return (table_ref[b, jnp.minimum(j, last_live)], 0, 0, 0)

    def sc_map(b, j, table_ref, start_ref, step_ref):
        last_live = _last_live_page(start_ref[b], ps)
        return (table_ref[b, jnp.minimum(j, last_live)], 0, 0)

    def chunk_map(b, j, table_ref, start_ref, step_ref):
        return (b, 0, 0, 0)

    def o_map(b, j, table_ref, start_ref, step_ref):
        return (b, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, maxp + 1),
        in_specs=[
            pl.BlockSpec((1, Hq, D), q_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, 1, Hkv), sc_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, 1, Hkv), sc_map),
            pl.BlockSpec((1, chunk_k.shape[1], Hkv, D), chunk_map),
            pl.BlockSpec((1, chunk_k.shape[1], Hkv, D), chunk_map),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), o_map),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, D), jnp.float32),    # acc
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running max (bcast)
            pltpu.VMEM((Hkv, G, 128), jnp.float32),  # running denom (bcast)
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_chunk_attn_kernel_quant, page_size=ps,
                          n_kv_heads=Hkv, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(table, starts, step_arr, q, k_pages, ks3, v_pages, vs3,
      chunk_k, chunk_v)
    return out


def _ragged_prefill_kernel_quant(table_ref, starts_ref, lens_ref,
                                 plens_ref, q_ref, sk_ref, sv_ref, kp_ref,
                                 kps_ref, vp_ref, vps_ref, o_ref, acc_ref,
                                 m_ref, l_ref, *, page_size: int,
                                 n_kv_heads: int, n_pages: int, tile: int,
                                 window):
    """Quantized ragged prefill: int8 PREFIX pages dequantize per page
    tile; the packed suffix stream (this wave's own K/V, not yet
    pool-resident) stays full precision."""
    r = pl.program_id(0)
    j = pl.program_id(1)
    n_steps = pl.num_programs(1)
    W, Hq, D = q_ref.shape
    Hkv = n_kv_heads
    G = Hq // Hkv
    ps = page_size
    start = starts_ref[r]
    ln = lens_ref[r]
    plen = plens_ref[r]
    scale = 1.0 / (D ** 0.5)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when((r == 0) & (j == 0))
    def _zero_out():
        o_ref[...] = jnp.zeros_like(o_ref)

    wq = jax.lax.div(
        jax.lax.broadcasted_iota(jnp.int32, (W * G, 1), 0), jnp.int32(G))
    q_abs = plen + wq - start

    def fold(k_tile, v_tile, valid):
        q = q_ref[...].reshape(W, Hkv, G, D).astype(jnp.float32)
        k = k_tile.astype(jnp.float32)
        v = v_tile.astype(jnp.float32)
        for h in range(Hkv):
            qh = q[:, h].reshape(W * G, D)
            s = jax.lax.dot_general(
                qh, k[:, h, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            _online_update(h, jnp.where(valid, s, -1e30), v[:, h, :],
                           acc_ref, m_ref, l_ref)

    @pl.when((j < n_pages) & (j * ps < plen))
    def _prefix():
        kpos = j * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps), 1)
        valid = kpos < plen
        if window is not None:
            valid &= kpos > (q_abs - window)
        kd = kp_ref[0].astype(jnp.float32) * kps_ref[...].reshape(1, Hkv, 1)
        vd = vp_ref[0].astype(jnp.float32) * vps_ref[...].reshape(1, Hkv, 1)
        fold(kd, vd, jnp.broadcast_to(valid, (W * G, ps)))

    @pl.when((j >= n_pages) & (ln > 0))
    def _suffix():
        t = j - n_pages
        first = jax.lax.div(start, jnp.int32(tile))
        last = jax.lax.div(start + ln - 1, jnp.int32(tile))
        tt = first + t

        @pl.when(tt <= last)
        def _live():
            s0 = jnp.minimum(tt * tile, jnp.int32(W - tile))
            x = s0 + jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
            valid = ((x >= tt * tile) & (x >= start) & (x < start + ln)
                     & (x <= wq))
            if window is not None:
                valid &= x > (wq - window)
            fold(sk_ref[pl.ds(s0, tile)], sv_ref[pl.ds(s0, tile)], valid)

    @pl.when(j == n_steps - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :, :1], 1e-30)
        out = (acc_ref[...] / denom).reshape(Hkv, W, G, D)
        out = out.transpose(1, 0, 2, 3).reshape(W, Hq, D)
        w_iota = jax.lax.broadcasted_iota(jnp.int32, (W, 1, 1), 0)
        mine = (w_iota >= start) & (w_iota < start + ln)
        o_ref[...] = jnp.where(mine, out.astype(o_ref.dtype), o_ref[...])


@functools.partial(jax.jit, static_argnames=("window", "tile", "interpret"))
def ragged_paged_prefill_attention_quant(
    q: jnp.ndarray,           # [W, Hq, D] packed query stream
    sfx_k: jnp.ndarray,       # [W, Hkv, D] packed suffix K (full precision)
    sfx_v: jnp.ndarray,
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D] int8 single-layer pool
    k_scale: jnp.ndarray,     # [P, Hkv] f32
    v_pages: jnp.ndarray,
    v_scale: jnp.ndarray,
    row_tables: jnp.ndarray,  # [R, maxp] int32 page ids per wave row
    starts: jnp.ndarray,      # [R] int32 — row r's offset in the stream
    lens: jnp.ndarray,        # [R] int32 — row r's token count (0 = dead)
    prefix_lens: jnp.ndarray,  # [R] int32 — tokens already in r's pages
    window=None,
    tile: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Quantized ragged paged prefill attention; returns [W, Hq, D]."""
    W, Hq, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    R, maxp = row_tables.shape
    G = Hq // Hkv
    Tk = min(tile, W)
    n_st = -(-W // Tk)
    table = row_tables.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    lens = lens.astype(jnp.int32)
    plens = prefix_lens.astype(jnp.int32)
    ks3 = k_scale.reshape(P, 1, Hkv)
    vs3 = v_scale.reshape(P, 1, Hkv)

    def stream_map(r, j, table_ref, starts_ref, lens_ref, plens_ref):
        return (0, 0, 0)

    def kv_map(r, j, table_ref, starts_ref, lens_ref, plens_ref):
        last_live = _last_live_page(plens_ref[r], ps)
        return (table_ref[r, jnp.minimum(j, last_live)], 0, 0, 0)

    def sc_map(r, j, table_ref, starts_ref, lens_ref, plens_ref):
        last_live = _last_live_page(plens_ref[r], ps)
        return (table_ref[r, jnp.minimum(j, last_live)], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(R, maxp + n_st),
        in_specs=[
            pl.BlockSpec((W, Hq, D), stream_map),
            pl.BlockSpec((W, Hkv, D), stream_map),
            pl.BlockSpec((W, Hkv, D), stream_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, 1, Hkv), sc_map),
            pl.BlockSpec((1, ps, Hkv, D), kv_map),
            pl.BlockSpec((1, 1, Hkv), sc_map),
        ],
        # swarmlint: revisit[r] -- every (r, j) step accumulates into the
        # one stream-resident output block; the masked finalize under
        # pl.when(j == n_steps - 1) writes each row's lanes exactly once
        out_specs=pl.BlockSpec((W, Hq, D), stream_map),
        scratch_shapes=[
            pltpu.VMEM((Hkv, W * G, D), jnp.float32),    # acc
            pltpu.VMEM((Hkv, W * G, 128), jnp.float32),  # running max
            pltpu.VMEM((Hkv, W * G, 128), jnp.float32),  # running denom
        ],
    )
    return pl.pallas_call(
        functools.partial(_ragged_prefill_kernel_quant, page_size=ps,
                          n_kv_heads=Hkv, n_pages=maxp, tile=Tk,
                          window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((W, Hq, D), q.dtype),
        interpret=interpret,
    )(table, starts, lens, plens, q, sfx_k, sfx_v,
      k_pages, ks3, v_pages, vs3)
