"""Ring attention — causal attention with K/V sharded over a mesh axis.

Long-context prefill support (SURVEY §5.7 notes the reference has nothing
here; the north-star build treats long-sequence handling as first-class):
a prompt longer than one chip's HBM/VMEM budget is sharded over a ``seq``
mesh axis; each device holds one Q/K/V chunk and K/V chunks rotate around
the ring with ``lax.ppermute`` while attention accumulates online
(flash-style running max / denominator), so no device ever materializes
the full [T, T] score matrix or the full K/V.

Written for use inside ``shard_map`` (see ``models/llama.py
forward_seq_parallel``): all collectives are XLA ``ppermute`` steps that
ride ICI neighbor links — total traffic per device is exactly one K/V
rotation around the ring, the canonical overlap-friendly pattern.

Causality is by GLOBAL position: each chunk carries its absolute
positions, so the mask is exact regardless of how chunks are laid out.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _block_attend(
    q: jnp.ndarray,        # [B, Tq, Hq, D] model dtype (bf16 on TPU)
    k: jnp.ndarray,        # [B, Tk, Hkv, D]
    v: jnp.ndarray,        # [B, Tk, Hkv, D]
    q_pos: jnp.ndarray,    # [B, Tq]
    kv_pos: jnp.ndarray,   # [B, Tk]
    window=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One block of masked attention: returns (scores-exp sum `l`,
    running max `m`, weighted values `o`) for online-softmax merging.

    Matmuls take the operands in their native dtype with fp32
    accumulation (the MXU fast path); softmax state is fp32 throughout.
    """
    B, Tq, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv
    qg = q.reshape(B, Tq, Hkv, group, D)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(D))
    causal = kv_pos[:, None, :] <= q_pos[:, :, None]  # [B,Tq,Tk]
    if window is not None:
        causal &= kv_pos[:, None, :] > (q_pos[:, :, None] - window)
    mask = causal[:, None, None]                      # [B,1,1,Tq,Tk]
    scores = jnp.where(mask, scores, -jnp.inf)

    m = jnp.max(scores, axis=-1)                      # [B,Hkv,G,Tq]
    # fully-masked rows (no valid kv in this block) must not produce NaNs
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)                           # [B,Hkv,G,Tq]
    o = jnp.einsum("bkgts,bskd->bkgtd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, l, jnp.where(jnp.isfinite(m), m, -jnp.inf)


def ring_attention(
    q: jnp.ndarray,        # [B, Tq, Hq, D] local query chunk
    k: jnp.ndarray,        # [B, Tk, Hkv, D] local key chunk
    v: jnp.ndarray,        # [B, Tk, Hkv, D] local value chunk
    q_pos: jnp.ndarray,    # [B, Tq] global positions of the local queries
    kv_pos: jnp.ndarray,   # [B, Tk] global positions of the local keys
    axis_name: str,
    window=None,           # sliding-window size (None = full causal)
) -> jnp.ndarray:
    """Causal GQA attention across a ring of devices (call under shard_map
    with ``axis_name`` bound). Returns [B, Tq, Hq, D] in q.dtype."""
    axis_size = jax.lax.psum(1, axis_name)
    B, Tq, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge(acc, block):
        o, l, m = acc
        o_b, l_b, m_b = block
        m_new = jnp.maximum(m, m_b)
        m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        s_old = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new_safe), 0.0)
        s_blk = jnp.where(jnp.isfinite(m_b), jnp.exp(m_b - m_new_safe), 0.0)
        return (
            o * s_old[..., None] + o_b * s_blk[..., None],
            l * s_old + l_b * s_blk,
            m_new,
        )

    def attend(k_cur, v_cur, pos_cur, acc):
        return merge(acc, _block_attend(
            q, k_cur, v_cur, q_pos, pos_cur, window=window,
        ))

    def step(carry, _):
        k_cur, v_cur, pos_cur, *acc = carry
        acc = attend(k_cur, v_cur, pos_cur, tuple(acc))
        # rotate K/V (+ their positions) one step around the ring
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        p_nxt = jax.lax.ppermute(pos_cur, axis_name, perm)
        return (k_nxt, v_nxt, p_nxt, *acc), None

    o0 = jnp.zeros((B, Hkv, group, Tq, D), jnp.float32)
    l0 = jnp.zeros((B, Hkv, group, Tq), jnp.float32)
    m0 = jnp.full((B, Hkv, group, Tq), -jnp.inf, jnp.float32)
    # axis_size - 1 rotations suffice: the last block is attended WITHOUT
    # rotating, since a final ppermute would only return chunks home
    (k_l, v_l, pos_l, *acc), _ = jax.lax.scan(
        step, (k, v, kv_pos, o0, l0, m0), None, length=axis_size - 1
    )
    o, l, _ = attend(k_l, v_l, pos_l, tuple(acc))
    out = o / jnp.maximum(l, 1e-30)[..., None]          # [B,Hkv,G,Tq,D]
    out = jnp.moveaxis(out, 3, 1).reshape(B, Tq, Hq, D)
    return out.astype(q.dtype)
