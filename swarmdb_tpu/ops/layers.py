"""Shared transformer building blocks (JAX, TPU-first).

Functional ops used by the Llama and Mixtral families: RMSNorm, rotary
embeddings, grouped-query attention over a slot-based KV cache, SwiGLU.
No reference counterpart (the reference has no model code, SURVEY §2.4/§5.7).

TPU notes:
- matmuls/einsums stay bf16 (MXU native); normalization statistics and
  softmax run in fp32 for stability, logits are returned fp32.
- all shapes are static under jit; the KV cache is a fixed [B, S, ...] slot
  buffer and validity is expressed by masking, never by dynamic shapes.
- attention is plain einsum + masked softmax: XLA fuses this well on TPU.
  (A Pallas ragged/paged decode kernel is the planned replacement on the
  serving hot path once it lands in ``ops/``.)
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


import contextlib
import threading

_pallas_ctx = threading.local()


@contextlib.contextmanager
def pallas_disabled():
    """Trace-time override: sharded (TP) forwards wrap their model call in
    this so SWARMDB_PALLAS=1 cannot route a head-sharded KV cache through
    pallas_call, which has no partitioning rule and would force a gather
    of the whole cache every step (parallel/serving.py)."""
    prev = getattr(_pallas_ctx, "disabled", False)
    _pallas_ctx.disabled = True
    try:
        yield
    finally:
        _pallas_ctx.disabled = prev


def _pallas_decode_enabled() -> bool:
    """SWARMDB_PALLAS=1 routes single-token decode attention through the
    Pallas kernel (ops/attention_pallas.py); 0/unset keeps the XLA einsum
    path. Checked at trace time (static under jit)."""
    if getattr(_pallas_ctx, "disabled", False):
        return False
    return os.environ.get("SWARMDB_PALLAS", "0") == "1"


def _paged_pallas_enabled() -> bool:
    """The ragged paged kernel DEFAULTS ON for TPU (it is the point of the
    paged cache: HBM reads ∝ live pages); SWARMDB_PALLAS=0 forces the XLA
    gather fallback, =1 forces the kernel even off-TPU (interpret mode —
    slow, for tests)."""
    if getattr(_pallas_ctx, "disabled", False):
        return False
    env = os.environ.get("SWARMDB_PALLAS", "")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def paged_attention_dispatch(
    q: jnp.ndarray,          # [B, 1, Hq, D] (decode only)
    k_pages: jnp.ndarray,    # [P, ps, Hkv, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, maxp]
    q_positions: jnp.ndarray,  # [B, 1]
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Decode attention over the paged pool: ragged Pallas kernel on TPU,
    XLA page-gather fallback elsewhere. Returns [B, 1, Hq, D]."""
    if _paged_pallas_enabled():
        from .attention_pallas import paged_decode_gqa_attention

        lengths = (q_positions[:, 0] + 1).astype(jnp.int32)
        out = paged_decode_gqa_attention(
            q[:, 0], k_pages, v_pages, page_table, lengths,
            window=window, interpret=jax.default_backend() != "tpu",
        )
        return out[:, None]
    from .paged_kv import paged_gather_kv

    kg, vg = paged_gather_kv(k_pages, v_pages, page_table)
    return gqa_attention(q, kg, vg, q_positions, window=window)


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMSNorm with fp32 statistics, output in x.dtype."""
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(x.dtype) * weight


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies [head_dim/2], fp32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def rope_cos_sin(
    positions: jnp.ndarray, head_dim: int, theta: float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Precompute RoPE rotation terms for a batch of positions.

    Returns (cos, sin), each [B, T, 1, D/2] fp32. Depends only on positions,
    so callers compute it ONCE per forward and reuse it across every layer —
    inside a scanned layer body XLA cannot hoist the transcendentals itself.
    """
    inv_freq = rope_frequencies(head_dim, theta)  # [D/2]
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, T, D/2]
    return jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotary position embedding with precomputed terms (`rope_cos_sin`).

    x: [B, T, H, D]. Pairs (x[..., :D/2], x[..., D/2:]) are rotated — the
    "split-half" convention used by HF Llama, so checkpoints interoperate.
    """
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jnp.ndarray, w_gate: jnp.ndarray, w_up: jnp.ndarray,
           w_down: jnp.ndarray) -> jnp.ndarray:
    """SwiGLU MLP: silu(x @ gate) * (x @ up) @ down."""
    g = jax.nn.silu(jnp.einsum("btd,df->btf", x, w_gate))
    u = jnp.einsum("btd,df->btf", x, w_up)
    return jnp.einsum("btf,fd->btd", g * u, w_down)


def write_kv_cache(
    cache_k: jnp.ndarray,  # [B, S, Hkv, D]
    cache_v: jnp.ndarray,
    k: jnp.ndarray,  # [B, T, Hkv, D]
    v: jnp.ndarray,
    positions: jnp.ndarray,  # [B, T]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter new K/V into per-slot cache rows at absolute positions.

    Positions may differ per batch row (continuous batching: each slot is at
    its own decode offset). Compiles to a scatter; shapes stay static.
    """
    b_idx = jnp.arange(cache_k.shape[0])[:, None]  # [B, 1]
    cache_k = cache_k.at[b_idx, positions].set(k.astype(cache_k.dtype))
    cache_v = cache_v.at[b_idx, positions].set(v.astype(cache_v.dtype))
    return cache_k, cache_v


def gqa_attention(
    q: jnp.ndarray,          # [B, T, Hq, D]
    cache_k: jnp.ndarray,    # [B, S, Hkv, D]
    cache_v: jnp.ndarray,    # [B, S, Hkv, D]
    q_positions: jnp.ndarray,  # [B, T] absolute position of each query
    *,
    window: Optional[int] = None,  # sliding-window size (None = full causal)
) -> jnp.ndarray:
    """Grouped-query attention against the full cache buffer with causal
    masking by absolute position.

    Validity invariant: a cache slot is filled monotonically from position 0,
    so every cache entry at position s <= q_position is live for that row.
    Returns [B, T, Hq, D] in q.dtype; softmax in fp32.
    """
    B, S = cache_k.shape[0], cache_k.shape[1]
    Hq, Hkv = q.shape[2], cache_k.shape[2]
    group = Hq // Hkv

    if q.shape[1] == 1 and window is None and _pallas_decode_enabled():
        from .attention_pallas import decode_gqa_attention

        out = decode_gqa_attention(
            q[:, 0],
            cache_k,
            cache_v,
            (q_positions[:, 0] + 1).astype(jnp.int32),
            interpret=jax.default_backend() != "tpu",
        )
        return out[:, None]

    qf = q.astype(jnp.float32)
    kf = cache_k.astype(jnp.float32)
    vf = cache_v.astype(jnp.float32)

    # [B, T, Hkv, group, D] x [B, S, Hkv, D] -> [B, Hkv, group, T, S]
    qg = qf.reshape(B, q.shape[1], Hkv, group, -1)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, kf)
    scores = scores / jnp.sqrt(jnp.float32(q.shape[-1]))

    kv_pos = jnp.arange(S)[None, None, :]                # [1, 1, S]
    causal = kv_pos <= q_positions[:, :, None]           # [B, T, S]
    if window is not None:
        causal &= kv_pos > (q_positions[:, :, None] - window)
    mask = causal[:, None, None, :, :]                   # [B, 1, 1, T, S]
    scores = jnp.where(mask, scores, jnp.float32(-1e30))

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, vf)
    return out.reshape(q.shape).astype(q.dtype)
