"""Shared transformer building blocks (JAX, TPU-first).

Functional ops used by the Llama and Mixtral families: RMSNorm, rotary
embeddings, grouped-query attention over a slot-based KV cache, SwiGLU.
No reference counterpart (the reference has no model code, SURVEY §2.4/§5.7).

TPU notes:
- matmuls/einsums stay bf16 (MXU native); normalization statistics and
  softmax run in fp32 for stability, logits are returned fp32.
- all shapes are static under jit; the KV cache is a fixed [B, S, ...] slot
  buffer and validity is expressed by masking, never by dynamic shapes.
- attention is plain einsum + masked softmax: XLA fuses this well on TPU.
  (A Pallas ragged/paged decode kernel is the planned replacement on the
  serving hot path once it lands in ``ops/``.)
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


import contextlib
import threading

_pallas_ctx = threading.local()


@contextlib.contextmanager
def pallas_disabled():
    """Trace-time override: sharded (TP) forwards wrap their model call in
    this so SWARMDB_PALLAS=1 cannot route a head-sharded KV cache through
    pallas_call, which has no partitioning rule and would force a gather
    of the whole cache every step (parallel/serving.py)."""
    prev = getattr(_pallas_ctx, "disabled", False)
    _pallas_ctx.disabled = True
    try:
        yield
    finally:
        _pallas_ctx.disabled = prev


def _pallas_decode_enabled() -> bool:
    """SWARMDB_PALLAS=1 routes single-token decode attention through the
    Pallas kernel (ops/attention_pallas.py); 0/unset keeps the XLA einsum
    path. Checked at trace time (static under jit)."""
    if getattr(_pallas_ctx, "disabled", False):
        return False
    return os.environ.get("SWARMDB_PALLAS", "0") == "1"


def _paged_pallas_enabled(kv_span: Optional[int] = None) -> bool:
    """The ragged paged kernel defaults ON for TPU in the LONG-context
    regime it exists for (HBM reads ∝ live pages). At short max_seq and
    full occupancy the XLA gather path wins — its big fused einsums fill
    the MXU where the kernel's per-page [G, ps] dots cannot (swarm100 on
    v5e at S=256: gather 2150 tok/s vs kernel 1484), so the TPU default
    flips to the kernel only when the table's coverage ``kv_span`` (maxp *
    page_size) reaches SWARMDB_PALLAS_KV_SPAN (default 1024 — the one
    v5e measurement above; retune the knob, not the code, when new
    silicon numbers land; the legacy SWARMDB_PALLAS_MIN_SEQ name is still
    honored). SWARMDB_PALLAS=0 forces the gather fallback everywhere,
    =1 forces the kernel even off-TPU (interpret mode — slow, for
    tests)."""
    if getattr(_pallas_ctx, "disabled", False):
        return False
    env = os.environ.get("SWARMDB_PALLAS", "")
    if env == "0":
        return False
    if env == "1":
        return True
    if jax.default_backend() != "tpu":
        return False
    if kv_span is None:
        return True
    thr = os.environ.get(
        "SWARMDB_PALLAS_KV_SPAN",
        os.environ.get("SWARMDB_PALLAS_MIN_SEQ", "1024"))
    return kv_span >= int(thr)


def decode_kernel_choice(kv_span: Optional[int] = None) -> str:
    """Host-side view of the decode-attention dispatch: ``"pallas"`` when
    the ragged paged kernel would serve a table of ``kv_span`` coverage,
    ``"gather"`` for the XLA page-gather fallback. The engine stamps this
    on flight-step records (and the bench on its mode record) so the
    analyzer can attribute a kernel-vs-gather regression instead of
    guessing which path a record measured."""
    return "pallas" if _paged_pallas_enabled(kv_span) else "gather"


def prefill_kernel_choice() -> str:
    """Host-side view of the ragged-prefill dispatch (the prefill twin
    of :func:`decode_kernel_choice`): ``"pallas-ragged"`` when
    ``ragged_prefill_dispatch`` would run the Pallas kernel,
    ``"xla-reference"`` for the dense fallback. swarmprof stamps this
    onto the ragged prefill variants' metadata at harvest time, so a
    profile dump says WHICH kernel its device seconds measured — the
    same record-provenance rule the bench's ``kernel`` field enforces
    for decode."""
    return ("pallas-ragged" if _ragged_prefill_kernel_enabled()
            else "xla-reference")


def _ragged_prefill_kernel_enabled() -> bool:
    """Gate for the ragged paged PREFILL kernel: SWARMDB_PALLAS=0 forces
    the XLA reference fallback, =1 forces the kernel even off-TPU
    (interpret mode — tests), default = kernel exactly on TPU. No
    kv-span crossover here: prefill waves amortize the page reads over
    the whole suffix, so the kernel's in-place page streaming wins as
    soon as there is any prefix at all and merely ties without one."""
    if getattr(_pallas_ctx, "disabled", False):
        return False
    env = os.environ.get("SWARMDB_PALLAS", "")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def _record_static_vmem(kernel: str, key: str, dims) -> None:
    """Fold the SWL903 static VMEM estimate for ``kernel`` into
    swarmprof's variant table under ``key``. Runs at dispatch trace
    time, where every dim in the site's symbolic footprint is a
    concrete Python int. Best-effort by contract: profiler off, no
    matching pallas_call site, or an unbound dim all mean 'no
    estimate', never an error on the dispatch path."""
    from ..obs.profiler import profiler

    prof = profiler()
    if not prof.enabled:
        return
    try:
        from ..analysis.kernelcheck import estimate_vmem, vmem_budget

        est = estimate_vmem(kernel, dims)
        if est is None:
            return
        devs = jax.devices()
        kind = devs[0].device_kind if devs else ""
        prof.record_vmem_estimate(key, est, vmem_budget(kind))
    except Exception:  # accounting must never break dispatch
        pass


def paged_attention_dispatch(
    q: jnp.ndarray,          # [B, 1, Hq, D] (decode only)
    k_pages: jnp.ndarray,    # [P, ps, Hkv, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, maxp]
    q_positions: jnp.ndarray,  # [B, 1]
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Decode attention over the paged pool: ragged Pallas kernel on TPU,
    XLA page-gather fallback elsewhere. Returns [B, 1, Hq, D].

    Accepts a plain pool OR an int8 :class:`~..ops.paged_kv.QuantPool`
    (SWARMDB_KV_DTYPE=int8): the quantized pool routes to the in-kernel
    dequant kernel variant; the gather fallback dequantizes to a dense
    f32 view inside ``paged_gather_kv``."""
    from .paged_kv import is_quantized, paged_gather_kv, pool_data

    kd = pool_data(k_pages)
    if _paged_pallas_enabled(page_table.shape[1] * kd.shape[1]):
        lengths = (q_positions[:, 0] + 1).astype(jnp.int32)
        interp = jax.default_backend() != "tpu"
        if is_quantized(k_pages):
            from .attention_pallas import paged_decode_gqa_attention_quant

            _record_static_vmem(
                "_paged_attn_kernel_quant", "kernel:pallas-int8",
                {"Hq": q.shape[2], "Hkv": kd.shape[2],
                 "D": q.shape[3], "ps": kd.shape[1]})
            out = paged_decode_gqa_attention_quant(
                q[:, 0], k_pages.data, k_pages.scale,
                v_pages.data, v_pages.scale, page_table, lengths,
                window=window, interpret=interp,
            )
            return out[:, None]
        from .attention_pallas import paged_decode_gqa_attention

        _record_static_vmem(
            "_paged_attn_kernel", "kernel:pallas",
            {"Hq": q.shape[2], "Hkv": kd.shape[2],
             "D": q.shape[3], "ps": kd.shape[1]})
        out = paged_decode_gqa_attention(
            q[:, 0], k_pages, v_pages, page_table, lengths,
            window=window, interpret=interp,
        )
        return out[:, None]
    kg, vg = paged_gather_kv(k_pages, v_pages, page_table)
    return gqa_attention(q, kg, vg, q_positions, window=window)


def paged_attention_dispatch_chunked(
    q: jnp.ndarray,           # [B, 1, Hq, D] decode query
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D] single-layer pool (FROZEN)
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, maxp]
    chunk_k: jnp.ndarray,     # [B, Kc, Hkv, D] this chunk's K so far
    chunk_v: jnp.ndarray,
    q_positions: jnp.ndarray,  # [B, 1]
    step: jnp.ndarray,        # scalar int32
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Two-segment decode attention for the PAGED cache: frozen page pool
    + in-chunk buffer under one softmax (the paged counterpart of
    ``gqa_attention_chunked``; the pool is only written once per chunk via
    ``ops.paged_kv.paged_write_chunk``).

    Ragged Pallas kernel on TPU (reads only live pages + the chunk
    buffer); XLA page-gather fallback elsewhere — the fallback reuses
    ``gqa_attention_chunked`` directly on the gathered dense view, whose
    frozen-segment mask (kv_pos < chunk start) already expresses "pool
    holds strictly the prefix".
    """
    from .paged_kv import is_quantized, paged_gather_kv, pool_data

    kd = pool_data(k_pages)
    if _paged_pallas_enabled(page_table.shape[1] * kd.shape[1]):
        starts = (q_positions[:, 0] - step).astype(jnp.int32)
        interp = jax.default_backend() != "tpu"
        if is_quantized(k_pages):
            from .attention_pallas import (
                paged_decode_gqa_attention_chunked_quant)

            out = paged_decode_gqa_attention_chunked_quant(
                q[:, 0], k_pages.data, k_pages.scale,
                v_pages.data, v_pages.scale, page_table, chunk_k,
                chunk_v, starts, step.astype(jnp.int32),
                window=window, interpret=interp,
            )
            return out[:, None]
        from .attention_pallas import paged_decode_gqa_attention_chunked

        out = paged_decode_gqa_attention_chunked(
            q[:, 0], k_pages, v_pages, page_table, chunk_k, chunk_v,
            starts, step.astype(jnp.int32),
            window=window, interpret=interp,
        )
        return out[:, None]
    kg, vg = paged_gather_kv(k_pages, v_pages, page_table)
    return gqa_attention_chunked(q, kg, vg, chunk_k, chunk_v, q_positions,
                                 step, window=window)


def ragged_prefill_attention_reference(
    q: jnp.ndarray,           # [W, Hq, D] packed query stream
    sfx_k: jnp.ndarray,       # [W, Hkv, D] packed suffix K
    sfx_v: jnp.ndarray,
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D] page pool (single layer)
    v_pages: jnp.ndarray,
    row_tables: jnp.ndarray,  # [R, maxp] int32
    starts: jnp.ndarray,      # [R] int32 stream offset per row
    lens: jnp.ndarray,        # [R] int32 suffix length per row (0 = dead)
    prefix_lens: jnp.ndarray,  # [R] int32 tokens already in the pages
    tok_row: jnp.ndarray,     # [W] int32 owning row per token (>= R = pad)
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Dense XLA reference for the ragged paged prefill kernel — and its
    off-TPU fallback. Every packed token attends its own row's prefix
    pages (gathered dense, positions ``0..prefix_lens[r]``) plus the
    row's suffix tokens causally; one fp32 softmax spans both segments.
    Cross-row scores are masked via ``tok_row``; padding tokens (row id
    >= R) match no real row and produce garbage the caller discards.

    Materializes [W, Pt] gathered prefix KV and [W, Pt + W] fp32 scores —
    the densification the Pallas kernel exists to avoid; fine for CPU
    tests/fallback waves, wrong for silicon. Quantized pools dequantize
    after the table gather (same math the quant kernel runs per tile).
    Returns [W, Hq, D]."""
    from .paged_kv import _dequantize_pages, is_quantized, pool_data

    W, Hq, D = q.shape
    Hkv = sfx_k.shape[1]
    G = Hq // Hkv
    R, maxp = row_tables.shape
    ps = pool_data(k_pages).shape[1]
    Pt = maxp * ps

    row = jnp.clip(tok_row, 0, R - 1)
    if is_quantized(k_pages):
        kp = _dequantize_pages(
            k_pages.data[row_tables],
            k_pages.scale[row_tables]).reshape(R, Pt, Hkv, D)
        vp = _dequantize_pages(
            v_pages.data[row_tables],
            v_pages.scale[row_tables]).reshape(R, Pt, Hkv, D)
    else:
        kp = k_pages[row_tables].reshape(R, Pt, Hkv, D)
        vp = v_pages[row_tables].reshape(R, Pt, Hkv, D)
    kp_t = kp[row]                                       # [W, Pt, Hkv, D]
    vp_t = vp[row]

    qg = q.reshape(W, Hkv, G, D)
    s_p = jnp.einsum("wkgd,wpkd->wkgp", qg, kp_t,
                     preferred_element_type=jnp.float32)
    s_s = jnp.einsum("wkgd,xkd->wkgx", qg, sfx_k,
                     preferred_element_type=jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    x = jnp.arange(W, dtype=jnp.int32)
    q_abs = prefix_lens[row] + x - starts[row]           # [W]
    p_pos = jnp.arange(Pt, dtype=jnp.int32)
    valid_p = p_pos[None, :] < prefix_lens[row][:, None]  # [W, Pt]
    if window is not None:
        valid_p &= p_pos[None, :] > (q_abs[:, None] - window)
    same = tok_row[:, None] == tok_row[None, :]          # [W, W]
    valid_s = same & (x[None, :] <= x[:, None])          # packed causal
    if window is not None:
        valid_s &= x[None, :] > (x[:, None] - window)

    s_p = jnp.where(valid_p[:, None, None, :], s_p * scale,
                    jnp.float32(-1e30))
    s_s = jnp.where(valid_s[:, None, None, :], s_s * scale,
                    jnp.float32(-1e30))
    s = jnp.concatenate([s_p, s_s], axis=-1)             # [W, Hkv, G, Pt+W]
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("wkgp,wpkd->wkgd", p[..., :Pt].astype(vp_t.dtype),
                     vp_t, preferred_element_type=jnp.float32)
    out = out + jnp.einsum("wkgx,xkd->wkgd",
                           p[..., Pt:].astype(sfx_v.dtype), sfx_v,
                           preferred_element_type=jnp.float32)
    return out.reshape(W, Hq, D).astype(q.dtype)


def ragged_prefill_dispatch(
    q: jnp.ndarray,           # [W, Hq, D] packed query stream
    sfx_k: jnp.ndarray,       # [W, Hkv, D]
    sfx_v: jnp.ndarray,
    k_pages: jnp.ndarray,     # [P, ps, Hkv, D]
    v_pages: jnp.ndarray,
    row_tables: jnp.ndarray,  # [R, maxp]
    starts: jnp.ndarray,
    lens: jnp.ndarray,
    prefix_lens: jnp.ndarray,
    tok_row: jnp.ndarray,     # [W]
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Packed ragged PREFILL attention over the paged pool: the Pallas
    ragged kernel on TPU (prefix pages read in place via the page table —
    no gather densification, no bucket padding), the dense XLA reference
    elsewhere. Same TPU-gated / interpreter-tested pattern as the paged
    decode dispatchers above. Returns [W, Hq, D]."""
    if _ragged_prefill_kernel_enabled():
        from .paged_kv import is_quantized, pool_data

        quant = is_quantized(k_pages)
        W = q.shape[0]
        pad = (-W) % 8                 # TPU sublane quantum for tiny waves
        _record_static_vmem(
            "_ragged_prefill_kernel_quant" if quant
            else "_ragged_prefill_kernel",
            f"prefill.ragged[w{W}]",
            {"W": W + pad, "Hq": q.shape[1], "Hkv": sfx_k.shape[1],
             "D": q.shape[2], "ps": pool_data(k_pages).shape[1]})
        if pad:
            grow = ((0, pad), (0, 0), (0, 0))
            q = jnp.pad(q, grow)
            sfx_k = jnp.pad(sfx_k, grow)
            sfx_v = jnp.pad(sfx_v, grow)
        interp = jax.default_backend() != "tpu"
        if quant:
            from .attention_pallas import (
                ragged_paged_prefill_attention_quant)

            out = ragged_paged_prefill_attention_quant(
                q, sfx_k, sfx_v, k_pages.data, k_pages.scale,
                v_pages.data, v_pages.scale, row_tables, starts, lens,
                prefix_lens, window=window, interpret=interp,
            )
        else:
            from .attention_pallas import ragged_paged_prefill_attention

            out = ragged_paged_prefill_attention(
                q, sfx_k, sfx_v, k_pages, v_pages, row_tables, starts,
                lens, prefix_lens, window=window, interpret=interp,
            )
        return out[:W] if pad else out
    return ragged_prefill_attention_reference(
        q, sfx_k, sfx_v, k_pages, v_pages, row_tables, starts, lens,
        prefix_lens, tok_row, window=window)


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMSNorm with fp32 statistics, output in x.dtype."""
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(x.dtype) * weight


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies [head_dim/2], fp32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def rope_cos_sin(
    positions: jnp.ndarray, head_dim: int, theta: float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Precompute RoPE rotation terms for a batch of positions.

    Returns (cos, sin), each [B, T, 1, D/2] fp32. Depends only on positions,
    so callers compute it ONCE per forward and reuse it across every layer —
    inside a scanned layer body XLA cannot hoist the transcendentals itself.
    """
    inv_freq = rope_frequencies(head_dim, theta)  # [D/2]
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, T, D/2]
    return jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotary position embedding with precomputed terms (`rope_cos_sin`).

    x: [B, T, H, D]. Pairs (x[..., :D/2], x[..., D/2:]) are rotated — the
    "split-half" convention used by HF Llama, so checkpoints interoperate.
    """
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def qkv_proj(
    h: jnp.ndarray,       # [B, T, D] normed hidden states
    lp: dict,             # layer params with "wq"/"wk"/"wv"
    n_heads: int,
    n_kv_heads: int,
    head_dim: int,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Q/K/V projections + head split + RoPE — the block every forward
    variant (dense, chunked, paged, seq-parallel, pipelined; Llama and
    Mixtral alike) starts its attention with."""
    B, T = h.shape[0], h.shape[1]
    q = jnp.einsum("btd,dh->bth", h, lp["wq"]).reshape(B, T, n_heads, head_dim)
    k = jnp.einsum("btd,dh->bth", h, lp["wk"]).reshape(B, T, n_kv_heads, head_dim)
    v = jnp.einsum("btd,dh->bth", h, lp["wv"]).reshape(B, T, n_kv_heads, head_dim)
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v


def swiglu(x: jnp.ndarray, w_gate: jnp.ndarray, w_up: jnp.ndarray,
           w_down: jnp.ndarray) -> jnp.ndarray:
    """SwiGLU MLP: silu(x @ gate) * (x @ up) @ down."""
    g = jax.nn.silu(jnp.einsum("btd,df->btf", x, w_gate))
    u = jnp.einsum("btd,df->btf", x, w_up)
    return jnp.einsum("btf,fd->btd", g * u, w_down)


def write_kv_cache(
    cache_k: jnp.ndarray,  # [B, S, Hkv, D]
    cache_v: jnp.ndarray,
    k: jnp.ndarray,  # [B, T, Hkv, D]
    v: jnp.ndarray,
    positions: jnp.ndarray,  # [B, T]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write new K/V into per-slot cache rows at absolute positions.

    Positions may differ per batch row (continuous batching: each slot is
    at its own decode offset). Three lowerings, picked by static shape:

    - T == S (prefill filling its whole temp cache): the write IS the
      cache — return the new values directly, zero data movement.
    - T == 1 (decode): a positional mask + select. TPU lowers per-row
      scatter to a serialized index loop (measured: it dominated the
      round-3 decode step); the mask form is a pure vectorized
      element-wise op over the cache the step already streams through.
    - general T: the scatter fallback (no serving path hits this today).
    """
    B, S = cache_k.shape[0], cache_k.shape[1]
    T = k.shape[1]
    if T == S:
        return k.astype(cache_k.dtype), v.astype(cache_v.dtype)
    if T == 1:
        hit = jnp.arange(S)[None, :] == positions  # [B, S]
        sel = hit[:, :, None, None]
        cache_k = jnp.where(sel, k.astype(cache_k.dtype), cache_k)
        cache_v = jnp.where(sel, v.astype(cache_v.dtype), cache_v)
        return cache_k, cache_v
    b_idx = jnp.arange(B)[:, None]  # [B, 1]
    cache_k = cache_k.at[b_idx, positions].set(k.astype(cache_k.dtype))
    cache_v = cache_v.at[b_idx, positions].set(v.astype(cache_v.dtype))
    return cache_k, cache_v


def gqa_attention_chunked(
    q: jnp.ndarray,          # [B, 1, Hq, D] decode query
    cache_k: jnp.ndarray,    # [B, S, Hkv, D] FROZEN prefix cache
    cache_v: jnp.ndarray,
    chunk_k: jnp.ndarray,    # [B, Kc, Hkv, D] this chunk's K so far
    chunk_v: jnp.ndarray,
    q_positions: jnp.ndarray,  # [B, 1] absolute position of the query
    step: jnp.ndarray,       # scalar int32: index of this step in the chunk
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Two-segment decode attention: frozen slot cache + in-chunk buffer.

    The engine's chunked decode (Engine._decode) keeps the big [B, S, ...]
    cache FROZEN for the K steps of a chunk and accumulates the chunk's own
    K/V in a tiny [B, Kc, ...] buffer written with dynamic_update_slice
    (uniform index — no per-row scatter). Attention therefore reads the
    big cache without ever rewriting it; the round-3 path rewrote the full
    cache every step, which profiling showed was the single largest cost
    of a decode chunk (~2x the model matmuls at batch 128).

    Masking: the frozen segment is valid strictly below the chunk's start
    position (entries at >= start are a previous occupant's garbage); the
    chunk segment is valid up to and including ``step``. One softmax spans
    both segments. Returns [B, 1, Hq, D].
    """
    B, S = cache_k.shape[0], cache_k.shape[1]
    Kc = chunk_k.shape[1]
    Hq, Hkv = q.shape[2], cache_k.shape[2]
    group = Hq // Hkv
    D = q.shape[-1]

    tile = min(S, 256)
    if _pallas_decode_enabled() and S % tile == 0:
        # round-4 silicon trace: the two einsums below run at 2.2x their
        # HBM floor and always read the FULL [S] lane; the kernel streams
        # tiles under an online softmax and skips the DMA past each
        # slot's live prefix, so traffic tracks occupancy
        from .attention_pallas import decode_gqa_attention_chunked

        out = decode_gqa_attention_chunked(
            q[:, 0], cache_k, cache_v, chunk_k, chunk_v,
            (q_positions[:, 0] - step).astype(jnp.int32), step,
            window=window, tile=tile,
            interpret=jax.default_backend() != "tpu",
        )
        return out[:, None]

    qg = q.reshape(B, 1, Hkv, group, D)
    s_f = jnp.einsum("btkgd,bskd->bkgts", qg, cache_k,
                     preferred_element_type=jnp.float32)
    s_c = jnp.einsum("btkgd,bskd->bkgts", qg, chunk_k,
                     preferred_element_type=jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    start = q_positions - step                           # [B, 1] chunk start
    kv_pos = jnp.arange(S)[None, None, :]                # [1, 1, S]
    valid_f = kv_pos < start[:, :, None]                 # [B, 1, S]
    if window is not None:
        valid_f &= kv_pos > (q_positions[:, :, None] - window)
    j = jnp.arange(Kc)[None, None, :]                    # [1, 1, Kc]
    valid_c = j <= step                                  # [1, 1, Kc]
    abs_c = start[:, :, None] + j                        # [B, 1, Kc]
    if window is not None:
        valid_c = valid_c & (abs_c > (q_positions[:, :, None] - window))

    s_f = jnp.where(valid_f[:, None, None], s_f * scale, jnp.float32(-1e30))
    s_c = jnp.where(valid_c[:, None, None], s_c * scale, jnp.float32(-1e30))
    s = jnp.concatenate([s_f, s_c], axis=-1)             # [B, Hkv, g, 1, S+Kc]
    p = jax.nn.softmax(s, axis=-1)
    p_f = p[..., :S].astype(cache_v.dtype)
    p_c = p[..., S:].astype(chunk_v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", p_f, cache_v,
                     preferred_element_type=jnp.float32)
    out = out + jnp.einsum("bkgts,bskd->btkgd", p_c, chunk_v,
                           preferred_element_type=jnp.float32)
    return out.reshape(q.shape).astype(q.dtype)


def merge_chunk_kv(
    cache_k: jnp.ndarray,   # [L, B, S, Hkv, D]
    cache_v: jnp.ndarray,
    chunk_k: jnp.ndarray,   # [L, B, Kc, Hkv, D]
    chunk_v: jnp.ndarray,
    start_positions: jnp.ndarray,  # [B] absolute position of chunk step 0
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold a finished chunk's K/V back into the big slot cache — ONCE per
    chunk instead of once per step.

    Expressed as a one-hot einsum + select: ``sel[b, s, j] = 1`` iff cache
    position s is chunk entry j for row b. A take_along_axis gather here
    is numerically identical but XLA-TPU takes minutes to compile the 5D
    batched gather (measured >5 min at serving shapes vs ~1 s for this
    form); the einsum is a tiny MXU contraction and the one-hot rows are
    exact (exactly one 1 per written position), so no precision is lost.
    """
    S = cache_k.shape[2]
    Kc = chunk_k.shape[2]
    kv_pos = jnp.arange(S)[None, :]                      # [1, S]
    start = start_positions[:, None]                     # [B, 1]
    j = jnp.arange(Kc)[None, None, :]                    # [1, 1, Kc]
    sel = ((kv_pos - start)[:, :, None] == j)            # [B, S, Kc]
    hit = (kv_pos >= start) & (kv_pos < start + Kc)      # [B, S]
    sel_b = sel.astype(cache_k.dtype)
    hit_b = hit[None, :, :, None, None]

    def upd(full, chunk):
        g = jnp.einsum("bsj,lbjhd->lbshd", sel_b, chunk,
                       preferred_element_type=full.dtype)
        return jnp.where(hit_b, g, full)

    return upd(cache_k, chunk_k), upd(cache_v, chunk_v)


def merge_chunk_kv_scatter(
    cache_k: jnp.ndarray,   # [L, B, S, Hkv, D]
    cache_v: jnp.ndarray,
    chunk_k: jnp.ndarray,   # [L, B, Kc, Hkv, D]
    chunk_v: jnp.ndarray,
    start_positions: jnp.ndarray,  # [B]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter formulation of ``merge_chunk_kv`` (numerically identical;
    `test_merge_chunk_scatter_matches_einsum`).

    One [B, Kc]-indexed `.at[].set` per cache tensor instead of the
    one-hot einsum + select. The chunk trace showed ~27 ms/chunk of
    merge + full-cache copies around the einsum form at B=128
    (PROFILE.md session 2); this form writes only the Kc columns and
    gives XLA a direct in-place-update pattern for the donated cache.
    TPU scatters serialize per index row, which is why the PER-STEP
    [B, 1] scatter lost badly in round 3 — per CHUNK the amortization
    may land differently. Raced on silicon by scripts/profile_merge.py;
    selected via SWARMDB_MERGE=scatter (backend/service.py)."""
    Kc = chunk_k.shape[2]
    b_idx = jnp.arange(cache_k.shape[1])[:, None]        # [B, 1]
    cols = start_positions[:, None] + jnp.arange(Kc)[None, :]  # [B, Kc]
    # a chunk may overshoot its lane (the engine dispatches full K-step
    # chunks and retires on max_seq at processing time): mode="drop"
    # discards the out-of-range columns, matching the einsum form's hit
    # mask (kv_pos < start + Kc never fires past S there)
    ck = cache_k.at[:, b_idx, cols].set(chunk_k.astype(cache_k.dtype),
                                        mode="drop")
    cv = cache_v.at[:, b_idx, cols].set(chunk_v.astype(cache_v.dtype),
                                        mode="drop")
    return ck, cv


def gqa_attention(
    q: jnp.ndarray,          # [B, T, Hq, D]
    cache_k: jnp.ndarray,    # [B, S, Hkv, D]
    cache_v: jnp.ndarray,    # [B, S, Hkv, D]
    q_positions: jnp.ndarray,  # [B, T] absolute position of each query
    *,
    window: Optional[int] = None,  # sliding-window size (None = full causal)
) -> jnp.ndarray:
    """Grouped-query attention against the full cache buffer with causal
    masking by absolute position.

    Validity invariant: a cache slot is filled monotonically from position 0,
    so every cache entry at position s <= q_position is live for that row.
    Returns [B, T, Hq, D] in q.dtype; softmax in fp32.
    """
    B, S = cache_k.shape[0], cache_k.shape[1]
    Hq, Hkv = q.shape[2], cache_k.shape[2]
    group = Hq // Hkv

    if q.shape[1] == 1 and window is None and _pallas_decode_enabled():
        from .attention_pallas import decode_gqa_attention

        out = decode_gqa_attention(
            q[:, 0],
            cache_k,
            cache_v,
            (q_positions[:, 0] + 1).astype(jnp.int32),
            interpret=jax.default_backend() != "tpu",
        )
        return out[:, None]

    # bf16 operands with fp32 accumulation: the MXU-native contraction. An
    # explicit .astype(f32) on the cache (the round-3 code) materializes
    # the WHOLE cache in fp32 every layer every step and pushes the matmul
    # off the bf16 fast path — measured ~2x slower decode chunks.
    # [B, T, Hkv, group, D] x [B, S, Hkv, D] -> [B, Hkv, group, T, S]
    qg = q.reshape(B, q.shape[1], Hkv, group, -1)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, cache_k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(q.shape[-1]))

    kv_pos = jnp.arange(S)[None, None, :]                # [1, 1, S]
    causal = kv_pos <= q_positions[:, :, None]           # [B, T, S]
    if window is not None:
        causal &= kv_pos > (q_positions[:, :, None] - window)
    mask = causal[:, None, None, :, :]                   # [B, 1, 1, T, S]
    scores = jnp.where(mask, scores, jnp.float32(-1e30))

    probs = jax.nn.softmax(scores, axis=-1)              # fp32
    out = jnp.einsum("bkgts,bskd->btkgd", probs.astype(cache_v.dtype),
                     cache_v, preferred_element_type=jnp.float32)
    return out.reshape(q.shape).astype(q.dtype)


def compose_prefix_lane(
    pool_k: jnp.ndarray,        # [L, P, ps, Hkv, D] prefix page pool
    pool_v: jnp.ndarray,
    prefix_table: jnp.ndarray,  # [Bp, PP] int32 page ids per row
    prefix_lens: jnp.ndarray,   # [Bp] int32 reused tokens per row
    sfx_k: jnp.ndarray,         # [L, Bp, T, Hkv, D] suffix K (stacked)
    sfx_v: jnp.ndarray,
    lane_pages: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compose per-row KV LANE IMAGES for the dense-cache prefix path:
    lane[b, j] = reused prefix page content for j < prefix_lens[b], else
    the suffix K/V one-hot-placed at absolute position prefix_lens[b]+t.

    The one-hot einsum expresses per-row ragged placement with uniform
    shapes — per-row gather/scatter forms either serialize on TPU or take
    minutes to compile (see merge_chunk_kv). Entries beyond a row's
    prompt hold zeros/pad garbage, unreachable under the engine's
    write-before-read invariant. Returns [L, Bp, lane_pages*ps, Hkv, D]
    lane_k, lane_v.
    """
    L, P, ps = pool_k.shape[0], pool_k.shape[1], pool_k.shape[2]
    Bp, PP = prefix_table.shape
    T = sfx_k.shape[2]
    Pt = PP * ps
    lane_t = lane_pages * ps

    kp = pool_k[:, prefix_table].reshape((L, Bp, Pt) + pool_k.shape[3:])
    vp = pool_v[:, prefix_table].reshape((L, Bp, Pt) + pool_v.shape[3:])
    lane_j = jnp.arange(lane_t, dtype=jnp.int32)[None, :]
    in_prefix = (lane_j < prefix_lens[:, None])[None, :, :, None, None]
    sel = (lane_j[:, :, None]
           == (prefix_lens[:, None, None]
               + jnp.arange(T, dtype=jnp.int32)[None, None, :]))

    def lane(prefix, fresh):
        if lane_t > Pt:
            pad = jnp.zeros((L, Bp, lane_t - Pt) + prefix.shape[3:],
                            prefix.dtype)
            pre = jnp.concatenate([prefix, pad], axis=2)
        else:
            pre = prefix[:, :, :lane_t]
        suf = jnp.einsum("bjt,lbthd->lbjhd", sel.astype(fresh.dtype),
                         fresh, preferred_element_type=prefix.dtype)
        return jnp.where(in_prefix, pre, suf.astype(prefix.dtype))

    return lane(kp, sfx_k), lane(vp, sfx_v)


def gqa_attention_prefix(
    q: jnp.ndarray,          # [B, T, Hq, D] suffix queries
    prefix_k: jnp.ndarray,   # [B, Pt, Hkv, D] gathered prefix K (positions 0..)
    prefix_v: jnp.ndarray,
    suffix_k: jnp.ndarray,   # [B, T, Hkv, D] this call's K (current tokens)
    suffix_v: jnp.ndarray,
    prefix_lens: jnp.ndarray,  # [B] int32 — valid prefix length per row
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Two-segment PREFILL attention for prefix-cache reuse: the suffix's
    queries attend a reused KV prefix (positions ``0..prefix_lens[b]``,
    gathered from the prefix page pool) plus the suffix itself causally.

    Row ``b``'s suffix token t sits at absolute position
    ``prefix_lens[b] + t``; the prefix segment is valid strictly below
    ``prefix_lens[b]`` (gather padding beyond a row's true prefix is
    masked). One fp32 softmax spans both segments — this is
    ``gqa_attention`` over the concatenated KV: because prefill attention
    reads the bf16-WRITTEN cache, the reused prefix K/V bytes are
    identical to a full recompute's, and only reduction tiling can differ
    (last-ulp). Returns [B, T, Hq, D] in q.dtype.

    No reference counterpart (the reference has no model/serving code);
    the vLLM-style automatic prefix caching pattern is noted in PAPERS.md.
    """
    B, T = q.shape[0], q.shape[1]
    Pt = prefix_k.shape[1]
    Hq, Hkv = q.shape[2], prefix_k.shape[2]
    group = Hq // Hkv
    D = q.shape[-1]

    qg = q.reshape(B, T, Hkv, group, D)
    s_p = jnp.einsum("btkgd,bskd->bkgts", qg, prefix_k,
                     preferred_element_type=jnp.float32)
    s_s = jnp.einsum("btkgd,bskd->bkgts", qg, suffix_k,
                     preferred_element_type=jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    plen = prefix_lens[:, None, None]                    # [B, 1, 1]
    q_abs = prefix_lens[:, None] + jnp.arange(T)[None, :]  # [B, T]
    kv_pos = jnp.arange(Pt)[None, None, :]               # [1, 1, Pt]
    valid_p = kv_pos < plen                              # [B, 1→T, Pt]
    valid_p = jnp.broadcast_to(valid_p, (B, T, Pt))
    j = jnp.arange(T)[None, None, :]                     # [1, 1, T]
    valid_s = j <= jnp.arange(T)[None, :, None]          # [1, T, T] causal
    valid_s = jnp.broadcast_to(valid_s, (B, T, T))
    if window is not None:
        lo = q_abs[:, :, None] - window                  # [B, T, 1]
        valid_p &= kv_pos > lo
        valid_s &= (plen + j) > lo
    s_p = jnp.where(valid_p[:, None, None], s_p * scale, jnp.float32(-1e30))
    s_s = jnp.where(valid_s[:, None, None], s_s * scale, jnp.float32(-1e30))
    s = jnp.concatenate([s_p, s_s], axis=-1)             # [B, Hkv, g, T, Pt+T]
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", p[..., :Pt].astype(prefix_v.dtype),
                     prefix_v, preferred_element_type=jnp.float32)
    out = out + jnp.einsum("bkgts,bskd->btkgd",
                           p[..., Pt:].astype(suffix_v.dtype), suffix_v,
                           preferred_element_type=jnp.float32)
    return out.reshape(q.shape).astype(q.dtype)


# --- kerncheck: interpreter-mode kernel sanitizer (obs/kerncheck.py) ----
# SWARMDB_KERNCHECK=1 swaps the TPU-gated dispatchers for shadow-checked
# wrappers: every concrete (non-traced) call re-runs the kernel through
# the numpy grid interpreter with canary-poisoned outputs and bounds-
# checked Refs, then asserts parity against the dispatched result. Flag
# off, this block never runs and the module exports the plain function
# objects — type identity is pinned by tests/test_kernelcheck.py.
if os.environ.get("SWARMDB_KERNCHECK", "0") == "1":
    from ..obs.kerncheck import (checked_paged_attention_dispatch,
                                 checked_ragged_prefill_dispatch)

    paged_attention_dispatch = checked_paged_attention_dispatch(
        paged_attention_dispatch)
    ragged_prefill_dispatch = checked_ragged_prefill_dispatch(
        ragged_prefill_dispatch)
