"""Warm-tier host-RAM page store (ISSUE 19 — swarmtier).

The middle rung of the three-tier conversation-state hierarchy: pages
demoted out of the device pool land here as raw numpy payloads (storage
width — int8 + scales on quantized pools, so a spilled page costs half
the bf16 bytes swarmmem's ``warm_tier_model`` already prices), keyed by
conversation. Promotion pops the payload and bulk-``device_put``s it
back into freshly reserved device pages; eviction out of THIS store is
the warm→cold transition (the conversation falls back to idempotent
re-prefill from the broker log — PR 8 proved that replay bit-identical).

Capacity is byte-priced: ``SWARMDB_TIER_WARM_MB`` (default 256) divided
by the live pool's ``pool_page_bytes`` (k+v across layers, scales
included). The store is plain LRU over conversations — temperature-aware
VICTIM selection happens on the device side (backend/tiering.py picks
who gets demoted); once spilled, recency is the only signal left.

Thread-safe: the engine thread gathers payloads in, the service thread
(``_rolling_plan``) pops them out at arrival time.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..utils.sync import make_lock


def warm_capacity_bytes() -> int:
    """Resolve SWARMDB_TIER_WARM_MB (default 256 MiB; 0 disables the
    warm tier entirely — demotions fall straight through to cold)."""
    try:
        mb = float(os.environ.get("SWARMDB_TIER_WARM_MB", "256"))
    except ValueError:
        mb = 256.0
    return max(0, int(mb * (1 << 20)))


# ------------------------------------------------------------- compression
#
# SWARMDB_TIER_ZSTD=1 compresses demoted payloads at rest (and on the
# fleet's prefill→decode handoff wire, which rides the same store). The
# container may not ship python-zstandard; zlib is the stdlib fallback —
# same seam, worse ratio. Codec is resolved per store at construction so
# tests can flip the env var per instance.

def _resolve_codec() -> Optional[Tuple[str, Any, Any]]:
    if os.environ.get("SWARMDB_TIER_ZSTD", "0") != "1":
        return None
    try:
        import zstandard  # type: ignore

        comp = zstandard.ZstdCompressor(level=3)
        deco = zstandard.ZstdDecompressor()
        return ("zstd", comp.compress, deco.decompress)
    except Exception:
        import zlib

        return ("zlib",
                lambda b: zlib.compress(b, 3),
                zlib.decompress)


class _Packed(NamedTuple):
    """One compressed array: blob + enough metadata to rebuild it."""

    blob: bytes
    dtype: str
    shape: Tuple[int, ...]
    raw_nbytes: int


def _pack_array(arr: Any, compress: Any) -> _Packed:
    a = np.ascontiguousarray(arr)
    return _Packed(compress(a.tobytes()), str(a.dtype),
                   tuple(a.shape), int(a.nbytes))


def _pack(payload: Any, compress: Any) -> Any:
    if isinstance(payload, tuple):
        return tuple(_pack_array(p, compress) for p in payload)
    return _pack_array(payload, compress)


def _unpack_array(p: _Packed, decompress: Any) -> np.ndarray:
    return np.frombuffer(decompress(p.blob),
                         dtype=np.dtype(p.dtype)).reshape(p.shape)


def _unpack(payload: Any, decompress: Any) -> Any:
    if isinstance(payload, _Packed):
        return _unpack_array(payload, decompress)
    if isinstance(payload, tuple):
        return tuple(_unpack_array(p, decompress) if isinstance(p, _Packed)
                     else p for p in payload)
    return payload


def _is_packed(payload: Any) -> bool:
    if isinstance(payload, _Packed):
        return True
    return (isinstance(payload, tuple)
            and any(isinstance(p, _Packed) for p in payload))


class WarmEntry(NamedTuple):
    """One spilled conversation: raw k/v payloads for ``n_pages`` pages.

    ``k``/``v`` are :func:`ops.paged_kv.pool_gather_pages` outputs —
    ``(int8 data, f32 scale)`` tuples on quantized pools, a single array
    on plain pools. ``length`` is the token count the pages cover (the
    registry's ``st["len"]``); promotion must reserve exactly
    ``n_pages`` device pages to rehydrate it.
    """

    k: Any
    v: Any
    n_pages: int
    length: int
    nbytes: int


def _payload_bytes(payload: Any) -> int:
    if isinstance(payload, _Packed):
        return len(payload.blob)
    if isinstance(payload, tuple):
        return sum(_payload_bytes(p) for p in payload)
    return int(np.asarray(payload).nbytes)


class HostPageStore:
    """LRU byte-capped map: conversation key -> :class:`WarmEntry`."""

    def __init__(self, capacity_bytes: Optional[int] = None,
                 label: str = "warm") -> None:
        self.capacity_bytes = (warm_capacity_bytes()
                               if capacity_bytes is None
                               else int(capacity_bytes))
        self.label = label
        self._lock = make_lock(f"host_pool.{label}")
        self._entries: "OrderedDict[Any, WarmEntry]" = OrderedDict()
        self._bytes = 0
        self._puts = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._codec = _resolve_codec()
        self._raw_in = 0     # uncompressed bytes offered to the codec
        self._comp_in = 0    # compressed bytes actually stored

    # ------------------------------------------------------------- write
    def put(self, key: Any, k_payload: Any, v_payload: Any,
            n_pages: int, length: int) -> List[Any]:
        """Store a demoted conversation; returns the keys EVICTED to make
        room (the caller counts them as warm→cold transitions). A key
        already present is replaced (latest demote wins). If the entry
        alone exceeds capacity it is not stored and ``[key]`` is
        returned — the demote degenerates to a cold eviction.
        """
        raw = _payload_bytes(k_payload) + _payload_bytes(v_payload)
        nbytes = raw
        if self._codec is not None:
            _, compress, _ = self._codec
            k_payload = _pack(k_payload, compress)
            v_payload = _pack(v_payload, compress)
            nbytes = _payload_bytes(k_payload) + _payload_bytes(v_payload)
            with self._lock:
                self._raw_in += raw
                self._comp_in += nbytes
        entry = WarmEntry(k_payload, v_payload, int(n_pages),
                          int(length), nbytes)
        evicted: List[Any] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            if nbytes > self.capacity_bytes:
                return [key]
            while self._bytes + nbytes > self.capacity_bytes and self._entries:
                victim, ventry = self._entries.popitem(last=False)
                self._bytes -= ventry.nbytes
                self._evictions += 1
                evicted.append(victim)
            self._entries[key] = entry
            self._bytes += nbytes
            self._puts += 1
        return evicted

    # -------------------------------------------------------------- read
    def pop(self, key: Any) -> Optional[WarmEntry]:
        """Remove and return the entry (promotion consumes it). Always
        returns real numpy payloads — compressed entries are inflated
        here, outside the lock."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                self._misses += 1
                return None
            self._bytes -= entry.nbytes
            self._hits += 1
        if _is_packed(entry.k) or _is_packed(entry.v):
            codec = self._codec or _resolve_codec()
            if codec is None:  # env flipped off mid-life; stdlib fallback
                import zlib

                decompress: Any = zlib.decompress
            else:
                decompress = codec[2]
            k = _unpack(entry.k, decompress)
            v = _unpack(entry.v, decompress)
            entry = WarmEntry(k, v, entry.n_pages, entry.length,
                              _payload_bytes(k) + _payload_bytes(v))
        return entry

    def has(self, key: Any) -> bool:
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)  # arrival interest = touch
                return True
            return False

    def drop(self, key: Any) -> bool:
        """Discard without counting a hit/miss (cold finalize paths)."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return False
            self._bytes -= entry.nbytes
            return True

    # ------------------------------------------------------------- intro
    def page_count(self) -> int:
        with self._lock:
            return sum(e.n_pages for e in self._entries.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "entries": len(self._entries),
                "pages": sum(e.n_pages for e in self._entries.values()),
                "bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
                "puts": self._puts,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "codec": self._codec[0] if self._codec else None,
            }
            if self._comp_in > 0:
                out["raw_bytes_in"] = self._raw_in
                out["compressed_bytes_in"] = self._comp_in
                out["compress_ratio"] = round(
                    self._raw_in / self._comp_in, 3)
            return out
