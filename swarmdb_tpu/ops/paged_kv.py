"""Block-paged KV cache (SURVEY §5.7 / §7 design hook, made real).

The round-1 cache was a dense ``[L, B, max_seq, Hkv, D]`` slot buffer: HBM
scales with ``max_batch x max_seq`` regardless of occupancy, which caps
batch x context well below what the 100-agent config needs (VERDICT r1
missing #2). Here K/V live in a shared POOL of fixed-size pages:

    k_pages, v_pages: [L, num_pages, page_size, Hkv, D]
    page_table:       [B, pages_per_slot] int32  (page ids per slot)

HBM is provisioned for the EXPECTED total live tokens (num_pages x
page_size), not worst-case ``B x S``. A host-side :class:`PageAllocator`
hands pages to slots at admission and reclaims them at retirement.

Pool invariants (all enforced here and in the engine):
- Page 0 is the TRASH page: never allocated. Inactive/retired slots keep a
  zeroed page-table row, so the decode step's masked garbage writes land in
  page 0 instead of corrupting pages that were freed and reallocated.
- Decode writes at positions >= max_seq are routed to the trash page (the
  dense cache dropped them via out-of-bounds scatter semantics; the paged
  indirection would otherwise CLAMP the page column and overwrite live
  entries).
- A retired slot's pages are freed only AFTER its page-table row is zeroed
  (``PageAllocator.flush_frees`` pairs the two), closing the
  stale-table/reused-page race.

All device functions are shape-static and jit-safe. The XLA attention path
gathers the slot's pages into a dense view (same HBM traffic as the dense
cache — correctness fallback); the bandwidth win on TPU comes from the
ragged Pallas kernel in ``ops/attention_pallas.py`` which reads only live
pages. No reference counterpart (the reference has no model code); pattern
follows the ragged paged attention design noted in PAPERS.md.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.sync import make_lock

PagedCache = Dict[str, jnp.ndarray]  # {"k", "v", "page_table"}


# --------------------------------------------------- quantized KV pages
# SWARMDB_KV_DTYPE picks the POOL storage dtype (ISSUE 18): f32 and bf16
# store pages verbatim (bf16 = today's default, bit-identical); int8
# stores symmetric per-page-per-head quantized pages with f32 scales
# alongside — decode's roofline bytes halve, and the hot kernels
# dequantize IN-KERNEL (ops/attention_pallas.py) so full-precision KV
# never round-trips through HBM. Applies to PAGED pools only; dense slot
# caches and the dense prefix side pool ignore the flag.

KV_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16, "int8": jnp.int8}

#: logical dtype a quantized pool represents — dequantized reads and
#: suffix-KV casts target this, matching the unquantized default
DEQUANT_DTYPE = jnp.bfloat16

#: quantized-pool range: symmetric [-127, 127], leaving int8's -128 free
#: for the page sanitizer's canary (never produced by the quantizer)
_QMAX = 127.0


class QuantPool(NamedTuple):
    """A quantized page pool: int8 payload + f32 symmetric scales.

    ``data``  [..., P, ps, Hkv, D] int8 — quantized K or V pages
    ``scale`` [..., P, Hkv]        f32  — per-page-per-head scale;
              dequantized value = data * scale. Leading axes mirror the
              payload's (a per-layer slice of an [L, ...] pool carries
              its per-layer scale slice — ``lax.scan`` over the pool
              slices both, since NamedTuples are pytrees).

    Stored under the same ``{"k", "v"}`` cache keys as a plain pool, so
    the engine's fused dispatches, donation, warmup specs, and sharded
    cache plumbing are structure-transparent; code that touches the raw
    arrays goes through the ``pool_*`` helpers below.
    """

    data: jnp.ndarray
    scale: jnp.ndarray


def kv_dtype_name() -> str:
    """Resolve SWARMDB_KV_DTYPE (default ``bf16`` — today's pool dtype,
    bit-identical with the flag unset)."""
    name = os.environ.get("SWARMDB_KV_DTYPE", "bf16").strip().lower()
    if name in ("", "auto"):
        return "bf16"
    if name not in KV_DTYPES:
        raise ValueError(
            f"SWARMDB_KV_DTYPE={name!r}: expected one of "
            f"{sorted(KV_DTYPES)}")
    return name


def kv_quantized(name: Optional[str] = None) -> bool:
    return (name or kv_dtype_name()) == "int8"


def is_quantized(pool: Any) -> bool:
    return isinstance(pool, QuantPool)


def pool_data(pool: Any) -> jnp.ndarray:
    """Raw storage array of a pool (int8 payload for quantized pools)."""
    return pool.data if isinstance(pool, QuantPool) else pool


def pool_dtype(pool: Any) -> jnp.dtype:
    """LOGICAL dtype of a pool — what reads dequantize to, and what
    suffix K/V should be cast to before attending (the write-what-you-
    attend contract of forward_ragged_prefill)."""
    return DEQUANT_DTYPE if isinstance(pool, QuantPool) else pool.dtype


def pool_layer(pool: Any, l: int) -> Any:
    """Layer ``l``'s slice of an [L, ...] pool. NOTE: plain ``pool[l]``
    on a :class:`QuantPool` is NamedTuple FIELD indexing (returns the
    payload array), not a layer slice — always go through here (inside
    ``lax.scan`` the pytree leaves are sliced per layer automatically,
    so scanned model code needs no change)."""
    if isinstance(pool, QuantPool):
        return QuantPool(pool.data[l], pool.scale[l])
    return pool[l]


def pool_flat(pool: Any) -> Any:
    """Flatten the leading (L, P) axes to one L*P page axis — the view
    the ragged/prefix forwards address with per-layer table offsets. A
    reshape on both payload and scales, never a copy."""
    if isinstance(pool, QuantPool):
        d, s = pool.data, pool.scale
        return QuantPool(d.reshape((-1,) + d.shape[2:]),
                         s.reshape((-1,) + s.shape[2:]))
    return pool.reshape((-1,) + pool.shape[2:])


def pool_page_bytes(pool: Any) -> int:
    """HBM bytes ONE page id of this pool occupies ACROSS layers, scale
    rows included — prices swarmmem's warm-tier H2D model (a page's
    admission moves its slot in every layer). Accepts [L, P, ...] or
    single-layer [P, ...] pools; the divisor is always the page axis."""
    if isinstance(pool, QuantPool):
        pages = int(pool.data.shape[-4])
        return (pool.data.nbytes + pool.scale.nbytes) // max(1, pages)
    pages = int(pool.shape[-4])
    return pool.nbytes // max(1, pages)


def _quantize_pages(vals: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-page-per-head quantization of full pages.

    ``vals`` [..., ps, Hkv, D] (any float dtype) -> (int8 [..., ps, Hkv,
    D], f32 scale [..., Hkv]). scale = amax(|page|, over token-slot and
    D) / 127; all-zero pages get a harmless positive scale (payload is
    zero either way, so dequantization is exact).
    """
    v = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v), axis=(-3, -1))            # [..., Hkv]
    scale = jnp.maximum(amax, 1e-30) / _QMAX
    q = jnp.clip(jnp.round(v / scale[..., None, :, None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale


def _dequantize_pages(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """f32 view of quantized pages: data [..., ps, Hkv, D] * scale
    [..., Hkv] (broadcast per head)."""
    return q.astype(jnp.float32) * scale[..., None, :, None]


def _requant_window(old_q: jnp.ndarray, old_s: jnp.ndarray,
                    new_v: jnp.ndarray, is_new: jnp.ndarray,
                    is_keep: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Shared requantization core for INCREMENTAL page writes.

    Whole-page writes quantize fresh values exactly; appends into a
    partially-filled page instead gather the touched pages, dequantize
    the SURVIVORS (``is_keep`` — slots before the write window), zero
    the stale slots (freed-page garbage / canaries must not poison the
    new amax), splice in the new tokens (``is_new``), and requantize the
    whole page. Requantizing an unchanged full page is idempotent (its
    amax slot re-rounds to +-127 exactly); when a new token raises the
    page amax, survivors re-round under the larger scale — a bounded,
    tolerance-tested error documented in README's quantization notes.

    ``old_q`` [..., ps, Hkv, D] int8, ``old_s`` [..., Hkv] f32,
    ``new_v`` broadcastable to [..., ps, Hkv, D] (float), ``is_new`` /
    ``is_keep`` [..., ps] bool. Returns the requantized (payload, scale).
    """
    old_f = _dequantize_pages(old_q, old_s)
    vals = jnp.where(is_new[..., None, None], new_v.astype(jnp.float32),
                     jnp.where(is_keep[..., None, None], old_f, 0.0))
    return _quantize_pages(vals)


def pagecheck_enabled() -> bool:
    """Runtime page sanitizer flag (obs/pagecheck.py, ISSUE 13)."""
    return os.environ.get("SWARMDB_PAGECHECK", "0") not in ("", "0")


def make_page_allocator(num_pages: int, page_size: int, max_seq: int,
                        batch: int, label: Optional[str] = None) -> Any:
    """Allocator factory — the page-pool twin of ``utils/sync.py``'s
    lock factory. Flag off (default): the plain :class:`PageAllocator`,
    the *exact* object callers constructed before the sanitizer existed
    (zero overhead, type identity pinned by tests/test_pagecheck.py).
    ``SWARMDB_PAGECHECK=1``: the checked subclass that mirrors every
    custody transition into the shadow registry."""
    if pagecheck_enabled():
        from ..obs import pagecheck

        return pagecheck.CheckedPageAllocator(
            num_pages, page_size, max_seq, batch, label=label)
    return PageAllocator(num_pages, page_size, max_seq, batch)


def make_sharded_page_allocator(pages_per_shard: int, n_shards: int,
                                page_size: int, max_seq: int,
                                batch: int,
                                label: Optional[str] = None) -> Any:
    if pagecheck_enabled():
        from ..obs import pagecheck

        return pagecheck.CheckedShardedPageAllocator(
            pages_per_shard, n_shards, page_size, max_seq, batch,
            label=label)
    return ShardedPageAllocator(pages_per_shard, n_shards, page_size,
                                max_seq, batch)


#: canary pattern stamped into freed pages' K/V under the sanitizer —
#: exactly representable in bf16/f32 (2^14), never produced by a real
#: forward pass at sane scales
CANARY_VALUE = -16384.0

#: int8 pools can't hold -16384: their canary is -128, the one int8 code
#: point the quantizer never emits (payload is clipped to [-127, 127])
INT8_CANARY_VALUE = -128

#: canary for a quantized pool's SCALE slots — real scales are strictly
#: positive by construction, so a write-after-free that recomputes a
#: page's scale always trips this even if the int8 payload collides
SCALE_CANARY_VALUE = -1.0


def canary_for(dtype: Any) -> float:
    """Dtype-derived canary value: the float canary where it's exactly
    representable, int8's reserved -128 code point on quantized pools."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        return float(INT8_CANARY_VALUE)
    return CANARY_VALUE


def canary_fill(k_pages: Any, v_pages: Any,
                page_ids: Sequence[int],
                value: Optional[float] = None) -> Tuple[Any, Any]:
    """Poison freed pages' device K/V with the canary (sanitizer-only
    path — an eager scatter per reclaim batch; the flag-off path never
    calls this). Quantized pools get BOTH slots poisoned: -128 in the
    int8 payload and -1.0 in the scale row."""
    ids = jnp.asarray(np.asarray(page_ids, np.int32))
    if isinstance(k_pages, QuantPool):
        dv = int(value) if value is not None else INT8_CANARY_VALUE
        k_pages = QuantPool(
            k_pages.data.at[:, ids].set(jnp.int8(dv)),
            k_pages.scale.at[:, ids].set(SCALE_CANARY_VALUE))
        v_pages = QuantPool(
            v_pages.data.at[:, ids].set(jnp.int8(dv)),
            v_pages.scale.at[:, ids].set(SCALE_CANARY_VALUE))
        return k_pages, v_pages
    fv = value if value is not None else canary_for(k_pages.dtype)
    k_pages = k_pages.at[:, ids].set(fv)
    v_pages = v_pages.at[:, ids].set(fv)
    return k_pages, v_pages


def canary_check(k_pages: Any, v_pages: Any,
                 page_ids: Sequence[int],
                 value: Optional[float] = None) -> List[int]:
    """Page ids whose canary was OVERWRITTEN between free and
    re-allocation (a write-after-free landed in the pool). One host
    sync per verified allocation — sanitizer-only path. Quantized pools
    verify payload AND scale slots (a crime that rewrites either is
    caught)."""
    ids = np.asarray(page_ids, np.int32)
    if ids.size == 0:
        return []
    quant = isinstance(k_pages, QuantPool)
    if quant:
        dv = int(value) if value is not None else INT8_CANARY_VALUE
        kc = np.asarray(jax.device_get(k_pages.data[:, ids]))
        vc = np.asarray(jax.device_get(v_pages.data[:, ids]))
        ks = np.asarray(jax.device_get(k_pages.scale[:, ids]))
        vs = np.asarray(jax.device_get(v_pages.scale[:, ids]))
        bad: List[int] = []
        for i, p in enumerate(ids):
            ok = (np.all(kc[:, i] == dv) and np.all(vc[:, i] == dv)
                  and np.all(ks[:, i] == SCALE_CANARY_VALUE)
                  and np.all(vs[:, i] == SCALE_CANARY_VALUE))
            if not ok:
                bad.append(int(p))
        return bad
    fv = value if value is not None else canary_for(k_pages.dtype)
    kc = np.asarray(jax.device_get(k_pages[:, ids]))
    vc = np.asarray(jax.device_get(v_pages[:, ids]))
    bad = []
    for i, p in enumerate(ids):
        if not (np.all(kc[:, i] == fv) and np.all(vc[:, i] == fv)):
            bad.append(int(p))
    return bad


def pages_per_slot(max_seq: int, page_size: int) -> int:
    return -(-max_seq // page_size)  # ceil


def init_paged_kv_cache(
    n_layers: int,
    num_pages: int,
    page_size: int,
    n_kv_heads: int,
    head_dim: int,
    batch: int,
    max_seq: int,
    dtype: Optional[jnp.dtype] = None,
) -> PagedCache:
    """Zeroed page pool + all-trash page table. ``num_pages`` INCLUDES the
    reserved trash page 0.

    ``dtype=None`` (the service default) resolves SWARMDB_KV_DTYPE:
    f32/bf16 give plain pools of that dtype, int8 gives :class:`QuantPool`
    entries under the same ``{"k", "v"}`` keys (int8 payload + zeroed f32
    scale rows — zero payload x any scale dequantizes to zero, matching
    the unquantized zero-init)."""
    if dtype is None:
        dtype = KV_DTYPES[kv_dtype_name()]
    shape = (n_layers, num_pages, page_size, n_kv_heads, head_dim)
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        def _qpool() -> QuantPool:
            return QuantPool(
                jnp.zeros(shape, jnp.int8),
                jnp.zeros((n_layers, num_pages, n_kv_heads), jnp.float32))

        return {
            "k": _qpool(),
            "v": _qpool(),
            "page_table": jnp.zeros(
                (batch, pages_per_slot(max_seq, page_size)), jnp.int32
            ),
            "pos0": jnp.zeros((batch,), jnp.int32),
        }
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "page_table": jnp.zeros(
            (batch, pages_per_slot(max_seq, page_size)), jnp.int32
        ),
        # per-row RoPE offset: rope position = logical lane position +
        # pos0. Zero for ordinary requests; rolling-KV conversations
        # (StreamingLLM-style front-page drop) advance it so kept pages'
        # K — rope'd at their original absolute positions — stay
        # consistent with future queries
        "pos0": jnp.zeros((batch,), jnp.int32),
    }


def paged_write_decode(
    k_pages: jnp.ndarray,   # [P, ps, Hkv, D] (single layer)
    v_pages: jnp.ndarray,
    k: jnp.ndarray,         # [B, 1, Hkv, D]
    v: jnp.ndarray,
    positions: jnp.ndarray,  # [B, 1] absolute write positions
    page_table: jnp.ndarray,  # [B, maxp]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter one decode token per slot into its page.

    Writes at positions >= maxp*ps (chunk overshoot on full lanes; the
    engine keeps max_seq a page multiple so this cap == max_seq) and
    writes from inactive slots (zeroed table rows) both land in trash
    page 0 — see module invariants.
    """
    ps = pool_data(k_pages).shape[1]
    maxp = page_table.shape[1]
    pos = positions[:, 0]                                # [B]
    col = jnp.minimum(pos // ps, maxp - 1)
    page = jnp.take_along_axis(page_table, col[:, None], axis=1)[:, 0]
    page = jnp.where(pos < maxp * ps, page, 0)           # overshoot -> trash
    off = pos % ps
    if isinstance(k_pages, QuantPool):
        # one-column requant window: slots before pos survive, the new
        # token lands at off, later slots are stale garbage -> zeroed
        slots = jnp.arange(ps, dtype=jnp.int32)[None, :]         # [1, ps]
        slot_pos = (col * ps)[:, None] + slots                   # [B, ps]
        is_new = slots == off[:, None]
        is_keep = slot_pos < pos[:, None]
        out = []
        for pool, tok in ((k_pages, k), (v_pages, v)):
            q, s = _requant_window(pool.data[page], pool.scale[page],
                                   tok[:, 0][:, None], is_new, is_keep)
            out.append(QuantPool(pool.data.at[page].set(q),
                                 pool.scale.at[page].set(s)))
        return out[0], out[1]
    k_pages = k_pages.at[page, off].set(k[:, 0].astype(k_pages.dtype))
    v_pages = v_pages.at[page, off].set(v[:, 0].astype(v_pages.dtype))
    return k_pages, v_pages


def paged_gather_kv(
    k_pages: jnp.ndarray,   # [P, ps, Hkv, D] (single layer)
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, maxp]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense [B, maxp*ps, Hkv, D] view of each slot's pages (XLA fallback
    attention input; bandwidth equals the dense cache, so use the Pallas
    ragged kernel on TPU for the savings)."""
    B, maxp = page_table.shape
    if isinstance(k_pages, QuantPool):
        # fallback dequant site: gather payload + scales, expand to a
        # dense f32 view (the XLA reference attends full precision; the
        # Pallas kernels dequantize per tile instead)
        ps = k_pages.data.shape[1]
        kg = _dequantize_pages(k_pages.data[page_table],
                               k_pages.scale[page_table])
        vg = _dequantize_pages(v_pages.data[page_table],
                               v_pages.scale[page_table])
        new_shape = (B, maxp * ps) + k_pages.data.shape[2:]
        return kg.reshape(new_shape), vg.reshape(new_shape)
    ps = k_pages.shape[1]
    kg = k_pages[page_table]  # [B, maxp, ps, Hkv, D]
    vg = v_pages[page_table]
    new_shape = (B, maxp * ps) + k_pages.shape[2:]
    return kg.reshape(new_shape), vg.reshape(new_shape)


def paged_insert_prefill(
    k_pages: jnp.ndarray,    # [L, P, ps, Hkv, D]
    v_pages: jnp.ndarray,
    dense_k: jnp.ndarray,    # [L, Bp, bucket, Hkv, D] prefill temp cache
    dense_v: jnp.ndarray,
    target_pages: jnp.ndarray,  # [n, bucket/ps] int32 page ids per admitted row
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter the first n rows of a dense bucket prefill cache into pages.

    ``bucket`` must be a multiple of the page size (buckets are powers of
    two >= page_size by construction). REFERENCE implementation: the
    engine's hot path performs this scatter inside its fused paged
    prefill (`Engine._prefill_paged_fused`); tests check that fused path
    against this standalone form."""
    L = pool_data(k_pages).shape[0]
    ps = pool_data(k_pages).shape[2]
    n, chunks = target_pages.shape
    bucket = dense_k.shape[2]
    assert bucket == chunks * ps, (bucket, chunks, ps)
    tail = dense_k.shape[3:]
    # [L, n, chunks, ps, Hkv, D] -> scatter chunks into the page axis
    kc = dense_k[:, :n].reshape((L, n * chunks, ps) + tail)
    vc = dense_v[:, :n].reshape((L, n * chunks, ps) + tail)
    flat = target_pages.reshape(-1)  # [n*chunks]
    k_pages = pool_insert_pages(k_pages, flat, kc)
    v_pages = pool_insert_pages(v_pages, flat, vc)
    return k_pages, v_pages


def pool_insert_pages(pool: Any, flat_ids: jnp.ndarray,
                      dense_pages: jnp.ndarray) -> Any:
    """WHOLE-page insert: ``dense_pages`` [L, n, ps, Hkv, D] full
    precision -> pool pages at ``flat_ids`` [n]. On quantized pools this
    is the EXACT quantization path (per-page amax over the fresh values
    only — no survivor requant); the engine's fused paged prefill and
    prefix-insert closures route their page scatters through here."""
    if isinstance(pool, QuantPool):
        q, s = _quantize_pages(dense_pages)
        return QuantPool(pool.data.at[:, flat_ids].set(q),
                         pool.scale.at[:, flat_ids].set(s))
    return pool.at[:, flat_ids].set(dense_pages.astype(pool.dtype))


def pool_gather_pages(pool: Any, ids: Sequence[int]) -> Any:
    """RAW payload of ``ids`` pages across all layers, as host numpy.

    The warm-tier spill format (ISSUE 19): pages leave the device at
    STORAGE width — int8 payload + f32 scales on quantized pools (the
    page spills at half the bf16 byte cost), pool dtype verbatim on
    plain pools. Reinserting the same payload via :func:`pool_insert_raw`
    is bit-identical: no dequant/requant round trip happens in either
    direction.

    Returns ``(data [L, n, ps, Hkv, D], scale [L, n, Hkv])`` numpy
    tuple for :class:`QuantPool`, else a single ``[L, n, ps, Hkv, D]``
    numpy array. Caller must run this on the engine thread — the gather
    reads pool buffers that engine jits donate.
    """
    n = len(ids)
    # pad the index to the next power of two with the trash page (0):
    # an advanced-index gather compiles per index LENGTH, and demotion
    # victims come in arbitrary page counts — unpadded, every new count
    # is a fresh XLA compile on the admission/eviction path (measured
    # as multi-ms stalls riding warm-hit TTFT). Pow2 padding bounds the
    # variants at ~log2(pool) per dtype; the pad rows are sliced off
    # host-side below.
    padded = max(1, 1 << (n - 1).bit_length()) if n else 1
    idx = np.zeros(padded, np.int32)
    idx[:n] = list(ids)
    if isinstance(pool, QuantPool):
        return (np.asarray(jax.device_get(pool.data[:, idx]))[:, :n],
                np.asarray(jax.device_get(pool.scale[:, idx]))[:, :n])
    return np.asarray(jax.device_get(pool[:, idx]))[:, :n]


def pool_insert_raw(pool: Any, flat_ids: jnp.ndarray, payload: Any) -> Any:
    """Reinsert a :func:`pool_gather_pages` payload at ``flat_ids``.

    The warm-tier promotion primitive: payload is already at storage
    width, so the insert is a plain ``.at[].set`` — the EXACT bytes that
    left the pool come back (quantized pools: int8 + scales set
    separately, no requantization). jit-safe; the engine wraps this in a
    donated dispatch so promotion rides the same buffer-reuse path as
    prefill inserts.
    """
    if isinstance(pool, QuantPool):
        q, s = payload
        return QuantPool(
            pool.data.at[:, flat_ids].set(jnp.asarray(q, jnp.int8)),
            pool.scale.at[:, flat_ids].set(jnp.asarray(s, jnp.float32)))
    return pool.at[:, flat_ids].set(jnp.asarray(payload, pool.dtype))


def paged_write_chunk(
    k_pages: jnp.ndarray,    # [L, P, ps, Hkv, D]
    v_pages: jnp.ndarray,
    chunk_k: jnp.ndarray,    # [L, B, Kc, Hkv, D] a finished decode chunk
    chunk_v: jnp.ndarray,
    start_positions: jnp.ndarray,  # [B] absolute position of chunk step 0
    page_table: jnp.ndarray,       # [B, maxp]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold a finished decode chunk's K/V into the page pool — ONE bulk
    scatter per chunk instead of one per step (the paged counterpart of
    ops/layers.merge_chunk_kv).

    Same trash-page invariants as :func:`paged_write_decode`: positions
    past the table's coverage and rows with zeroed (retired/inactive)
    table entries land in trash page 0 and are never read.
    """
    L = pool_data(k_pages).shape[0]
    ps = pool_data(k_pages).shape[2]
    B, maxp = page_table.shape
    Kc = chunk_k.shape[2]
    if isinstance(k_pages, QuantPool):
        # requant window: the chunk spans at most ceil((ps-1+Kc)/ps)
        # consecutive page columns from start//ps. Survivors are slots
        # before start; slots past the chunk end are stale -> zeroed.
        npc = min(maxp, (Kc + 2 * ps - 2) // ps)
        start = start_positions.astype(jnp.int32)
        c0 = jnp.clip(start // ps, 0, maxp - 1)                  # [B]
        cols = c0[:, None] + jnp.arange(npc, dtype=jnp.int32)    # [B, npc]
        colc = jnp.clip(cols, 0, maxp - 1)
        page = jnp.take_along_axis(page_table, colc, axis=1)     # [B, npc]
        touched = (cols < maxp) & (cols * ps < (start + Kc)[:, None])
        page = jnp.where(touched, page, 0)                       # -> trash
        slots = jnp.arange(ps, dtype=jnp.int32)
        slot_pos = cols[..., None] * ps + slots                  # [B, npc, ps]
        t = slot_pos - start[:, None, None]                      # chunk index
        is_new = (t >= 0) & (t < Kc) & (slot_pos < maxp * ps)
        is_keep = slot_pos < start[:, None, None]
        tc = jnp.clip(t, 0, Kc - 1)
        bidx = jnp.arange(B)[:, None, None]
        pf = page.reshape(-1)                                    # [B*npc]
        out = []
        for pool, chunk in ((k_pages, chunk_k), (v_pages, chunk_v)):
            new_v = chunk[:, bidx, tc]           # [L, B, npc, ps, Hkv, D]
            q, s = _requant_window(pool.data[:, page],
                                   pool.scale[:, page],
                                   new_v, is_new, is_keep)
            out.append(QuantPool(
                pool.data.at[:, pf].set(
                    q.reshape((L, B * npc) + q.shape[3:])),
                pool.scale.at[:, pf].set(
                    s.reshape((L, B * npc) + s.shape[3:]))))
        return out[0], out[1]
    pos = start_positions[:, None] + jnp.arange(Kc, dtype=jnp.int32)[None, :]
    col = jnp.minimum(pos // ps, maxp - 1)
    page = jnp.take_along_axis(page_table, col, axis=1)   # [B, Kc]
    page = jnp.where(pos < maxp * ps, page, 0)            # overshoot -> trash
    off = pos % ps
    pf, of = page.reshape(-1), off.reshape(-1)            # [B*Kc]
    tail = chunk_k.shape[3:]
    kc = chunk_k.reshape((L, B * Kc) + tail)
    vc = chunk_v.reshape((L, B * Kc) + tail)
    k_pages = k_pages.at[:, pf, of].set(kc.astype(k_pages.dtype))
    v_pages = v_pages.at[:, pf, of].set(vc.astype(v_pages.dtype))
    return k_pages, v_pages


def paged_write_ragged(
    k_pages: jnp.ndarray,    # [L, P, ps, Hkv, D]
    v_pages: jnp.ndarray,
    sfx_k: jnp.ndarray,      # [L, W, Hkv, D] packed wave K (stream order)
    sfx_v: jnp.ndarray,
    tok_row: jnp.ndarray,    # [W] int32 owning wave row (>= R = padding)
    tok_pos: jnp.ndarray,    # [W] int32 absolute position within the row
    row_tables: jnp.ndarray,  # [R, maxp] int32 page ids per wave row
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Positional per-token scatter of a PACKED ragged prefill wave's K/V
    into the page pool: stream token t lands at page
    ``row_tables[tok_row[t], tok_pos[t] // ps]`` offset ``tok_pos[t] %
    ps``. Padding tokens (row id out of range, or positions past the
    table's coverage) land in trash page 0 — the same invariants as
    :func:`paged_write_decode` / :func:`paged_write_chunk`."""
    ps = pool_data(k_pages).shape[2]
    R, maxp = row_tables.shape
    if isinstance(k_pages, QuantPool):
        return _paged_write_ragged_quant(
            k_pages, v_pages, sfx_k, sfx_v, tok_row, tok_pos, row_tables)
    col = jnp.clip(tok_pos // ps, 0, maxp - 1)
    row = jnp.clip(tok_row, 0, R - 1)
    page = row_tables[row, col]                          # [W]
    dead = (tok_pos >= maxp * ps) | (tok_row < 0) | (tok_row >= R)
    page = jnp.where(dead, 0, page)
    off = jnp.where(dead, 0, tok_pos % ps)
    k_pages = k_pages.at[:, page, off].set(sfx_k.astype(k_pages.dtype))
    v_pages = v_pages.at[:, page, off].set(sfx_v.astype(v_pages.dtype))
    return k_pages, v_pages


def _paged_write_ragged_quant(
    k_pages: "QuantPool", v_pages: "QuantPool",
    sfx_k: jnp.ndarray, sfx_v: jnp.ndarray,
    tok_row: jnp.ndarray, tok_pos: jnp.ndarray,
    row_tables: jnp.ndarray,
) -> Tuple["QuantPool", "QuantPool"]:
    """Quantized ragged wave write: per-row requant window.

    Each wave row's tokens are CONTIGUOUS positions, so a row touches at
    most ceil(W/ps)+1 consecutive page columns starting at its first
    token's column (derived on-device via a segment-min over ``tok_pos``
    — the signature carries no per-row lengths). Survivors are slots
    before the row's first wave token (earlier chunks of a split prompt
    in the same partially-filled page); slots past the row's last wave
    token are stale -> zeroed. Prefix-cache HIT pages are page-aligned
    and sit strictly before every window, so shared pages are never
    rewritten. Untouched window columns and dead/padding rows route to
    trash page 0. Write amplification vs the unquantized scatter is
    ~R x window pages (the wave path is compute-bound; documented in
    README's quantization notes).
    """
    L = k_pages.data.shape[0]
    ps = k_pages.data.shape[2]
    tail = k_pages.data.shape[3:]                         # (Hkv, D)
    R, maxp = row_tables.shape
    W = tok_pos.shape[0]
    big = maxp * ps
    live = ((tok_row >= 0) & (tok_row < R)
            & (tok_pos >= 0) & (tok_pos < big))
    rowc = jnp.clip(tok_row, 0, R - 1)
    row_min = jnp.full((R,), big, jnp.int32).at[rowc].min(
        jnp.where(live, tok_pos, big))
    row_max = jnp.full((R,), -1, jnp.int32).at[rowc].max(
        jnp.where(live, tok_pos, -1))
    npc = min(maxp, -(-W // ps) + 1)
    c0 = jnp.clip(row_min // ps, 0, maxp - 1)             # [R]
    cols = c0[:, None] + jnp.arange(npc, dtype=jnp.int32)  # [R, npc]
    colc = jnp.clip(cols, 0, maxp - 1)
    page = jnp.take_along_axis(row_tables, colc, axis=1)  # [R, npc]
    touched = (cols < maxp) & (cols * ps <= row_max[:, None])
    page = jnp.where(touched, page, 0)                    # -> trash
    # stage the packed wave into per-row dense windows (scatter; padding
    # tokens and out-of-window strays are dropped via OOB row index)
    rel = tok_pos - c0[rowc] * ps
    okw = live & (rel >= 0) & (rel < npc * ps)
    sr = jnp.where(okw, rowc, R)                          # R = dropped
    srel = jnp.where(okw, rel, 0)
    is_new = jnp.zeros((R, npc * ps), bool).at[sr, srel].set(
        True, mode="drop").reshape(R, npc, ps)
    slots = jnp.arange(ps, dtype=jnp.int32)
    slot_pos = cols[..., None] * ps + slots               # [R, npc, ps]
    is_keep = slot_pos < row_min[:, None, None]
    pf = page.reshape(-1)                                 # [R*npc]
    out = []
    for pool, sfx in ((k_pages, sfx_k), (v_pages, sfx_v)):
        stage = jnp.zeros((L, R, npc * ps) + tail, jnp.float32)
        stage = stage.at[:, sr, srel].set(
            sfx.astype(jnp.float32), mode="drop")
        new_v = stage.reshape((L, R, npc, ps) + tail)
        q, s = _requant_window(pool.data[:, page], pool.scale[:, page],
                               new_v, is_new, is_keep)
        out.append(QuantPool(
            pool.data.at[:, pf].set(q.reshape((L, R * npc) + q.shape[3:])),
            pool.scale.at[:, pf].set(
                s.reshape((L, R * npc) + s.shape[3:]))))
    return out[0], out[1]


_set_page_table_rows = jax.jit(
    lambda pt, rows, values: pt.at[rows].set(values, mode="drop")
)


def set_page_table_rows(
    page_table: jnp.ndarray, rows, values
) -> jnp.ndarray:
    """Replace whole page-table rows (admission assigns, retirement zeroes).

    The host arrays are padded to the full batch with out-of-bounds row
    indices (dropped by the scatter): a shape per DISTINCT row count would
    compile up to max_batch variants, each a multi-second stall on the
    tunneled TPU — the round-4 paged-prefix bench collapse was exactly
    these landing in the measured window."""
    B, maxp = page_table.shape
    rows = np.asarray(rows, np.int32)
    values = np.asarray(values, np.int32).reshape(len(rows), maxp)
    n = len(rows)
    if n < B:
        pad_rows = np.full(B, B, np.int32)       # B = out of bounds -> drop
        pad_rows[:n] = rows
        pad_vals = np.zeros((B, maxp), np.int32)
        pad_vals[:n] = values
        rows, values = pad_rows, pad_vals
    return _set_page_table_rows(page_table, rows, values)


@dataclass
class _SlotPages:
    pages: List[int]


class PageAllocator:
    """Host-side page pool bookkeeping (engine admission/retirement path).

    Thread-safety: engine calls happen on the engine thread only, but the
    lock keeps stats()/external probes safe. Page 0 (trash) is never
    handed out.
    """

    def __init__(self, num_pages: int, page_size: int, max_seq: int,
                 batch: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.page_size = page_size
        self.max_seq = max_seq
        self.maxp = pages_per_slot(max_seq, page_size)
        self.num_pages = num_pages
        self._by_slot: Dict[int, _SlotPages] = {}
        self._pending_free: List[int] = []  # slot ids retired, not yet flushed
        self._lock = make_lock("ops.paged_kv.PageAllocator._lock")
        self.batch = batch
        # cumulative churn (page-grant / page-return counts): two int
        # adds under the lock the public methods already hold — the
        # /metrics per-lane churn counters read these off stats()
        self.pages_allocated_total = 0
        self.pages_freed_total = 0
        # pool generation: bumped by every reset(). Page ids held OUTSIDE
        # the allocator (the serving layer's rolling-KV registry) are only
        # valid within the generation they were handed out in — a reset
        # reclaims the whole pool, so a stale holder resuming or freeing
        # them would alias another slot's pages (ADVICE r4 medium #2).
        self.generation = 0
        # swarmmem residency ledger (ISSUE 17): page alloc/free stamps
        # piggybacked on the critical sections below. Flag off -> the
        # shared NullPool, one no-op call per hook site.
        from ..obs.memprof import memprof

        self.mem = memprof().pool(self.stats)
        self._rebuild_free()

    # -- free-list geometry (the ONLY pieces the sharded subclass swaps) -----

    # swarmlint: holds[self._lock]
    def _rebuild_free(self) -> None:
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))

    # swarmlint: holds[self._lock]
    def _take(self, slot_id: int, n: int) -> Optional[List[int]]:
        """Pop ``n`` pages usable by ``slot_id``; None if uncoverable.
        Caller holds the lock."""
        if len(self._free) < n:
            return None
        return [self._free.pop() for _ in range(n)]

    # swarmlint: holds[self._lock]
    def _give(self, page_ids: List[int]) -> None:
        """Return pages to the free list. Caller holds the lock."""
        self._free.extend(page_ids)

    def _check_prefix(self, slot_id: int, prefix_pages: List[int]) -> None:
        """Engine-bug guard hook: referenced (not owned) pages must be
        addressable by this slot. No constraint on the single pool."""

    # -- admission -----------------------------------------------------------

    def pages_needed(self, prompt_len: int, max_new: int, chunk: int) -> int:
        """Pages covering every position this request can ever WRITE:
        prompt + generated tokens + up to one chunk of overshoot, capped at
        max_seq (beyond-cap writes are trash-routed)."""
        worst = min(self.max_seq, prompt_len + max_new + chunk)
        return min(self.maxp, -(-worst // self.page_size))

    def can_allocate(self, n: int) -> bool:
        with self._lock:
            return len(self._free) >= n

    def allocate(self, slot_id: int, n: int) -> Optional[np.ndarray]:
        """Take n pages for a slot; None if the pool can't cover it.
        Returns the slot's FULL page-table row (maxp wide, trash-padded)."""
        with self._lock:
            if slot_id in self._by_slot:
                raise RuntimeError(f"slot {slot_id} already holds pages")
            pages = self._take(slot_id, n)
            if pages is None:
                return None
            self.pages_allocated_total += len(pages)
            self.mem.page_alloc(pages)
            self._by_slot[slot_id] = _SlotPages(pages)
            row = np.zeros(self.maxp, np.int32)
            row[: len(pages)] = pages
            return row

    # swarmlint: borrows[page]: prefix_pages
    def allocate_with_prefix(self, slot_id: int, prefix_pages: List[int],
                             n_fresh: int) -> Optional[np.ndarray]:
        """Row = ``prefix_pages`` (cache-custody pages the slot only
        REFERENCES — the prefix cache pins them; they are not recorded in
        ``_by_slot`` and retirement does not free them) followed by
        ``n_fresh`` newly owned pages. None if the pool can't cover the
        fresh part."""
        with self._lock:
            if slot_id in self._by_slot:
                raise RuntimeError(f"slot {slot_id} already holds pages")
            self._check_prefix(slot_id, prefix_pages)
            fresh = self._take(slot_id, n_fresh)
            if fresh is None:
                return None
            self.pages_allocated_total += len(fresh)
            self.mem.page_alloc(fresh)
            self._by_slot[slot_id] = _SlotPages(fresh)
            row = np.zeros(self.maxp, np.int32)
            pages = list(prefix_pages) + fresh
            row[: len(pages)] = pages
            return row

    def transfer_to_cache(self, slot_id: int, page_ids: List[int]) -> None:
        """Remove ``page_ids`` from a slot's OWNED set: custody moves to
        the prefix cache (registration), so retirement won't free them."""
        with self._lock:
            sp = self._by_slot.get(slot_id)
            if sp is not None:
                drop = set(page_ids)
                sp.pages = [p for p in sp.pages if p not in drop]

    def add_free(self, page_ids: List[int]) -> None:
        """Return cache-evicted pages to the pool (prefix-cache eviction
        path; the caller guarantees no live slot references them)."""
        with self._lock:
            self.pages_freed_total += len(page_ids)
            self.mem.page_free(page_ids)
            self._give(page_ids)

    def reserve(self, n: int) -> List[int]:
        """Withdraw up to ``n`` free pages from circulation (serving
        chaos: pool-squeeze fault). Reserved pages are never referenced
        by any table row — the fault only starves admission, exactly
        like a burst of long-lived occupants. Return them with
        :meth:`add_free` (the heal path); a reset() reclaims them
        implicitly (the ids die with the generation)."""
        with self._lock:
            take = min(n, len(self._free))
            out = [self._free.pop() for _ in range(take)]
            self.mem.page_alloc(out)
            return out

    def free_count(self, slot_id: Optional[int] = None) -> int:
        """Free pages available — to ``slot_id`` if given (the sharded
        allocator restricts each slot to its shard's sub-pool)."""
        with self._lock:
            return len(self._free)

    def pages_for(self, slot_id: int) -> List[int]:
        with self._lock:
            sp = self._by_slot.get(slot_id)
            return list(sp.pages) if sp else []

    # -- retirement ----------------------------------------------------------

    def mark_retired(self, slot_id: int) -> None:
        """Queue a slot's pages for reclaim. The pages stay OWNED (absorbing
        end-of-chunk garbage writes) until flush_frees() zeroes the slot's
        table row and returns them to the pool."""
        with self._lock:
            if slot_id in self._by_slot:
                self._pending_free.append(slot_id)

    def take_pending_frees(self) -> List[int]:
        """Drain the retired-slot queue WITHOUT freeing pages yet — the
        caller zeroes the slots' table rows on device first (possibly
        mirroring that update to pod workers), then calls
        :meth:`release_taken`. Split out of flush_frees so the engine can
        route the device update through its multihost mirror."""
        with self._lock:
            pending, self._pending_free = self._pending_free, []
        return pending

    def release_taken(self, pending: List[int]) -> None:
        """Free the pages of slots drained by take_pending_frees — only
        AFTER their table-row zeroing is enqueued on device: the device
        order (zero row -> later writes by a new owner) is program order."""
        with self._lock:
            for slot_id in pending:
                sp = self._by_slot.pop(slot_id, None)
                if sp is not None:
                    self.pages_freed_total += len(sp.pages)
                    self.mem.page_free(sp.pages)
                    self._give(list(reversed(sp.pages)))

    def requeue_pending(self, pending: List[int]) -> None:
        """Put a drained retirement batch BACK on the pending queue: the
        caller's table-row zeroing dispatch failed, so the pages must
        not be freed (their rows may still reference them) but must not
        be forgotten either — the next admission round retries. Found
        by swarmlint SWL801: a drained batch held across a raising
        dispatch with no requeue leaked its pages forever."""
        with self._lock:
            self._pending_free[:0] = pending

    def flush_frees(self, page_table: jnp.ndarray) -> jnp.ndarray:
        """Zero retired slots' table rows on device, then free their pages.
        Call at the START of each admission round."""
        pending = self.take_pending_frees()
        if not pending:
            return page_table
        rows = np.asarray(pending, np.int32)
        zeros = np.zeros((len(pending), self.maxp), np.int32)
        try:
            page_table = set_page_table_rows(page_table, rows, zeros)
        except Exception:
            # the rows were never zeroed: freeing now would reopen the
            # stale-table/reused-page race, dropping the batch would
            # leak it (SWL801) — requeue for the next round
            self.requeue_pending(pending)
            raise
        self.release_taken(pending)
        return page_table

    # -- DP-sharding hooks (no-ops for the single-pool allocator) ------------

    def usable_prefix(self, slot_id: int, hits: List[int]) -> int:
        """How many of ``hits`` (a prefix-cache chain, in order) this slot
        may reference. The single pool has no locality constraint."""
        return len(hits)

    def shard_of(self, slot_id: int) -> int:
        return 0

    def slot_capacity(self) -> int:
        """Most pages any single request can ever be granted — the
        admission-feasibility bound Engine.submit checks (a request
        needing more would wedge the no-skip-ahead admission queue
        forever)."""
        return self.num_pages - 1

    def evictable(self, slot_id: int):
        """Predicate for prefix-cache eviction on behalf of ``slot_id``:
        only pages that could actually cover its shortfall qualify. The
        single pool accepts any page (None = no filter)."""
        return None

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_pages": self.num_pages,
                "free_pages": len(self._free),
                "live_slots": len(self._by_slot),
                "page_size": self.page_size,
                "pages_allocated_total": self.pages_allocated_total,
                "pages_freed_total": self.pages_freed_total,
            }

    def reset(self) -> None:
        with self._lock:
            # bump BEFORE rebuilding the free list: a racing epoch check
            # must never observe (old generation, rebuilt pool)
            self.generation += 1
            self._rebuild_free()
            self._by_slot.clear()
            self._pending_free.clear()
            self.mem.pool_reset()


class ShardedPageAllocator(PageAllocator):
    """Slot→shard-affine page pool for DP-sharded paged serving
    (parallel/serving.py ``build_serving_engine(paged=True)``).

    Global page ids are STRIPED per data shard: shard ``k`` owns
    ``[k*Pl, (k+1)*Pl)`` (``Pl = pages_per_shard``), and slot ``s``
    belongs to shard ``s // (batch / n_shards)``. Every page a slot's
    table row references therefore lives in that slot's shard of the
    device pool (the pool array shards its PAGE axis over ``data``), so
    the shard_map'd decode step's gathers and scatters are purely
    shard-local — the SPMD decode program contains zero collectives and
    scales linearly over the data axis.

    Id ``k*Pl`` is shard-``k``'s TRASH page, never handed out: inside the
    shard_map the table is localized as ``clip(table - k*Pl, 0, Pl-1)``,
    which maps this shard's ids to ``[1, Pl)``, the global trash 0 (and
    any zeroed/retired row) to local 0, and can never alias a foreign
    shard's pages because foreign ids are simply not reachable from this
    shard's table rows.

    Inherits all retirement/custody bookkeeping (``_by_slot``,
    ``flush_frees``) from the base class — only the free-list geometry
    and the prefix-locality check change.
    """

    def __init__(self, pages_per_shard: int, n_shards: int, page_size: int,
                 max_seq: int, batch: int) -> None:
        if n_shards < 1 or batch % n_shards:
            raise ValueError(
                f"batch {batch} must divide over n_shards {n_shards}")
        if pages_per_shard < 2:
            raise ValueError("need >= 2 pages per shard (one is trash)")
        # geometry attrs BEFORE super().__init__ — it calls the overridden
        # _rebuild_free, which needs them
        self.n_shards = n_shards
        self.pages_per_shard = pages_per_shard
        self.slots_per_shard = batch // n_shards
        super().__init__(pages_per_shard * n_shards, page_size, max_seq,
                         batch)

    # -- free-list geometry (everything else is inherited) -------------------

    def _rebuild_free(self) -> None:
        # per-shard stacks; ids k*Pl (per-shard trash) are never free
        pl = self.pages_per_shard
        self._free_by_shard: List[List[int]] = [
            list(range((k + 1) * pl - 1, k * pl, -1))
            for k in range(self.n_shards)
        ]

    def _take(self, slot_id: int, n: int) -> Optional[List[int]]:
        free = self._free_by_shard[self.shard_of(slot_id)]
        if len(free) < n:
            return None
        return [free.pop() for _ in range(n)]

    def _give(self, page_ids: List[int]) -> None:
        for p in page_ids:
            self._free_by_shard[self.shard_of_page(p)].append(p)

    def _check_prefix(self, slot_id: int, prefix_pages: List[int]) -> None:
        shard = self.shard_of(slot_id)
        if any(self.shard_of_page(p) != shard for p in prefix_pages):
            # engine bug guard: usable_prefix() must have trimmed these
            raise RuntimeError(
                f"slot {slot_id} (shard {shard}) referencing foreign-"
                f"shard prefix pages {prefix_pages}")

    # -- shard geometry ------------------------------------------------------

    def shard_of(self, slot_id: int) -> int:
        return min(self.n_shards - 1, slot_id // self.slots_per_shard)

    def shard_of_page(self, page_id: int) -> int:
        return min(self.n_shards - 1, page_id // self.pages_per_shard)

    def slot_capacity(self) -> int:
        # a slot can only ever draw from its own shard's sub-pool
        return self.pages_per_shard - 1

    def evictable(self, slot_id: int):
        shard = self.shard_of(slot_id)
        return lambda p: self.shard_of_page(p) == shard

    def can_allocate(self, n: int) -> bool:
        with self._lock:
            return any(len(f) >= n for f in self._free_by_shard)

    def free_count(self, slot_id: Optional[int] = None) -> int:
        with self._lock:
            if slot_id is None:
                return sum(len(f) for f in self._free_by_shard)
            return len(self._free_by_shard[self.shard_of(slot_id)])

    def usable_prefix(self, slot_id: int, hits: List[int]) -> int:
        """Truncate a prefix-chain match at the first page outside the
        slot's shard: the shard_map'd decode can only address its own
        sub-pool, so a cross-shard reference would localize to a wrong
        page. (Chains register whole per-shard, so in practice a chain
        is either fully usable or fully foreign.)"""
        shard = self.shard_of(slot_id)
        n = 0
        for p in hits:
            if self.shard_of_page(p) != shard:
                break
            n += 1
        return n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_pages": self.num_pages,
                "free_pages": sum(len(f) for f in self._free_by_shard),
                "free_by_shard": [len(f) for f in self._free_by_shard],
                "live_slots": len(self._by_slot),
                "page_size": self.page_size,
                "n_shards": self.n_shards,
                "pages_allocated_total": self.pages_allocated_total,
                "pages_freed_total": self.pages_freed_total,
            }


# --- kerncheck: descriptor + scatter-replay sanitizer (obs/kerncheck) ---
# SWARMDB_KERNCHECK=1 wraps the ragged wave scatter so every concrete
# call first audits its descriptors (live-token page OOB, trash-page
# targets, duplicate (page, offset) cells) and then replays the scatter
# in numpy against the returned pool. Flag off this block never runs —
# the module exports the plain function object (type identity pinned by
# tests/test_kernelcheck.py).
if os.environ.get("SWARMDB_KERNCHECK", "0") == "1":
    from ..obs.kerncheck import checked_paged_write_ragged

    paged_write_ragged = checked_paged_write_ragged(paged_write_ragged)
