"""NativeBroker — ctypes binding over the C++ partitioned log engine.

Implements the same ``Broker`` ABC as ``LocalBroker`` on top of
``cpp/libswarmbroker.so`` (built by ``cpp/Makefile``; ``build_native()``
invokes make on demand). This is the in-tree replacement for the
reference's only native dependency, librdkafka + the external
Kafka/Zookeeper containers (SURVEY §2.3; reference ` main.py:12-18`,
`dockerfile-compose.yaml:5-48`): durable partitioned logs, consumer-group
offsets, retention, and blocking consumption — no external brokers.
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import subprocess
import tempfile
import threading
from typing import Dict, List, Optional

import numpy as np

from .base import Broker, BrokerError, Record, TopicMeta, UnknownTopicError

_CPP_DIR = os.path.join(os.path.dirname(__file__), "cpp")
# SWARMDB_BROKER_LIB overrides the library path — used by the TSAN job
# (scripts/tsan_stress.sh) to load the -fsanitize=thread build.
_LIB_PATH = os.environ.get(
    "SWARMDB_BROKER_LIB", os.path.join(_CPP_DIR, "libswarmbroker.so")
)

_REC_HDR = struct.Struct("<qdii")  # offset, ts, key_len, val_len


def build_native() -> bool:
    """Build (or freshen) the shared library; True if it is now present.

    Always invokes make when targeting the in-tree library — the Makefile's
    ``broker.cpp`` dependency makes it a no-op when fresh, and it guarantees
    edits to broker.cpp are never shadowed by a stale binary (the .so is
    gitignored, never committed). A custom SWARMDB_BROKER_LIB (e.g. the TSAN
    build) is loaded as-is.
    """
    if _LIB_PATH != os.path.join(_CPP_DIR, "libswarmbroker.so"):
        return os.path.exists(_LIB_PATH)
    try:
        subprocess.run(
            ["make", "-s", "libswarmbroker.so"],
            cwd=_CPP_DIR, check=True, capture_output=True, timeout=120,
        )
    except Exception:
        pass  # no toolchain: fall back to an existing binary if present
    return os.path.exists(_LIB_PATH)


def native_available(autobuild: bool = True) -> bool:
    if autobuild:
        return build_native()
    return os.path.exists(_LIB_PATH)


_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not native_available():
        raise ImportError("libswarmbroker.so not built (run make in broker/cpp)")
    lib = ctypes.CDLL(_LIB_PATH)
    c = ctypes.c_char_p
    lib.swb_open.restype = ctypes.c_void_p
    lib.swb_open.argtypes = [c]
    lib.swb_open2.restype = ctypes.c_void_p
    lib.swb_open2.argtypes = [c, ctypes.c_int]
    lib.swb_durable_offset.restype = ctypes.c_longlong
    lib.swb_durable_offset.argtypes = [ctypes.c_void_p, c, ctypes.c_int]
    lib.swb_wait_durable.restype = ctypes.c_int
    lib.swb_wait_durable.argtypes = [ctypes.c_void_p, c, ctypes.c_int,
                                     ctypes.c_longlong, ctypes.c_double]
    lib.swb_shutdown.argtypes = [ctypes.c_void_p]
    lib.swb_create_topic.restype = ctypes.c_int
    lib.swb_create_topic.argtypes = [ctypes.c_void_p, c, ctypes.c_int,
                                     ctypes.c_longlong]
    lib.swb_list_topics_json.restype = ctypes.POINTER(ctypes.c_char)
    lib.swb_list_topics_json.argtypes = [ctypes.c_void_p]
    lib.swb_free_buf.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.swb_create_partitions.restype = ctypes.c_int
    lib.swb_create_partitions.argtypes = [ctypes.c_void_p, c, ctypes.c_int]
    lib.swb_append.restype = ctypes.c_longlong
    lib.swb_append.argtypes = [ctypes.c_void_p, c, ctypes.c_int, c,
                               ctypes.c_int, c, ctypes.c_int, ctypes.c_double]
    lib.swb_fetch.restype = ctypes.c_longlong
    lib.swb_fetch.argtypes = [ctypes.c_void_p, c, ctypes.c_int,
                              ctypes.c_longlong, ctypes.c_int,
                              ctypes.POINTER(ctypes.c_uint8),
                              ctypes.c_longlong,
                              ctypes.POINTER(ctypes.c_int)]
    lib.swb_end_offset.restype = ctypes.c_longlong
    lib.swb_end_offset.argtypes = [ctypes.c_void_p, c, ctypes.c_int]
    lib.swb_begin_offset.restype = ctypes.c_longlong
    lib.swb_begin_offset.argtypes = [ctypes.c_void_p, c, ctypes.c_int]
    lib.swb_wait_for_data.restype = ctypes.c_int
    lib.swb_wait_for_data.argtypes = [ctypes.c_void_p, c, ctypes.c_int,
                                      ctypes.c_longlong, ctypes.c_double]
    lib.swb_commit_offset.argtypes = [ctypes.c_void_p, c, c, ctypes.c_int,
                                      ctypes.c_longlong]
    lib.swb_committed_offset.restype = ctypes.c_longlong
    lib.swb_committed_offset.argtypes = [ctypes.c_void_p, c, c, ctypes.c_int]
    lib.swb_trim_older_than.restype = ctypes.c_longlong
    lib.swb_trim_older_than.argtypes = [ctypes.c_void_p, c, ctypes.c_double]
    lib.swb_flush.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeBroker(Broker):
    """Durable partitioned-log broker backed by the C++ engine."""

    def __init__(self, log_dir: Optional[str] = None,
                 sync_interval_ms: int = 5) -> None:
        self._lib = _load()
        if log_dir is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="swarmbroker_")
            log_dir = self._tmp.name
        else:
            self._tmp = None
            os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self._h = self._lib.swb_open2(log_dir.encode(), sync_interval_ms)
        if not self._h:
            raise BrokerError(f"swb_open failed for {log_dir}")
        self._fetch_cap = 1 << 18
        self._fetch_bufs = threading.local()  # reused per thread, no memset
        self._closed = False

    # -- admin ---------------------------------------------------------------

    def create_topic(self, name: str, num_partitions: int,
                     retention_ms: int = 7 * 24 * 3600 * 1000) -> bool:
        r = self._lib.swb_create_topic(
            self._h, name.encode(), num_partitions, retention_ms
        )
        if r < 0:
            raise BrokerError(f"create_topic({name}) failed")
        return r == 1

    def list_topics(self) -> Dict[str, TopicMeta]:
        p = self._lib.swb_list_topics_json(self._h)
        try:
            raw = ctypes.cast(p, ctypes.c_char_p).value or b"{}"
        finally:
            self._lib.swb_free_buf(p)
        return {
            name: TopicMeta(name, nparts, ret)
            for name, (nparts, ret) in json.loads(raw.decode()).items()
        }

    def create_partitions(self, name: str, new_total: int) -> None:
        if self._lib.swb_create_partitions(self._h, name.encode(), new_total) < 0:
            raise UnknownTopicError(name)

    # -- data plane ----------------------------------------------------------

    def append(self, topic: str, partition: int, value: bytes,
               key: Optional[bytes] = None,
               timestamp: Optional[float] = None) -> int:
        import time as _t

        off = self._lib.swb_append(
            self._h, topic.encode(), partition,
            key, -1 if key is None else len(key),
            value, len(value),
            timestamp if timestamp is not None else _t.time(),
        )
        if off < 0:
            raise UnknownTopicError(f"{topic}[{partition}]")
        return int(off)

    def _fetch_buf(self) -> "np.ndarray":
        """Per-thread reusable buffer (np.empty: no zero-fill, unlike a
        fresh ctypes array — review finding: ~1 MB memset per message)."""
        buf = getattr(self._fetch_bufs, "buf", None)
        if buf is None or buf.nbytes < self._fetch_cap:
            buf = np.empty(self._fetch_cap, np.uint8)
            self._fetch_bufs.buf = buf
        return buf

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int = 256) -> List[Record]:
        while True:
            buf = self._fetch_buf()
            count = ctypes.c_int(0)
            n = self._lib.swb_fetch(
                self._h, topic.encode(), partition, offset, max_records,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                buf.nbytes, ctypes.byref(count),
            )
            if n == -1:
                raise UnknownTopicError(f"{topic}[{partition}]")
            if n < -1:  # first record needs -n bytes
                self._fetch_cap = max(self._fetch_cap * 2, int(-n))
                continue
            break
        out: List[Record] = []
        raw = buf[: int(n)].tobytes()
        pos = 0
        for _ in range(count.value):
            off, ts, klen, vlen = _REC_HDR.unpack_from(raw, pos)
            pos += _REC_HDR.size
            key = None
            if klen >= 0:
                key = raw[pos: pos + klen]
                pos += klen
            value = raw[pos: pos + vlen]
            pos += vlen
            out.append(Record(topic, partition, off, key, value, ts))
        return out

    def end_offset(self, topic: str, partition: int) -> int:
        off = self._lib.swb_end_offset(self._h, topic.encode(), partition)
        if off < 0:
            raise UnknownTopicError(f"{topic}[{partition}]")
        return int(off)

    def begin_offset(self, topic: str, partition: int) -> int:
        off = self._lib.swb_begin_offset(self._h, topic.encode(), partition)
        if off < 0:
            raise UnknownTopicError(f"{topic}[{partition}]")
        return int(off)

    def wait_for_data(self, topic: str, partition: int, offset: int,
                      timeout_s: float) -> bool:
        return self._lib.swb_wait_for_data(
            self._h, topic.encode(), partition, offset, timeout_s
        ) == 1

    # -- consumer-group offsets ---------------------------------------------

    def commit_offset(self, group: str, topic: str, partition: int,
                      offset: int) -> None:
        self._lib.swb_commit_offset(
            self._h, group.encode(), topic.encode(), partition, offset
        )

    def committed_offset(self, group: str, topic: str,
                         partition: int) -> Optional[int]:
        off = self._lib.swb_committed_offset(
            self._h, group.encode(), topic.encode(), partition
        )
        return None if off < 0 else int(off)

    # -- retention / durability ---------------------------------------------

    def _check_open(self) -> None:
        if self._closed or self._h is None:
            raise BrokerError("broker is closed")

    def durable_offset(self, topic: str, partition: int) -> int:
        self._check_open()
        off = self._lib.swb_durable_offset(self._h, topic.encode(), partition)
        if off == -2:
            # poisoned by a failed fsync: records can never become durable
            raise BrokerError(
                f"{topic}[{partition}]: partition poisoned by fsync failure"
            )
        if off < 0:
            raise UnknownTopicError(f"{topic}[{partition}]")
        return int(off)

    def wait_durable(self, topic: str, partition: int, offset: int,
                     timeout_s: float) -> bool:
        self._check_open()
        return self._lib.swb_wait_durable(
            self._h, topic.encode(), partition, offset, timeout_s
        ) == 1

    def trim_older_than(self, topic: str, cutoff_ts: float) -> int:
        n = self._lib.swb_trim_older_than(self._h, topic.encode(), cutoff_ts)
        if n < 0:
            raise UnknownTopicError(topic)
        return int(n)

    def flush(self) -> None:
        self._lib.swb_flush(self._h)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._lib.swb_flush(self._h)
        self._lib.swb_shutdown(self._h)
        self._h = None
        if self._tmp is not None:
            self._tmp.cleanup()
