"""Broker transport interface (the L1 layer).

This is the contract the reference consumes from confluent_kafka/librdkafka
(produce/poll/flush at ` main.py:476-484,1386`; subscribe/poll/close at
`:344,557,367`; list_topics/create_topics/create_partitions at
`:241,277,1349`) re-expressed as an in-tree interface with two
implementations:

- ``broker.local.LocalBroker`` — pure-Python, thread-safe, in-memory with
  optional JSON durability; used for tests and single-process serving.
- ``broker.native.NativeBroker`` — C++ engine (mmap append-only segment log,
  per-partition rings) loaded via ctypes; the production path.

Key semantic choices (deliberate departures from the reference, per SURVEY):

- Partition affinity is REAL: consumers subscribe to specific partitions and
  unicast messages are produced to the receiver's partition, so receive is
  O(own messages). The reference's consumers re-read the whole topic and
  filter client-side (defect D8, ` main.py:334-345,579-585`).
- Broadcast is a fan-out WRITE (one record per partition) instead of a
  fan-out READ, preserving single-partition consumption.
- The partitioner is stable FNV-1a (fixes defect D6).
"""

from __future__ import annotations

import abc
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple
from ..utils.sync import make_lock


@dataclass(frozen=True)
class Record:
    """One entry in a partition log (librdkafka ``Message`` equivalent)."""

    topic: str
    partition: int
    offset: int
    key: Optional[bytes]
    value: bytes
    timestamp: float


@dataclass
class TopicMeta:
    name: str
    num_partitions: int
    retention_ms: int


DeliveryCallback = Callable[[Optional[str], Record], None]
# signature mirrors rdkafka's (err, msg) delivery report (` main.py:374-391`):
# err is None on success, else a human-readable error string.


class BrokerError(Exception):
    #: True when retrying the same operation (possibly against a newly
    #: resolved leader) is safe and likely to succeed. Callers that queue
    #: work (the runtime's send path) use this to distinguish "try again"
    #: from "give up".
    retryable = False


class UnknownTopicError(BrokerError):
    pass


class FencedError(BrokerError):
    """A deposed leader tried to write with a stale fencing epoch.

    Raised by :class:`~swarmdb_tpu.broker.replica.ReplicatedBroker` once a
    follower (or the cluster map) reports a higher epoch than this
    leader's: its appends and mirror connections are refused so a
    partitioned old leader coming back can never fork the replicated log.
    NOT retryable — the process must rejoin as a follower (see the HA
    runbook in the README).

    Partition-scoped since ISSUE 10: under partition-level leadership a
    node is fenced per ``(topic, partition)`` lease, not per process —
    ``topic``/``partition``/``epoch`` carry which lease was lost and at
    what fencing epoch, while the node's OTHER leaderships keep writing.
    Node-level fencing leaves them ``None``."""

    retryable = False

    def __init__(self, *args, topic: "Optional[str]" = None,
                 partition: "Optional[int]" = None,
                 epoch: "Optional[int]" = None) -> None:
        super().__init__(*args)
        self.topic = topic
        self.partition = partition
        self.epoch = epoch


class LeaderChangedError(BrokerError):
    """The cluster leader moved (failover in progress or completed).

    Raised by :class:`~swarmdb_tpu.ha.client.ClusterBroker` when the node
    it was bound to died or was deposed. Retryable: the next attempt
    re-resolves the leader from the cluster map."""

    retryable = True


class Broker(abc.ABC):
    """Storage + admin plane. One per process (or one native engine)."""

    # -- admin (AdminClient equivalent: ` main.py:241,277,1349`) -------------

    @abc.abstractmethod
    def create_topic(
        self, name: str, num_partitions: int, retention_ms: int = 7 * 24 * 3600 * 1000
    ) -> bool:
        """Create a topic; returns False if it already existed."""

    @abc.abstractmethod
    def list_topics(self) -> Dict[str, TopicMeta]: ...

    @abc.abstractmethod
    def create_partitions(self, name: str, new_total: int) -> None:
        """Grow (never shrink) a topic's partition count
        (reference `auto_scale_partitions`, ` main.py:1327-1365`)."""

    # -- data plane ----------------------------------------------------------

    @abc.abstractmethod
    def append(
        self,
        topic: str,
        partition: int,
        value: bytes,
        key: Optional[bytes] = None,
        timestamp: Optional[float] = None,
    ) -> int:
        """Append one record; returns its offset."""

    @abc.abstractmethod
    def fetch(
        self, topic: str, partition: int, offset: int, max_records: int = 256
    ) -> List[Record]:
        """Read records at >= offset. Non-blocking; empty list if none."""

    @abc.abstractmethod
    def end_offset(self, topic: str, partition: int) -> int:
        """Offset one past the last record (== next offset to be assigned)."""

    @abc.abstractmethod
    def begin_offset(self, topic: str, partition: int) -> int:
        """Earliest retained offset (>0 after retention trims)."""

    @abc.abstractmethod
    def wait_for_data(
        self, topic: str, partition: int, offset: int, timeout_s: float
    ) -> bool:
        """Block until a record at >= offset exists or timeout. True if data."""

    # -- consumer-group offsets ---------------------------------------------

    @abc.abstractmethod
    def commit_offset(self, group: str, topic: str, partition: int, offset: int) -> None: ...

    @abc.abstractmethod
    def committed_offset(self, group: str, topic: str, partition: int) -> Optional[int]: ...

    # -- retention / durability ---------------------------------------------

    @abc.abstractmethod
    def trim_older_than(self, topic: str, cutoff_ts: float) -> int:
        """Drop records older than cutoff; returns number dropped."""

    def durable_offset(self, topic: str, partition: int) -> int:
        """Offsets below this are crash-durable. The default (== end_offset)
        is correct for brokers whose append IS the durability point (the
        in-memory LocalBroker); the native broker reports its group-commit
        fsync watermark instead."""
        return self.end_offset(topic, partition)

    def wait_durable(self, topic: str, partition: int, offset: int,
                     timeout_s: float) -> bool:
        """Block until the record at ``offset`` is durable (or timeout)."""
        return self.durable_offset(topic, partition) > offset

    def flush(self) -> None:
        """Force durability (fsync segment logs). No-op for in-memory."""

    def close(self) -> None:
        pass

    # -- health --------------------------------------------------------------

    def healthy(self) -> bool:
        """Liveness probe used by GET /health (reference `api.py:794-800`)."""
        try:
            self.list_topics()
            return True
        except Exception:
            return False


class Producer:
    """Client-side producer with acks=all delivery reports.

    Mirrors the confluent Producer surface the reference uses
    (` main.py:476-484`): ``produce(topic, value, key, partition,
    on_delivery)`` + ``poll`` + ``flush``. Callbacks are queued at produce
    time and fired from ``poll``/``flush`` — but ONLY once the record's
    offset clears the broker's durability watermark
    (``Broker.durable_offset``), matching the reference's ``acks=all``
    contract (` main.py:196-197`): a delivery report implies the record
    survives a broker crash. For the in-memory LocalBroker the watermark is
    the end offset, so callbacks fire on the next poll; for the native
    broker they fire after its group-commit fsync (~sync_interval_ms).
    """

    def __init__(self, broker: Broker) -> None:
        self._broker = broker
        self._pending: List[Tuple[DeliveryCallback, Optional[str], Record]] = []
        # swarmlint: guarded-by[self._pending_lock]: _pending
        self._pending_lock = make_lock("broker.base.Producer._pending_lock")
        # serializes whole poll() invocations: two concurrent pollers (the
        # runtime's delivery-poll thread + send_message's inline poll) could
        # otherwise swap out separate batches and fire per-partition
        # callbacks out of order
        self._poll_lock = make_lock("broker.base.Producer._poll_lock")

    def produce(
        self,
        topic: str,
        value: bytes,
        key: Optional[bytes] = None,
        partition: Optional[int] = None,
        on_delivery: Optional[DeliveryCallback] = None,
    ) -> Record:
        if partition is None:
            from ..utils.hashing import stable_partition

            meta = self._broker.list_topics().get(topic)
            if meta is None:
                raise UnknownTopicError(topic)
            partition = stable_partition(
                (key or value).decode("utf-8", "replace"), meta.num_partitions
            )
        # Local errors raise synchronously (rdkafka contract); the delivery
        # callback reports the committed (topic, partition, offset).
        ts = time.time()
        offset = self._broker.append(topic, partition, value, key=key, timestamp=ts)
        record = Record(topic, partition, offset, key, value, ts)
        if on_delivery is not None:
            with self._pending_lock:
                self._pending.append((on_delivery, None, record))
        return record

    def poll(self, timeout: float = 0.0) -> int:
        """Fire delivery callbacks for durably-committed records.

        Returns how many fired. Records not yet past the durability
        watermark stay queued for a later poll (or ``flush``). A positive
        ``timeout`` blocks up to that long for the oldest pending record to
        become durable.
        """
        if timeout > 0:
            # blocking wait happens OUTSIDE _poll_lock: the background
            # delivery poller parks here for its whole timeout, and holding
            # the lock through it would stall every send_message's inline
            # poll(0) behind the wait
            with self._pending_lock:
                oldest = self._pending[0][2] if self._pending else None
            if oldest is not None:
                self._broker.wait_durable(
                    oldest.topic, oldest.partition, oldest.offset, timeout
                )
        with self._poll_lock:
            with self._pending_lock:
                batch, self._pending = self._pending, []
            if not batch:
                return 0
            fired = 0
            requeue: List[Tuple[DeliveryCallback, Optional[str], Record]] = []
            watermarks: Dict[Tuple[str, int], int] = {}
            part_errors: Dict[Tuple[str, int], str] = {}
            for cb, err, rec in batch:
                tp = (rec.topic, rec.partition)
                if tp not in watermarks and tp not in part_errors:
                    try:
                        watermarks[tp] = self._broker.durable_offset(*tp)
                    except BrokerError as exc:
                        # topic gone or partition poisoned (failed fsync):
                        # durability can never be confirmed — report the
                        # ERROR, never a false DELIVERED
                        part_errors[tp] = str(exc)
                if tp in part_errors and err is None:
                    err = part_errors[tp]
                if err is not None or rec.offset < watermarks[tp]:
                    cb(err, rec)
                    fired += 1
                else:
                    requeue.append((cb, err, rec))
            if requeue:
                with self._pending_lock:
                    # prepend to preserve per-partition callback order
                    self._pending = requeue + self._pending
            return fired

    def flush(self, timeout: float = -1.0) -> int:
        """Force durability, then fire every pending callback."""
        self._broker.flush()
        self.poll(0)
        with self._pending_lock:
            remaining = len(self._pending)
        return remaining

    @property
    def pending_count(self) -> int:
        """Delivery callbacks queued but not yet past the durability gate."""
        with self._pending_lock:
            return len(self._pending)


@dataclass
class _PartitionCursor:
    topic: str
    partition: int
    next_offset: int
    buffer: "deque" = field(default_factory=lambda: deque())


class Consumer:
    """Partition-affine consumer with committed offsets.

    Unlike the reference's consumers (whole-topic subscribe + client-side
    filter, defect D8), a Consumer subscribes to explicit ``(topic,
    partition)`` pairs — normally exactly the one partition its agent hashes
    to — and round-robins across them.
    """

    # prefetch granularity and auto-commit cadence (rdkafka-style periodic
    # commits: at-least-once, bounded redelivery window after a crash)
    FETCH_BATCH = 64
    COMMIT_EVERY_RECORDS = 64
    COMMIT_EVERY_S = 1.0

    def __init__(
        self,
        broker: Broker,
        group_id: str,
        auto_offset_reset: str = "earliest",
        auto_commit: bool = True,
    ) -> None:
        self._broker = broker
        self.group_id = group_id
        self._auto_offset_reset = auto_offset_reset
        self._auto_commit = auto_commit
        self._cursors: List[_PartitionCursor] = []
        self._rr = 0  # round-robin index
        self._closed = False
        self._uncommitted = 0
        self._last_commit = time.time()

    def assign(self, assignments: Sequence[Tuple[str, int]]) -> None:
        """Subscribe to explicit (topic, partition) pairs."""
        self._cursors = []
        for topic, part in assignments:
            committed = self._broker.committed_offset(self.group_id, topic, part)
            if committed is not None:
                start = committed
            elif self._auto_offset_reset == "latest":
                start = self._broker.end_offset(topic, part)
            else:  # earliest
                start = self._broker.begin_offset(topic, part)
            self._cursors.append(_PartitionCursor(topic, part, start))

    def add_assignment(
        self, topic: str, partition: int, start_offset: Optional[int] = None
    ) -> bool:
        """Incrementally add one partition, KEEPING existing assignments.

        Used on partition-count growth (`SwarmDB.auto_scale_partitions`): the
        old partition stays assigned so its undelivered backlog drains, and
        the newly-mapped partition starts at committed-offset-if-any, else
        ``start_offset`` (the caller's pre-growth end snapshot), else its
        CURRENT END — never earliest — so historical records there (e.g.
        broadcast fan-out copies this group already consumed via its old
        partition) are not replayed. Returns False if already assigned.
        """
        for cur in self._cursors:
            if (cur.topic, cur.partition) == (topic, partition):
                return False
        committed = self._broker.committed_offset(self.group_id, topic, partition)
        if committed is not None:
            start = committed
        elif start_offset is not None:
            start = start_offset
        else:
            start = self._broker.end_offset(topic, partition)
        self._cursors.append(_PartitionCursor(topic, partition, start))
        return True

    def subscribe_topic(self, topic: str) -> None:
        """Whole-topic subscription (all partitions) — reference-compatible
        mode used by admin/replay tooling, not the per-agent hot path."""
        meta = self._broker.list_topics().get(topic)
        if meta is None:
            raise UnknownTopicError(topic)
        self.assign([(topic, p) for p in range(meta.num_partitions)])

    def _take(self, cur: _PartitionCursor) -> Record:
        rec = cur.buffer.popleft()
        cur.next_offset = rec.offset + 1
        if self._auto_commit:
            # periodic commit, not per record: a commit is a durable-log
            # append broker-side, so per-record committing puts one file
            # write on every consumed message
            self._uncommitted += 1
            now = time.time()
            if (self._uncommitted >= self.COMMIT_EVERY_RECORDS
                    or now - self._last_commit >= self.COMMIT_EVERY_S):
                self.commit()
        return rec

    def poll(self, timeout: float = 0.0) -> Optional[Record]:
        """Next record from any assigned partition, or None on timeout.

        Records are prefetched in batches of ``FETCH_BATCH`` per broker
        call; offsets auto-commit periodically (see _take).
        """
        if self._closed or not self._cursors:
            return None
        deadline = time.time() + max(0.0, timeout)
        while True:
            for _ in range(len(self._cursors)):
                cur = self._cursors[self._rr % len(self._cursors)]
                self._rr += 1
                if cur.buffer:
                    return self._take(cur)
                # Retention may have trimmed past our cursor — skip forward.
                begin = self._broker.begin_offset(cur.topic, cur.partition)
                if cur.next_offset < begin:
                    cur.next_offset = begin
                recs = self._broker.fetch(
                    cur.topic, cur.partition, cur.next_offset, self.FETCH_BATCH
                )
                if recs:
                    cur.buffer.extend(recs)
                    return self._take(cur)
            remaining = deadline - time.time()
            if remaining <= 0:
                return None
            # Block on the first cursor's partition for the remainder; any
            # new data there wakes us, otherwise we re-scan on timeout.
            cur = self._cursors[self._rr % len(self._cursors)]
            self._broker.wait_for_data(
                cur.topic, cur.partition, cur.next_offset, min(remaining, 0.05)
            )

    def commit(self) -> None:
        for cur in self._cursors:
            self._broker.commit_offset(
                self.group_id, cur.topic, cur.partition, cur.next_offset
            )
        self._uncommitted = 0
        self._last_commit = time.time()

    def close(self) -> None:
        if not self._closed:
            if self._auto_commit:
                self.commit()
            self._closed = True

    @property
    def assignments(self) -> List[Tuple[str, int]]:
        return [(c.topic, c.partition) for c in self._cursors]
