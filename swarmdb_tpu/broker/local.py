"""Pure-Python in-process broker.

Thread-safe partitioned log with blocking reads, consumer-group offsets,
retention trimming, and optional JSON snapshot durability. Implements the
full :class:`~swarmdb_tpu.broker.base.Broker` contract so everything above
the transport (core runtime, API, TPU backend) runs with no external
cluster — the role Kafka+Zookeeper containers play for the reference
(`dockerfile-compose.yaml:5-48`).

Concurrency model: one ``threading.Condition`` per partition guards a plain
list of records. Appends are O(1); fetches are O(result) via offset
arithmetic (offset - base index). This is the semantics twin of the C++
engine in ``broker/cpp/``; tests run against both through the same suite.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .base import Broker, BrokerError, Record, TopicMeta, UnknownTopicError
from ..utils.sync import make_condition, make_lock


class _Partition:
    __slots__ = ("cond", "records", "base_offset")

    def __init__(self) -> None:
        self.cond = make_condition("broker.local._Partition.cond")
        self.records: List[Record] = []
        self.base_offset = 0  # offset of records[0]; grows as retention trims

    def end_offset(self) -> int:
        return self.base_offset + len(self.records)


class LocalBroker(Broker):
    # floor between durability-driven snapshots (wait_durable): one snapshot
    # covers every record pending at that moment (group commit), so this
    # bounds snapshot I/O at ~5/s regardless of message rate instead of
    # letting the 5ms delivery poller rewrite full state per cycle.
    SNAPSHOT_MIN_INTERVAL_S = 0.2

    def __init__(self, snapshot_path: Optional[str] = None) -> None:
        self._topics: Dict[str, TopicMeta] = {}
        self._parts: Dict[Tuple[str, int], _Partition] = {}
        self._offsets: Dict[Tuple[str, str, int], int] = {}  # (group, topic, part)
        self._meta_lock = make_lock("broker.local.LocalBroker._meta_lock")
        self._snapshot_path = snapshot_path
        # durability watermark per (topic, partition): end offsets captured by
        # the last snapshot. Only meaningful in snapshot mode — pure in-memory
        # operation has no crash durability, so append IS its durability point
        # and durable_offset == end_offset (see Broker.durable_offset).
        self._snap_ends: Dict[Tuple[str, int], int] = {}
        self._last_snapshot = 0.0
        # serializes snapshot writes: concurrent flush() callers (delivery
        # poller + explicit flush) share one fixed tmp path
        self._snap_lock = make_lock("broker.local.LocalBroker._snap_lock")
        if snapshot_path and os.path.exists(snapshot_path):
            self._restore(snapshot_path)

    # -- admin ---------------------------------------------------------------

    def create_topic(
        self, name: str, num_partitions: int, retention_ms: int = 7 * 24 * 3600 * 1000
    ) -> bool:
        with self._meta_lock:
            if name in self._topics:
                return False
            self._topics[name] = TopicMeta(name, num_partitions, retention_ms)
            for p in range(num_partitions):
                self._parts[(name, p)] = _Partition()
            return True

    def list_topics(self) -> Dict[str, TopicMeta]:
        with self._meta_lock:
            return dict(self._topics)

    def create_partitions(self, name: str, new_total: int) -> None:
        with self._meta_lock:
            meta = self._topics.get(name)
            if meta is None:
                raise UnknownTopicError(name)
            if new_total <= meta.num_partitions:
                return  # grow-only, like Kafka create_partitions
            for p in range(meta.num_partitions, new_total):
                self._parts[(name, p)] = _Partition()
            meta.num_partitions = new_total

    # -- data plane ----------------------------------------------------------

    def _part(self, topic: str, partition: int) -> _Partition:
        # under _meta_lock (swarmlint SWL303): an unguarded lookup racing
        # create_topic could observe the topic registered but its
        # partitions not yet built and mis-report "partition out of
        # range" for a topic that is coming up fine
        with self._meta_lock:
            part = self._parts.get((topic, partition))
            in_topics = topic in self._topics
        if part is None:
            if not in_topics:
                raise UnknownTopicError(topic)
            raise BrokerError(f"partition {partition} out of range for topic {topic!r}")
        return part

    def append(
        self,
        topic: str,
        partition: int,
        value: bytes,
        key: Optional[bytes] = None,
        timestamp: Optional[float] = None,
    ) -> int:
        part = self._part(topic, partition)
        ts = timestamp if timestamp is not None else time.time()
        with part.cond:
            offset = part.end_offset()
            part.records.append(Record(topic, partition, offset, key, value, ts))
            part.cond.notify_all()
            return offset

    def fetch(
        self, topic: str, partition: int, offset: int, max_records: int = 256
    ) -> List[Record]:
        part = self._part(topic, partition)
        with part.cond:
            start = max(offset, part.base_offset) - part.base_offset
            if start >= len(part.records):
                return []
            return list(part.records[start : start + max_records])

    def end_offset(self, topic: str, partition: int) -> int:
        part = self._part(topic, partition)
        with part.cond:
            return part.end_offset()

    def begin_offset(self, topic: str, partition: int) -> int:
        part = self._part(topic, partition)
        with part.cond:
            return part.base_offset

    def wait_for_data(
        self, topic: str, partition: int, offset: int, timeout_s: float
    ) -> bool:
        part = self._part(topic, partition)
        deadline = time.time() + timeout_s
        with part.cond:
            # predicate re-checked in a while loop (swarmlint SWL304):
            # the single-wait shape returned early on any spurious
            # wakeup or a notify for an already-consumed append,
            # degrading the long-poll into a busy poll
            while part.end_offset() <= offset:
                left = deadline - time.time()
                if left <= 0:
                    return False
                part.cond.wait(left)
            return True

    # -- consumer-group offsets ---------------------------------------------

    def commit_offset(self, group: str, topic: str, partition: int, offset: int) -> None:
        with self._meta_lock:
            self._offsets[(group, topic, partition)] = offset

    def committed_offset(self, group: str, topic: str, partition: int) -> Optional[int]:
        with self._meta_lock:
            return self._offsets.get((group, topic, partition))

    # -- retention -----------------------------------------------------------

    def trim_older_than(self, topic: str, cutoff_ts: float) -> int:
        meta = self.list_topics().get(topic)
        if meta is None:
            raise UnknownTopicError(topic)
        dropped = 0
        for p in range(meta.num_partitions):
            part = self._part(topic, p)
            with part.cond:
                i = 0
                while i < len(part.records) and part.records[i].timestamp < cutoff_ts:
                    i += 1
                if i:
                    part.records = part.records[i:]
                    part.base_offset += i
                    dropped += i
        return dropped

    def enforce_retention(self) -> int:
        """Trim every topic per its retention_ms (broker-side GC sweep)."""
        now = time.time()
        total = 0
        for meta in self.list_topics().values():
            total += self.trim_older_than(meta.name, now - meta.retention_ms / 1000.0)
        return total

    # -- durability ----------------------------------------------------------

    def flush(self) -> None:
        if self._snapshot_path:
            self.save_snapshot(self._snapshot_path)

    def durable_offset(self, topic: str, partition: int) -> int:
        """In snapshot mode the durability point is the last snapshot, not
        append — delivery reports (acks=all) must not outrun it."""
        if not self._snapshot_path:
            return self.end_offset(topic, partition)
        self._part(topic, partition)  # raises on unknown topic/partition
        with self._meta_lock:
            return self._snap_ends.get((topic, partition), 0)

    def wait_durable(self, topic: str, partition: int, offset: int,
                     timeout_s: float) -> bool:
        if not self._snapshot_path:
            return self.end_offset(topic, partition) > offset
        if self.durable_offset(topic, partition) > offset:
            return True
        # group commit, degenerate form: snapshot now (covers every pending
        # record at once) — rate-limited so a tight delivery-poll loop can't
        # turn the send path into O(full state) disk writes per cycle; an
        # explicit Producer.flush() -> Broker.flush() still snapshots
        # unconditionally. Honor timeout_s: wait out the rate-limit window
        # (or as much of it as the timeout allows) instead of returning
        # immediately and inviting a caller busy-spin.
        hold = self.SNAPSHOT_MIN_INTERVAL_S - (time.time() - self._last_snapshot)
        if hold > 0:
            time.sleep(min(hold, timeout_s))
        if time.time() - self._last_snapshot >= self.SNAPSHOT_MIN_INTERVAL_S:
            self.flush()
        return self.durable_offset(topic, partition) > offset

    def save_snapshot(self, path: str) -> None:
        """Full-state JSON snapshot (reference persistence shape analog,
        ` main.py:852-892`, applied at the broker layer)."""
        with self._snap_lock:
            self._save_snapshot_locked(path)

    def _save_snapshot_locked(self, path: str) -> None:
        with self._meta_lock:
            topics = {
                n: {"num_partitions": m.num_partitions, "retention_ms": m.retention_ms}
                for n, m in self._topics.items()
            }
            # JSON-array keys: group/topic names may contain any separator
            # character, so positional encoding is the only safe flattening.
            offsets = [[g, t, p, v] for (g, t, p), v in self._offsets.items()]
            parts = dict(self._parts)
        state = {
            "topics": topics,
            "partitions": [],
            "offsets": offsets,
            "timestamp": time.time(),
        }
        ends: Dict[Tuple[str, int], int] = {}
        for (topic, p), part in parts.items():
            with part.cond:
                ends[(topic, p)] = part.end_offset()
                state["partitions"].append({
                    "topic": topic,
                    "partition": p,
                    "base_offset": part.base_offset,
                    # base64: record keys/values are arbitrary bytes; a utf-8
                    # round-trip would corrupt binary payloads.
                    "records": [
                        {
                            "offset": r.offset,
                            "key": base64.b64encode(r.key).decode() if r.key else None,
                            "value": base64.b64encode(r.value).decode(),
                            "timestamp": r.timestamp,
                        }
                        for r in part.records
                    ],
                })
        tmp = path + ".tmp"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, path)
        with self._meta_lock:
            self._snap_ends.update(ends)
            self._last_snapshot = time.time()

    def _restore(self, path: str) -> None:
        with open(path) as f:
            state = json.load(f)
        for name, m in state.get("topics", {}).items():
            self.create_topic(name, m["num_partitions"], m["retention_ms"])
        for pdata in state.get("partitions", []):
            topic, pnum = pdata["topic"], pdata["partition"]
            part = self._part(topic, pnum)
            part.base_offset = pdata["base_offset"]
            part.records = [
                Record(
                    topic,
                    pnum,
                    r["offset"],
                    base64.b64decode(r["key"]) if r["key"] else None,
                    base64.b64decode(r["value"]),
                    r["timestamp"],
                )
                for r in pdata["records"]
            ]
        with self._meta_lock:
            for group, topic, pnum, off in state.get("offsets", []):
                self._offsets[(group, topic, pnum)] = off
            for (topic, p), part in self._parts.items():
                self._snap_ends[(topic, p)] = part.end_offset()
